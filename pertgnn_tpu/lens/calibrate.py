"""Calibration math for multi-quantile serving — tiny and testable.

A quantile head is only worth serving if its columns MEAN what their
levels claim: the tau-column's prediction should exceed the true label
about tau of the time. ``coverage_per_tau`` measures exactly that
(empirical coverage over a labeled split), and
``benchmarks/lens_bench.py`` exit-code-gates it against a
pre-registered budget so a head whose calibration drifts turns the
bench red instead of shipping quietly. ``monotone_violations`` is the
serving-side check of the non-crossing guarantee (which holds by
construction — models/pert_model.py cumulative-softplus head — but the
bench asserts it on every SERVED vector, proving the property survived
packing, quantization tiers, and the transport round-trip).
"""

from __future__ import annotations

import numpy as np


def coverage_per_tau(y_true: np.ndarray,
                     preds: np.ndarray) -> np.ndarray:
    """Empirical coverage of each quantile column: fraction of rows
    whose predicted quantile is >= the true label. A calibrated
    tau-column covers ~tau. ``preds`` is (rows, T); returns (T,)."""
    y = np.asarray(y_true, np.float64)
    p = np.asarray(preds, np.float64)
    if p.ndim == 1:
        p = p[:, None]
    if len(y) != len(p):
        raise ValueError(f"{len(y)} labels vs {len(p)} prediction rows")
    if len(y) == 0:
        raise ValueError("coverage needs at least one labeled row")
    return (p >= y[:, None]).mean(axis=0)


def calibration_errors(y_true: np.ndarray, preds: np.ndarray,
                       taus) -> np.ndarray:
    """|coverage - tau| per column — what the lens_bench gate compares
    against its pre-registered budget."""
    taus = np.asarray(list(taus), np.float64)
    cov = coverage_per_tau(y_true, preds)
    if len(cov) != len(taus):
        raise ValueError(f"{len(cov)} prediction columns vs "
                         f"{len(taus)} taus")
    return np.abs(cov - taus)


def monotone_violations(preds: np.ndarray, atol: float = 0.0) -> int:
    """Rows whose quantile vector DECREASES anywhere along the tau axis
    (beyond ``atol``). 0 for every vector the non-crossing head can
    produce; the bench asserts 0 on every served prediction."""
    p = np.asarray(preds, np.float64)
    if p.ndim == 1 or p.shape[1] < 2:
        return 0
    return int((np.diff(p, axis=1) < -atol).any(axis=1).sum())
