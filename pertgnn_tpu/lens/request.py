"""The lens request/result vocabulary riding the serving request path.

``LensRequest`` is the per-request variant spec a caller attaches to
``submit(lens=...)`` at either front door (serve/queue.MicrobatchQueue,
fleet/router.FleetRouter); ``LensResult`` is what the Future resolves to
when the request asked for more than a scalar. Both have wire codecs
because the fleet transport carries them as JSON next to the SLO/trace
fields (fleet/transport.py) — ``to_wire`` returns None for an
all-default request so plain traffic pays zero extra wire bytes, the
same omit-when-default rule the slo/dg/trace fields follow.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class LensRequest:
    """One request's lens variant flags (all-default = a plain request).

    ``attribute_k`` > 0 asks for root-cause attribution: the top-k
    per-node local predictions of this request's mixture, mapped to
    (ms, interface) calls (lens/attribute.py); requires the engine's
    local-pred rung programs (``LensConfig.lens_local``), else the
    submit is refused with the typed LensDisabled. ``edits`` is a tuple
    of counterfactual edit ops applied to the request's call graph
    before packing (lens/whatif.py documents the op vocabulary and the
    refusal cases)."""

    attribute_k: int = 0
    edits: tuple = ()

    @property
    def wants_local(self) -> bool:
        return self.attribute_k > 0

    @property
    def is_default(self) -> bool:
        return self.attribute_k <= 0 and not self.edits

    def to_wire(self) -> dict | None:
        """JSON-able transport form; None when default (omitted from the
        POST body entirely)."""
        if self.is_default:
            return None
        out: dict = {}
        if self.attribute_k > 0:
            out["k"] = int(self.attribute_k)
        if self.edits:
            out["edits"] = [dict(e) for e in self.edits]
        return out

    @classmethod
    def from_wire(cls, d: dict | None) -> "LensRequest | None":
        if not isinstance(d, dict):
            return None
        return cls(attribute_k=int(d.get("k", 0)),
                   edits=tuple(dict(e) for e in d.get("edits", ())))


@dataclasses.dataclass(frozen=True)
class LensResult:
    """What a lens request's Future resolves to.

    ``pred`` keeps the plain-request contract (a float in single-tau
    mode, a (T,)-float32 vector under a multi-quantile head — monotone
    by construction). ``attribution`` is a tuple of JSON-able row dicts
    in descending local-pred order (lens/attribute.py: node / ms_id /
    iface / local, plus ms / interface names when the engine was built
    with the arena vocabularies); empty when the request did not ask
    for attribution."""

    pred: object
    attribution: tuple = ()
