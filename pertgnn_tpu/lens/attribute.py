"""Root-cause attribution: per-node local predictions -> named calls.

The model's local head scores every node (service stage) of a request's
mixture with its own latency prediction — trained against the trace
label when ``ModelConfig.local_loss_weight`` > 0 (the reference never
trains it, so attribution from a zero-weight head is noise —
docs/GUIDE.md §13). Serving routes that vector out of the step program
with pad rows pinned to -inf IN-GRAPH (serve/engine.py ``step_local``;
graftaudit's padding-taint pass proves the pin on the traced program),
so by the time this module ranks nodes a padded row is unrankable by
construction: every value it sees belongs to a real node.

``top_k_rows`` ranks one request's real-node local predictions and maps
each winner back through the arena representation to the call it names:
the node's microservice id and the interface of its INCOMING edge (the
call that produced this stage; roots have none). ``name_rows``
translates the ids to strings through the preprocess vocabularies
(ingest/preprocess.PreprocessResult ``ms_vocab`` /
``interface_vocab``) when the caller has them.
"""

from __future__ import annotations

import numpy as np

from pertgnn_tpu.batching.mixture import Mixture


def top_k_rows(local_vals: np.ndarray, mixture: Mixture, k: int,
               ms_names=None, iface_names=None) -> list[dict]:
    """Top-k attribution rows for ONE request, descending local-pred
    order (ties broken by node index for determinism — a hedged
    re-dispatch must produce the identical row list).

    ``local_vals`` is the request's real-node slice of the engine's
    local output, aligned with the mixture's node order (pack_single
    lays a request's nodes out contiguously in mixture order). Rows are
    JSON-able: node / ms_id / iface (None for a root) / local; id->name
    translation is ``name_rows``' job (the ONE naming point), applied
    here when the vocabularies are provided."""
    local_vals = np.asarray(local_vals, np.float32)
    if len(local_vals) != mixture.num_nodes:
        raise ValueError(
            f"attribution got {len(local_vals)} local values for a "
            f"{mixture.num_nodes}-node mixture — the pad mask leaked")
    if not np.isfinite(local_vals).all():
        # -inf is the PAD pin; a real node carrying it means the mask
        # slipped — refuse rather than silently rank garbage
        raise ValueError(
            "attribution saw non-finite local predictions on real "
            "nodes — the pad pin leaked into real lanes")
    k = max(0, min(int(k), mixture.num_nodes))
    # stable argsort on (-value, index): deterministic under ties
    order = np.lexsort((np.arange(len(local_vals)), -local_vals))[:k]
    rows: list[dict] = []
    recv = mixture.receivers
    for node in order.tolist():
        incoming = np.nonzero(recv == node)[0]
        iface = (int(mixture.edge_iface[incoming[0]])
                 if len(incoming) else None)
        rows.append({"node": int(node),
                     "ms_id": int(mixture.ms_id[node]), "iface": iface,
                     "local": float(local_vals[node])})
    return name_rows(rows, ms_names, iface_names)


def name_rows(rows: list[dict], ms_vocab=None,
              iface_vocab=None) -> list[dict]:
    """Translate id-based attribution rows to named calls through the
    preprocess vocabularies (code -> original string) — THE one naming
    point: ``top_k_rows`` routes through it, and callers holding a
    PreprocessResult can apply it to rows that crossed the fleet wire
    id-only. None vocabularies pass rows through unchanged."""
    if ms_vocab is None and iface_vocab is None:
        return [dict(r) for r in rows]
    out = []
    for r in rows:
        r = dict(r)
        if (ms_vocab is not None
                and 0 <= r.get("ms_id", -1) < len(ms_vocab)):
            r["ms"] = str(ms_vocab[r["ms_id"]])
        iface = r.get("iface")
        if (iface_vocab is not None and iface is not None
                and 0 <= iface < len(iface_vocab)):
            r["interface"] = str(iface_vocab[iface])
        out.append(r)
    return out
