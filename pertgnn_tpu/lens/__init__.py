"""graftlens: distributional, explainable what-if serving.

The model has always computed more than the single scalar serving
exposed: it regresses under a pinball loss (a QUANTILE, not a mean) and
produces a per-node ``local_pred`` next to the trace-level prediction
(models/pert_model.py — the reference computes it and throws it away,
pert_gnn.py:245). This package opens those capabilities as three new
REQUEST VARIANTS through the existing pack/dispatch/hedge/trace
machinery, so the fault invariants (PR 4), trace chains (PR 12), and
graftaudit proofs (PR 10) extend to them mechanically:

- **multi-quantile predictions** — ``ModelConfig.quantile_taus``
  widens the global head to one column per level under a
  cumulative-softplus NON-CROSSING parameterization (monotone by
  construction); served vectors are exit-code-gated on empirical
  calibration (benchmarks/lens_bench.py, lens/calibrate.py);
- **root-cause attribution** — a request flag (``LensRequest.
  attribute_k``) routes the already-computed local head out of the
  step program (pad rows pinned to -inf in-graph so top-k can never
  rank them — graftaudit's padding-taint verifies the pin) and
  lens/attribute.py maps the top-k node predictions back through the
  arena's vocabulary to (ms, interface) calls;
- **counterfactual topology queries** — ``LensRequest.edits`` applies
  pure drop/substitute edits over the Mixture arena representation
  (lens/whatif.py) and re-packs through the existing bucket ladder:
  zero fresh compiles by construction, since rungs key on shape.

Request fields ride ``MicrobatchQueue.submit(lens=...)``,
``FleetRouter.submit(lens=...)``, and the fleet transport body (omitted
when default, like PR 13's SLO classes). docs/GUIDE.md §13 documents
the request types, the calibration gate, and the counterfactual
semantics including every refusal case.
"""

from pertgnn_tpu.lens.attribute import name_rows, top_k_rows  # noqa: F401
from pertgnn_tpu.lens.calibrate import (coverage_per_tau,  # noqa: F401
                                        monotone_violations)
from pertgnn_tpu.lens.request import LensRequest, LensResult  # noqa: F401
from pertgnn_tpu.lens.whatif import apply_whatif  # noqa: F401
