"""Canonical normal form for what-if edit scripts (lens/whatif.py).

Two edit scripts that describe the SAME counterfactual should share one
cache entry in the fleet's prediction memo (fleet/memo.py) — the memo
keys on the request, and ``apply_whatif`` is a pure bit-deterministic
function, so equivalence of scripts is a real, checkable property.
This module computes a normal form such that

    apply_whatif(m, edits) == apply_whatif(m, canonical_edits(edits))

bit-identically for EVERY mixture ``m`` (or both refuse), which
tests/test_memo.py proves against the whatif oracle under hypothesis
permutations.  The transformations are deliberately only the ones
provable WITHOUT the mixture — the router holds no mixtures, so the
normal form must be sound on arrays it never sees:

- **runs of substitutions** (``sub_node`` / ``sub_edge``): writes to
  distinct targets commute; an edit identical to the last write on the
  same (target, field) is a no-op and is dropped; the run is then
  stable-sorted by (op, index) so every order of commuting edits keys
  identically.  Writes to the SAME target keep their relative order
  (last-write-wins is order-sensitive, so the sort key ties and the
  stable sort preserves it).
- **runs of drops** of one kind (``drop_edge`` xor ``drop_node``):
  consecutive drops shift each other's index space, but the run is
  equivalent to dropping a SET of original-space indices; the run is
  translated to that set and re-emitted in descending original order
  (descending drops do not shift each other).  ``[drop_edge 0,
  drop_edge 0]`` and ``[drop_edge 1, drop_edge 0]`` both become
  ``[drop_edge 1, drop_edge 0]``.  Out-of-range raw indices translate
  to out-of-range original indices, so refusals are preserved;
  drop_node's last-node-of-pattern refusal depends only on the dropped
  SET, not the order (each drop shrinks its pattern by exactly one).
- **across run boundaries nothing moves**: a ``drop_node`` removes
  incident edges the router cannot enumerate, so edge indices after it
  are not translatable without the mixture.  Runs stay in sequence.

Anything not obviously canonicalizable — an unknown op, a non-int or
negative index, a ``sub_edge`` with neither field, an over-cap script —
is returned UNCHANGED (soundness by identity: apply_whatif refuses raw
and canonical alike).  The normal form is idempotent:
``canonical_edits(canonical_edits(e)) == canonical_edits(e)``.

``canonical_lens_key`` wraps the normal form into the hashable tuple
the memo keys on (None for a default/absent lens payload).
"""

from __future__ import annotations

import json

from pertgnn_tpu.lens.whatif import MAX_EDITS

# fields each op carries beyond "op"; anything else is ignored by
# apply_whatif and therefore dropped from the normal form
_OP_FIELDS = {
    "drop_edge": ("edge",),
    "drop_node": ("node",),
    "sub_node": ("node", "ms_id"),
    "sub_edge": ("edge", "iface", "rpctype"),
}
_INDEX_FIELD = {"drop_edge": "edge", "drop_node": "node",
                "sub_node": "node", "sub_edge": "edge"}


class _Uncanonical(Exception):
    """Internal: the script left the provable fragment — emit it raw."""


def _as_nonneg_int(value) -> int:
    try:
        v = int(value)
    except (TypeError, ValueError):
        raise _Uncanonical(f"non-int field {value!r}")
    if v < 0:
        raise _Uncanonical(f"negative field {v}")
    return v


def _parse(edit) -> dict:
    """One edit into its normalized dict (known fields only, int
    values) — or _Uncanonical when it is outside the provable
    fragment."""
    if not isinstance(edit, dict):
        raise _Uncanonical(f"edit is {type(edit).__name__}, not dict")
    op = edit.get("op")
    if op not in _OP_FIELDS:
        raise _Uncanonical(f"unknown op {op!r}")
    out = {"op": op}
    for f in _OP_FIELDS[op]:
        if f in edit:
            out[f] = _as_nonneg_int(edit[f])
    if _INDEX_FIELD[op] not in out:
        raise _Uncanonical(f"{op} without its index field")
    if op == "sub_node" and "ms_id" not in out:
        raise _Uncanonical("sub_node without ms_id")
    if op == "sub_edge" and "iface" not in out and "rpctype" not in out:
        raise _Uncanonical("sub_edge with neither iface nor rpctype")
    return out


def _seg_kind(e: dict) -> str:
    op = e["op"]
    return op if op in ("drop_edge", "drop_node") else "sub"


def _canon_sub_run(run: list[dict]) -> list[dict]:
    """Dedup no-op writes, then stable-sort the commuting writes.

    A write is a no-op iff every (target, field) it sets equals the
    LAST value written to that slot earlier in the run — dropping it
    never changes the arrays, and never changes refusal behavior (the
    identical earlier write refuses first if the value is invalid)."""
    kept: list[dict] = []
    last_write: dict[tuple, int] = {}
    for e in run:
        slots = [(e["op"], e[_INDEX_FIELD[e["op"]]], f, e[f])
                 for f in _OP_FIELDS[e["op"]][1:] if f in e]
        if slots and all(last_write.get(s[:3]) == s[3] for s in slots):
            continue
        for op, idx, f, v in slots:
            last_write[(op, idx, f)] = v
        kept.append(e)
    # sub_edge before sub_node (they touch disjoint arrays and always
    # commute); equal keys keep their order — same-target writes are
    # order-sensitive and must not be permuted
    kept.sort(key=lambda e: (e["op"] != "sub_edge",
                             e[_INDEX_FIELD[e["op"]]]))
    return kept


def _canon_drop_run(run: list[dict], op: str) -> list[dict]:
    """A run of same-kind drops as descending original-space drops."""
    field = _INDEX_FIELD[op]
    dropped: list[int] = []
    for e in run:
        orig = e[field]
        for d in sorted(dropped):
            if d <= orig:
                orig += 1
        dropped.append(orig)
    return [{"op": op, field: d}
            for d in sorted(dropped, reverse=True)]


def canonical_edits(edits) -> tuple:
    """The normal form of an edit script, as a tuple of edit dicts.

    Pure and mixture-free; returns the input (tuple-ified) whenever any
    edit falls outside the provable fragment, so the bit-identity
    oracle holds unconditionally."""
    edits = list(edits)
    if len(edits) > MAX_EDITS:
        # apply_whatif refuses over-cap scripts before reading them; a
        # normal form that shrank one under the cap would turn a
        # refusal into an answer
        return tuple(edits)
    try:
        parsed = [_parse(e) for e in edits]
    except _Uncanonical:
        return tuple(edits)
    out: list[dict] = []
    i = 0
    while i < len(parsed):
        kind = _seg_kind(parsed[i])
        j = i
        while j < len(parsed) and _seg_kind(parsed[j]) == kind:
            j += 1
        run = parsed[i:j]
        out.extend(_canon_sub_run(run) if kind == "sub"
                   else _canon_drop_run(run, kind))
        i = j
    return tuple(out)


def canonical_lens_key(lens_wire: dict | None):
    """The hashable cache-key component for a lens wire payload
    (LensRequest.to_wire form) — None for plain/default traffic, else a
    tuple over (attribute_k, canonical edit script)."""
    if not lens_wire:
        return None
    try:
        k = int(lens_wire.get("k", 0))
    except (TypeError, ValueError):
        k = -1
    edits = canonical_edits(lens_wire.get("edits", ()))
    try:
        ekey = tuple(tuple(sorted(e.items())) for e in edits)
    except TypeError:
        # unhashable values inside a raw passthrough — key on a
        # deterministic serialization instead
        ekey = json.dumps(list(edits), sort_keys=True, default=repr)
    return (k, ekey)
