"""Counterfactual topology edits: pure functions over the Mixture arena.

The serving engine answers "what latency does the model predict for
THIS entry" — a what-if query asks "...and what if this call were not
there / went through a different service?" Because a Mixture is just
flat numpy arrays (batching/mixture.py), a counterfactual is a PURE
edit of those arrays: no ingest, no graph reconstruction, no state.
The edited mixture re-packs through the existing bucket ladder
(serve/engine.pack_microbatch with a per-request mixture override), and
since ladder rungs key on SHAPE — and edits never grow the graph — a
counterfactual dispatch can never trigger a compile: zero fresh
compiles by construction (benchmarks/lens_bench.py exit-code-asserts
exactly that).

Edit-op vocabulary (JSON-able dicts, applied in order):

- ``{"op": "drop_edge", "edge": i}``     — remove edge i (a call);
- ``{"op": "drop_node", "node": i}``     — remove node i (a service
  stage) and every incident edge; the node's pattern shrinks by one;
- ``{"op": "sub_node", "node": i, "ms_id": m}`` — the node's stage runs
  on microservice ``m`` instead (same topology);
- ``{"op": "sub_edge", "edge": i, "iface": f[, "rpctype": r]}`` — the
  call goes through interface ``f`` (and optionally rpctype ``r``).

Semantics (the parts a pure edit must PIN, and the from-scratch oracle
in tests/test_lens.py verifies): node/edge index spaces are the
mixture's own (block-diagonal over its runtime patterns, recoverable
from ``pattern_size`` — each block's length IS its nodes' size value);
``pattern_prob`` is untouched (the mixture weighting is observed
traffic, not topology); ``pattern_size`` follows the edited node count
so pooling weights match a from-scratch build of the edited graph;
``feature_mask`` is recomputed per pattern block with the reference's
last-stage-copy rule (build_mixtures._last_occurrence_mask — a
substitution can move which copy is "last"); ``node_depth`` keeps the
OBSERVED values (depth is a feature of the measured topology; the
counterfactual does not re-derive features the real system never
emitted for it).

Everything the algebra cannot honor is REFUSED with the typed
``WhatIfRefused`` (serve/errors.py) at submit — out-of-range indices,
substitute ids outside the embedding vocabularies, dropping a
pattern's last node (its pooling weight would divide by zero), or an
oversized edit list. Never an approximate edit.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pertgnn_tpu.batching.mixture import Mixture, _last_occurrence_mask
from pertgnn_tpu.serve.errors import WhatIfRefused

# Backstop against degenerate requests hauling unbounded edit scripts
# through the admission path; real what-if queries edit a handful of
# calls.
MAX_EDITS = 64

EDIT_OPS = ("drop_edge", "drop_node", "sub_node", "sub_edge")


def pattern_blocks(mixture: Mixture) -> list[tuple[int, int]]:
    """[start, end) node ranges of the mixture's runtime-pattern blocks,
    recovered from ``pattern_size`` — build_mixtures lays patterns out
    contiguously and stamps each node with its pattern's node count, so
    the size at a block's first node IS the block length."""
    blocks: list[tuple[int, int]] = []
    i, n = 0, mixture.num_nodes
    while i < n:
        size = int(mixture.pattern_size[i])
        if size <= 0 or i + size > n:
            raise WhatIfRefused(
                f"mixture pattern layout is inconsistent at node {i} "
                f"(size {size} of {n}) — cannot edit it safely")
        blocks.append((i, i + size))
        i += size
    return blocks


def _check_index(kind: str, idx, limit: int) -> int:
    try:
        i = int(idx)
    except (TypeError, ValueError):
        raise WhatIfRefused(f"{kind} index must be an int, got {idx!r}")
    if not 0 <= i < limit:
        raise WhatIfRefused(
            f"{kind} index {i} out of range [0, {limit})")
    return i


def _check_vocab(kind: str, value, limit: int | None) -> int:
    try:
        v = int(value)
    except (TypeError, ValueError):
        raise WhatIfRefused(f"{kind} must be an int, got {value!r}")
    if v < 0 or (limit is not None and v >= limit):
        raise WhatIfRefused(
            f"{kind} {v} outside the embedding vocabulary "
            f"[0, {limit if limit is not None else 'unknown'}) — a "
            f"counterfactual cannot invent services the model never "
            f"embedded")
    return v


def _recompute_feature_mask(mix: dict, blocks: list[tuple[int, int]],
                            feature_all_stage_copies: bool) -> np.ndarray:
    if feature_all_stage_copies:
        return np.ones(len(mix["ms_id"]), dtype=bool)
    parts = [_last_occurrence_mask(mix["ms_id"][a:b]) for a, b in blocks]
    return (np.concatenate(parts) if parts
            else np.zeros(0, dtype=bool))


def apply_whatif(mixture: Mixture, edits, *,
                 num_ms: int | None = None,
                 num_interfaces: int | None = None,
                 num_rpctypes: int | None = None,
                 feature_all_stage_copies: bool = False) -> Mixture:
    """The edited Mixture — a pure function of (mixture, edits); the
    input is never mutated. Raises ``WhatIfRefused`` for anything the
    edit algebra cannot honor (module docstring lists the cases). The
    vocabulary bounds are optional (None skips that check) so the
    function stays usable on bare mixtures in tests; the serving path
    always passes the dataset's sizes."""
    edits = list(edits)
    if len(edits) > MAX_EDITS:
        raise WhatIfRefused(
            f"{len(edits)} edits exceed the {MAX_EDITS}-op cap")
    arr = {
        "senders": mixture.senders.copy(),
        "receivers": mixture.receivers.copy(),
        "edge_iface": mixture.edge_iface.copy(),
        "edge_rpctype": mixture.edge_rpctype.copy(),
        "edge_duration": mixture.edge_duration.copy(),
        "ms_id": mixture.ms_id.copy(),
        "node_depth": mixture.node_depth.copy(),
        "pattern_prob": mixture.pattern_prob.copy(),
        "pattern_size": mixture.pattern_size.copy(),
    }
    for e in edits:
        if not isinstance(e, dict):
            raise WhatIfRefused(f"edit must be a dict, got {type(e)}")
        op = e.get("op")
        if op == "drop_edge":
            i = _check_index("edge", e.get("edge"), len(arr["senders"]))
            for f in ("senders", "receivers", "edge_iface",
                      "edge_rpctype", "edge_duration"):
                arr[f] = np.delete(arr[f], i)
        elif op == "sub_edge":
            i = _check_index("edge", e.get("edge"), len(arr["senders"]))
            if "iface" in e:
                arr["edge_iface"][i] = _check_vocab(
                    "iface", e["iface"], num_interfaces)
            if "rpctype" in e:
                arr["edge_rpctype"][i] = _check_vocab(
                    "rpctype", e["rpctype"], num_rpctypes)
            if "iface" not in e and "rpctype" not in e:
                raise WhatIfRefused(
                    "sub_edge needs an 'iface' and/or 'rpctype'")
        elif op == "sub_node":
            i = _check_index("node", e.get("node"), len(arr["ms_id"]))
            arr["ms_id"][i] = _check_vocab("ms_id", e.get("ms_id"),
                                           num_ms)
        elif op == "drop_node":
            i = _check_index("node", e.get("node"), len(arr["ms_id"]))
            if int(arr["pattern_size"][i]) <= 1:
                raise WhatIfRefused(
                    f"node {i} is its pattern's last node — dropping it "
                    f"would leave an empty pattern (pooling weight "
                    f"divides by pattern_size)")
            # the node's contiguous pattern block shrinks by one, so
            # remaining members' pattern_size matches a from-scratch
            # build of the edited graph; recover the block via the
            # layout walk (sizes change as edits apply)
            size = arr["pattern_size"][i]
            start = 0
            n = len(arr["ms_id"])
            while start < n:
                b = int(arr["pattern_size"][start])
                if start <= i < start + b:
                    break
                start += b
            else:  # pragma: no cover — _check_index bounds i
                raise WhatIfRefused(f"node {i} not inside any pattern")
            sel = slice(start, start + int(size))
            arr["pattern_size"][sel] = size - 1
            keep_e = (arr["senders"] != i) & (arr["receivers"] != i)
            for f in ("senders", "receivers", "edge_iface",
                      "edge_rpctype", "edge_duration"):
                arr[f] = arr[f][keep_e]
            arr["senders"] = np.where(arr["senders"] > i,
                                      arr["senders"] - 1, arr["senders"])
            arr["receivers"] = np.where(arr["receivers"] > i,
                                        arr["receivers"] - 1,
                                        arr["receivers"])
            for f in ("ms_id", "node_depth", "pattern_prob",
                      "pattern_size"):
                arr[f] = np.delete(arr[f], i)
        else:
            raise WhatIfRefused(
                f"unknown edit op {op!r} (choose from {EDIT_OPS})")
    if len(arr["ms_id"]) == 0:
        raise WhatIfRefused("edits removed every node of the mixture")
    out = dataclasses.replace(
        mixture,
        senders=arr["senders"].astype(np.int32),
        receivers=arr["receivers"].astype(np.int32),
        edge_iface=arr["edge_iface"].astype(np.int32),
        edge_rpctype=arr["edge_rpctype"].astype(np.int32),
        edge_duration=arr["edge_duration"].astype(np.float32),
        ms_id=arr["ms_id"].astype(np.int32),
        node_depth=arr["node_depth"].astype(np.float32),
        pattern_prob=arr["pattern_prob"].astype(np.float32),
        pattern_size=arr["pattern_size"].astype(np.float32),
        feature_mask=np.zeros(0, dtype=bool),  # recomputed below
        num_nodes=int(len(arr["ms_id"])),
        num_edges=int(len(arr["senders"])),
    )
    blocks = pattern_blocks(out)
    out = dataclasses.replace(
        out, feature_mask=_recompute_feature_mask(
            arr, blocks, feature_all_stage_copies))
    # edits only drop or substitute: the capacity accounting at the
    # front doors keeps using the BASE mixture's sizes as a safe upper
    # bound, which this invariant is load-bearing for
    assert out.num_nodes <= mixture.num_nodes
    assert out.num_edges <= mixture.num_edges
    return out
