from pertgnn_tpu.ops.segment import (
    segment_sum,
    segment_max,
    segment_softmax,
    segment_edge_attention,
    segment_mean_by_graph,
)
