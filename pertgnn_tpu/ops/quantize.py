"""Weight-only int8 quantization for the serve tier.

`ServeConfig.serve_dtype="int8"` serves with per-output-channel
symmetric int8 WEIGHT quantization: every 2-D float parameter (Dense
kernels, embedding tables) is stored on device as an int8 matrix plus a
float32 per-column scale, and dequantized IN-GRAPH to bf16 right before
its matmul — XLA fuses the `q.astype(bf16) * scale` into the consumer,
so the executable reads a quarter of the weight bytes from HBM while the
MXU still runs a dense bf16 GEMM. For a memory-bound workload (MBU is
the honest utilization number here — utils/flops.py) weight bytes are
exactly what the roofline charges for.

1-D parameters (biases, BatchNorm scale/bias) and the running statistics
stay float32: they are O(features) bytes — quantizing them saves nothing
and costs accuracy.

Quality is never assumed: benchmarks/serve_bench.py exit-code-asserts
the quantile-loss delta vs the f32 engine against a pre-registered
per-dtype threshold, and the serve engine's AOT store keys cover
`serve_dtype` so a quantized executable can never be replayed for an f32
config (tests/test_aot.py).
"""

from __future__ import annotations

import jax.numpy as jnp

# The two leaves of one quantized parameter. Kept as a plain dict so the
# quantized tree is an ordinary pytree: the AOT store's abstract
# signature sees the int8 leaves + treedef and keys the executables
# accordingly for free.
_QKEYS = frozenset(("int8", "scale"))


def quantize_array(w, *, axis: int = 0):
    """(int8 q, float32 scale) with symmetric per-output-channel scales:
    `scale` has w's shape with `axis` reduced (kept as size 1), chosen so
    q = round(w / scale) ∈ [-127, 127]. All-zero channels get scale 1 so
    dequantization stays exact (0 * 1 = 0)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_array(q, scale, dtype=jnp.bfloat16):
    """In-graph dequantize: the int8 matrix is the HBM-resident form;
    the cast+scale fuses into the consuming matmul."""
    return q.astype(dtype) * scale.astype(dtype)


def quantize_tree(params):
    """Quantize every 2-D float leaf of a (nested-dict) param tree to
    {"int8": ..., "scale": ...}; everything else passes through
    unchanged. The result is a valid pytree with the same nesting."""
    def rec(node):
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        a = jnp.asarray(node)
        if a.ndim == 2 and jnp.issubdtype(a.dtype, jnp.floating):
            q, scale = quantize_array(a)
            return {"int8": q, "scale": scale}
        return node
    return rec(params)


def dequantize_tree(params, dtype=jnp.bfloat16):
    """Inverse of `quantize_tree` as traced graph ops: quantized leaves
    come back as `dtype` (bf16) matrices, pass-through leaves unchanged.
    Runs INSIDE the serve step program (serve/engine.py) so the compiled
    executable's parameter inputs stay int8."""
    def rec(node):
        if isinstance(node, dict):
            if set(node) == _QKEYS:
                return dequantize_array(node["int8"], node["scale"], dtype)
            return {k: rec(v) for k, v in node.items()}
        return node
    return rec(params)


def quantization_error(params) -> dict:
    """Max relative round-trip error per quantized leaf count — a cheap
    sanity probe for tests/benches (not a quality gate; the REAL gate is
    serve_bench's quantile-loss delta)."""
    import numpy as np

    errs = []
    # round-trip error needs the original; computed by comparing against
    # dequantized-from-quantized of the caller's tree
    q = quantize_tree(params)

    def walk(orig, quant):
        if isinstance(quant, dict) and set(quant) == _QKEYS:
            w0 = np.asarray(orig, np.float32)
            w1 = np.asarray(dequantize_array(quant["int8"], quant["scale"],
                                             jnp.float32))
            denom = max(float(np.abs(w0).max()), 1e-12)
            errs.append(float(np.abs(w1 - w0).max()) / denom)
        elif isinstance(quant, dict):
            for k in quant:
                walk(orig[k], quant[k])

    walk(params, q)
    return {"quantized_leaves": len(errs),
            "max_rel_error": max(errs) if errs else 0.0}
