"""Pallas fused edge-attention kernels (TPU) — forward AND backward.

The conv hot op is per-edge attention: score each edge against its
destination node, softmax over each destination's incoming edges, and
aggregate messages (the PyG `TransformerConv` inner loop the reference runs
on CUDA scatter kernels, /root/reference/model.py:100-104). The default XLA
path (ops/segment.py `segment_edge_attention`) expresses it as gather →
segment-softmax → segment-sum, which materializes per-edge q/k/v
intermediates in HBM between fusions.

These kernels do the whole pass in one HBM round-trip per direction,
gather-free, shaped for the MXU:

- edges are sorted by destination (receiver) — legal because segment
  aggregation is order-free — and padded/masked edges are given receiver id
  N so they sort to the tail and can never match a real node row;
- tiles of (node block × edge block): the scores are a dense
  `q_block @ k_edge_blockᵀ` matmul (MXU) masked by the incidence
  `receiver[e] == node_id[n]` built from iota — the gather/scatter of the
  segment formulation becomes a masked dense matmul, the standard TPU trick
  for irregular access;
- forward: FlashAttention-style online softmax (running max / denominator /
  numerator in VMEM scratch); also emits the per-(node, head) logsumexp so
  backward can recompute attention weights in one pass;
- backward (flash recompute): with g = dL/dout, the softmax row term is
  D_n = Σ_e α_e (v_e·g_n) = out_n·g_n — free from saved outputs. Then
      dv_e = α_e g_r(e)            dq_n = Σ_e ds_e k_e · scale
      ds_e = α_e ((v_e·g_r(e)) − D_r(e))      dk_e = ds_e q_r(e) · scale.
  dq is node-indexed → accumulated over the forward's node-major walk;
  dk/dv are edge-indexed → a TRANSPOSED edge-major walk, where each edge
  block's covering node blocks are contiguous (receivers sorted), so its
  output tile stays resident in VMEM across its ≤(BE/BN + 2) visits;
- both walks are flattened to a 1-D grid of ACTIVE tiles with a static step
  bound (nNB + nEB, telescoping on the sorted receiver cut points); skipped
  tiles cost nothing.

Nodes with no (valid) incoming edges produce zeros, matching
`segment_softmax` (an absent destination never appears in the scatter);
masked edges receive zero gradients (their receiver row is a zero-g pad).

When to use (measured on one TPU chip, f32, full train step = grad):
with the fused backward, the kernel beats XLA's sorted-segment path
1.1-2.0x on dense-degree microbenches (deg 2-8, hidden 32-256, per-call
sync); on the flagship packed-batch model (avg degree ~1.3) it is at
parity within run-to-run noise (medians 2.06M vs 2.02M graphs/s over 5
interleaved runs; tunnel variance ~±40%). It runs per-device (no SPMD
partitioning rules), so `ModelConfig.use_pallas_attention` defaults to
False and is enabled explicitly for single-chip runs (bench.py does);
the CPU test platform uses interpret mode, which is slow — keep it off
in CPU-bound tests unless testing the kernel itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pertgnn_tpu.ops.segment import segment_edge_attention

_NEG = -1e30
_HI = jax.lax.Precision.HIGHEST


def _round_up(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _walk(lo, hi, num_minor_blocks: int, t_max: int):
    """Flatten per-row [lo, hi) minor-block ranges into a 1-D active-step
    walk of static length `t_max` (rows get max(span, 1) steps; tail steps
    duplicate the last indices and are marked invalid).

    Returns (major_seq (t_max+1,) with -1 sentinel, minor_idx (t_max,),
    valid (t_max,) int32)."""
    num_rows = lo.shape[0]
    spans = jnp.maximum(hi - lo, 0)
    steps = jnp.maximum(spans, 1)
    cum = jnp.cumsum(steps)
    total = cum[-1]
    t_arr = jnp.arange(t_max, dtype=jnp.int32)
    in_range = t_arr < total
    row = jnp.searchsorted(cum, t_arr, side="right").astype(jnp.int32)
    row = jnp.where(in_range, jnp.minimum(row, num_rows - 1), num_rows - 1)
    off = t_arr - (cum - steps)[row]
    minor = jnp.clip(lo[row] + jnp.minimum(off, jnp.maximum(spans[row] - 1,
                                                            0)),
                     0, num_minor_blocks - 1).astype(jnp.int32)
    valid = (in_range & (spans[row] > 0) & (off < spans[row])).astype(
        jnp.int32)
    seq = jnp.concatenate([row, jnp.full((1,), -1, jnp.int32)])
    return seq, minor, valid


def _edge_block_ranges(rcv_sorted, block_n, block_e, num_node_blocks,
                       num_edge_blocks):
    """Per node block i: edge-block range [lo_i, hi_i) that can contain its
    receivers (sorted receivers → searchsorted cut points)."""
    starts = jnp.arange(num_node_blocks, dtype=jnp.int32) * block_n
    lo = (jnp.searchsorted(rcv_sorted, starts, side="left")
          // block_e).astype(jnp.int32)
    hi_edge = jnp.searchsorted(rcv_sorted, starts + block_n, side="left")
    hi = ((hi_edge + block_e - 1) // block_e).astype(jnp.int32)
    return lo, hi


def _node_block_ranges(rcv_sorted, block_n, block_e, num_node_blocks,
                       num_edge_blocks):
    """Per edge block j: node-block range [plo_j, phi_j) covering its
    receivers (contiguous because receivers are sorted)."""
    e_pad = rcv_sorted.shape[0]
    first = rcv_sorted[jnp.arange(num_edge_blocks) * block_e]
    last = rcv_sorted[jnp.minimum(
        (jnp.arange(num_edge_blocks) + 1) * block_e - 1, e_pad - 1)]
    plo = jnp.clip(first // block_n, 0, num_node_blocks - 1).astype(
        jnp.int32)
    phi = jnp.clip(last // block_n + 1, plo + 1, num_node_blocks).astype(
        jnp.int32)
    return plo, phi


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(it_ref, jdx_ref, valid_ref, q_ref, k_ref, v_ref, rcv_ref,
                out_ref, lse_ref, m_ref, l_ref, acc_ref, *, heads: int,
                head_dim: int, block_n: int, block_e: int):
    t = pl.program_id(0)
    i = it_ref[t]

    # first step of a new node block → reset the online-softmax state
    @pl.when((t == 0) | (i != it_ref[jnp.maximum(t - 1, 0)]))
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(valid_ref[t] == 1)
    def _block():
        rcv = rcv_ref[0, :]  # (BE,)
        node_ids = i * block_n + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, block_e), 0)
        incidence = node_ids == rcv[None, :]  # (BN, BE)
        scale = 1.0 / float(np.sqrt(head_dim))
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            qh = q_ref[:, sl]  # (BN, C)
            kh = k_ref[:, sl]  # (BE, C)
            vh = v_ref[:, sl]
            scores = jax.lax.dot_general(
                qh, kh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_HI) * scale  # (BN, BE)
            scores = jnp.where(incidence, scores, _NEG)
            m_prev = m_ref[:, h:h + 1]                         # (BN, 1)
            m_new = jnp.maximum(m_prev,
                                jnp.max(scores, axis=1, keepdims=True))
            # explicit re-mask: when a row has no incidence yet,
            # scores - m_new = 0 and exp would leak 1s
            p = jnp.where(incidence, jnp.exp(scores - m_new), 0.0)
            corr = jnp.exp(m_prev - m_new)                     # (BN, 1)
            l_ref[:, h:h + 1] = (l_ref[:, h:h + 1] * corr
                                 + jnp.sum(p, axis=1, keepdims=True))
            acc_ref[:, sl] = acc_ref[:, sl] * corr + jnp.dot(
                p, vh, preferred_element_type=jnp.float32, precision=_HI)
            m_ref[:, h:h + 1] = m_new

    # last step of this node block (sentinel it[-1] = -1 closes the final
    # block) → normalize, emit output and logsumexp
    @pl.when(it_ref[t + 1] != i)
    def _finalize():
        l = l_ref[:]  # (BN, H)
        denom = jnp.where(l > 0, l, 1.0)
        inv = 1.0 / denom
        lse_ref[:] = jnp.where(l > 0, m_ref[:] + jnp.log(denom), 0.0)
        out = acc_ref[:]
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            out_ref[:, sl] = (out[:, sl] * inv[:, h:h + 1]).astype(
                out_ref.dtype)


def _forward_sorted(q2, k_s, v_s, rcv_row, lo, hi, *, heads, head_dim,
                    block_n, block_e, interpret):
    """Already padded + receiver-sorted inputs → (out, lse), both padded."""
    n_pad, hd = q2.shape
    e_pad = k_s.shape[0]
    num_node_blocks = n_pad // block_n
    num_edge_blocks = e_pad // block_e
    t_max = num_node_blocks + num_edge_blocks
    it_seq, jdx, valid = _walk(lo, hi, num_edge_blocks, t_max)

    kernel = functools.partial(_fwd_kernel, heads=heads, head_dim=head_dim,
                               block_n=block_n, block_e=block_e)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda t, it, jdx, v: (it[t], 0)),
            pl.BlockSpec((block_e, hd), lambda t, it, jdx, v: (jdx[t], 0)),
            pl.BlockSpec((block_e, hd), lambda t, it, jdx, v: (jdx[t], 0)),
            pl.BlockSpec((1, block_e), lambda t, it, jdx, v: (0, jdx[t])),
        ],
        out_specs=(
            pl.BlockSpec((block_n, hd), lambda t, it, jdx, v: (it[t], 0)),
            pl.BlockSpec((block_n, heads),
                         lambda t, it, jdx, v: (it[t], 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_n, heads), jnp.float32),  # running max
            pltpu.VMEM((block_n, heads), jnp.float32),  # running denom
            pltpu.VMEM((block_n, hd), jnp.float32),     # running numerator
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((n_pad, hd), jnp.float32),
                   jax.ShapeDtypeStruct((n_pad, heads), jnp.float32)),
        interpret=interpret,
    )(it_seq, jdx, valid, q2, k_s, v_s, rcv_row)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(it_ref, jdx_ref, valid_ref, q_ref, k_ref, v_ref, g_ref,
                   lse_ref, d_ref, rcv_ref, dq_ref, dq_acc, *, heads: int,
                   head_dim: int, block_n: int, block_e: int):
    """Node-major walk: dq_n = Σ_e α_e ((v_e·g_n) − D_n) k_e · scale."""
    t = pl.program_id(0)
    i = it_ref[t]

    @pl.when((t == 0) | (i != it_ref[jnp.maximum(t - 1, 0)]))
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(valid_ref[t] == 1)
    def _block():
        rcv = rcv_ref[0, :]
        node_ids = i * block_n + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, block_e), 0)
        incidence = node_ids == rcv[None, :]
        scale = 1.0 / float(np.sqrt(head_dim))
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            qh, kh, vh, gh = (q_ref[:, sl], k_ref[:, sl], v_ref[:, sl],
                              g_ref[:, sl])
            scores = jax.lax.dot_general(
                qh, kh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI) * scale
            alpha = jnp.where(incidence,
                              jnp.exp(scores - lse_ref[:, h:h + 1]), 0.0)
            dalpha = jax.lax.dot_general(
                gh, vh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI)
            ds = alpha * (dalpha - d_ref[:, h:h + 1])
            dq_acc[:, sl] += jnp.dot(ds, kh,
                                     preferred_element_type=jnp.float32,
                                     precision=_HI) * scale

    @pl.when(it_ref[t + 1] != i)
    def _finalize():
        dq_ref[:] = dq_acc[:]


def _bwd_dkv_kernel(jt_ref, ip_ref, valid_ref, q_ref, k_ref, v_ref, g_ref,
                    lse_ref, d_ref, rcv_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, heads: int, head_dim: int, block_n: int,
                    block_e: int):
    """Edge-major walk: dv_e = α_e g_r(e); dk_e = ds_e q_r(e) · scale.
    Each edge block's covering node blocks are visited consecutively, so
    its accumulators live in VMEM across visits."""
    t = pl.program_id(0)
    j = jt_ref[t]
    i = ip_ref[t]

    @pl.when((t == 0) | (j != jt_ref[jnp.maximum(t - 1, 0)]))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(valid_ref[t] == 1)
    def _block():
        rcv = rcv_ref[0, :]
        node_ids = i * block_n + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, block_e), 0)
        incidence = node_ids == rcv[None, :]
        scale = 1.0 / float(np.sqrt(head_dim))
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            qh, kh, vh, gh = (q_ref[:, sl], k_ref[:, sl], v_ref[:, sl],
                              g_ref[:, sl])
            scores = jax.lax.dot_general(
                qh, kh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI) * scale
            alpha = jnp.where(incidence,
                              jnp.exp(scores - lse_ref[:, h:h + 1]), 0.0)
            dalpha = jax.lax.dot_general(
                gh, vh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI)
            ds = alpha * (dalpha - d_ref[:, h:h + 1])
            # contract over the node dim (0): (BN,BE)^T @ (BN,C) -> (BE,C)
            dv_acc[:, sl] += jax.lax.dot_general(
                alpha, gh, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI)
            dk_acc[:, sl] += jax.lax.dot_general(
                ds, qh, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_HI) * scale

    @pl.when(jt_ref[t + 1] != j)
    def _finalize():
        dk_ref[:] = dk_acc[:]
        dv_ref[:] = dv_acc[:]


def _backward_sorted(q2, k_s, v_s, rcv_row, lo, hi, lse, out, g, *, heads,
                     head_dim, block_n, block_e, interpret):
    """Padded + sorted inputs → (dq, dk_sorted, dv_sorted), all padded."""
    n_pad, hd = q2.shape
    e_pad = k_s.shape[0]
    num_node_blocks = n_pad // block_n
    num_edge_blocks = e_pad // block_e
    rcv_sorted = rcv_row[0]

    # D_n,h = out_n,h-slice · g_n,h-slice  (softmax row term)
    d = (out.reshape(n_pad, heads, head_dim)
         * g.reshape(n_pad, heads, head_dim)).sum(-1)

    common = dict(heads=heads, head_dim=head_dim, block_n=block_n,
                  block_e=block_e)

    # dq: node-major walk (same as forward)
    t_max = num_node_blocks + num_edge_blocks
    it_seq, jdx, valid = _walk(lo, hi, num_edge_blocks, t_max)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(t_max,),
            in_specs=[
                pl.BlockSpec((block_n, hd), lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((block_n, hd), lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_n, heads),
                             lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_n, heads),
                             lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((1, block_e), lambda t, a, b, c: (0, b[t])),
            ],
            out_specs=pl.BlockSpec((block_n, hd),
                                   lambda t, a, b, c: (a[t], 0)),
            scratch_shapes=[pltpu.VMEM((block_n, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, hd), jnp.float32),
        interpret=interpret,
    )(it_seq, jdx, valid, q2, k_s, v_s, g, lse, d, rcv_row)

    # dk/dv: edge-major walk over covering node blocks
    plo, phi = _node_block_ranges(rcv_sorted, block_n, block_e,
                                  num_node_blocks, num_edge_blocks)
    t2_max = num_edge_blocks + num_node_blocks
    jt_seq, ip, valid2 = _walk(plo, phi, num_node_blocks, t2_max)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(t2_max,),
            in_specs=[
                pl.BlockSpec((block_n, hd), lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_n, hd), lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((block_n, heads),
                             lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((block_n, heads),
                             lambda t, a, b, c: (b[t], 0)),
                pl.BlockSpec((1, block_e), lambda t, a, b, c: (0, a[t])),
            ],
            out_specs=(
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (a[t], 0)),
                pl.BlockSpec((block_e, hd), lambda t, a, b, c: (a[t], 0)),
            ),
            scratch_shapes=[pltpu.VMEM((block_e, hd), jnp.float32),
                            pltpu.VMEM((block_e, hd), jnp.float32)],
        ),
        out_shape=(jax.ShapeDtypeStruct((e_pad, hd), jnp.float32),
                   jax.ShapeDtypeStruct((e_pad, hd), jnp.float32)),
        interpret=interpret,
    )(jt_seq, ip, valid2, q2, k_s, v_s, g, lse, d, rcv_row)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


def _pad_inputs(q, k_s, v_s, rcv_eff_sorted, num_nodes, n, e, hd, n_pad,
                e_pad):
    q2 = jnp.zeros((n_pad, hd), jnp.float32).at[:n].set(
        q.reshape(n, hd).astype(jnp.float32))
    k2 = jnp.zeros((e_pad, hd), jnp.float32).at[:e].set(
        k_s.reshape(e, hd).astype(jnp.float32))
    v2 = jnp.zeros((e_pad, hd), jnp.float32).at[:e].set(
        v_s.reshape(e, hd).astype(jnp.float32))
    rcv_row = jnp.full((1, e_pad), num_nodes, jnp.int32).at[0, :e].set(
        rcv_eff_sorted.astype(jnp.int32))
    return q2, k2, v2, rcv_row


# static config travels via nondiff_argnums; the (integer, traced) sorted
# receivers are a PRIMAL with a float0 cotangent — custom_vjp cannot close
# over traced arrays.
@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _fused_sorted(num_nodes, n, e, heads, head_dim, block_n, block_e,
                  interpret, q, k_s, v_s, rcv_eff_sorted):
    """Fused attention over SORTED inputs: q (N,H,C), k_s/v_s (E,H,C),
    rcv_eff_sorted (E,) ascending with masked edges = num_nodes at the
    tail. Returns (N, H*C) float32."""
    out, _ = _fused_fwd(num_nodes, n, e, heads, head_dim, block_n, block_e,
                        interpret, q, k_s, v_s, rcv_eff_sorted)
    return out


def _fused_fwd(num_nodes, n, e, heads, head_dim, block_n, block_e,
               interpret, q, k_s, v_s, rcv_eff_sorted):
    hd = heads * head_dim
    n_pad = _round_up(max(n, block_n), block_n)
    e_pad = _round_up(max(e, block_e), block_e)
    q2, k2, v2, rcv_row = _pad_inputs(q, k_s, v_s, rcv_eff_sorted,
                                      num_nodes, n, e, hd, n_pad, e_pad)
    lo, hi = _edge_block_ranges(rcv_row[0], block_n, block_e,
                                n_pad // block_n, e_pad // block_e)
    out, lse = _forward_sorted(q2, k2, v2, rcv_row, lo, hi, heads=heads,
                               head_dim=head_dim, block_n=block_n,
                               block_e=block_e, interpret=interpret)
    return out[:n], (q2, k2, v2, rcv_row, lo, hi, lse, out)


def _fused_bwd(num_nodes, n, e, heads, head_dim, block_n, block_e,
               interpret, res, g):
    q2, k2, v2, rcv_row, lo, hi, lse, out = res
    hd = heads * head_dim
    n_pad = q2.shape[0]
    g2 = jnp.zeros((n_pad, hd), jnp.float32).at[:n].set(
        g.astype(jnp.float32))
    dq, dk, dv = _backward_sorted(q2, k2, v2, rcv_row, lo, hi, lse, out, g2,
                                  heads=heads, head_dim=head_dim,
                                  block_n=block_n, block_e=block_e,
                                  interpret=interpret)
    return (dq[:n].reshape(n, heads, head_dim),
            dk[:e].reshape(e, heads, head_dim),
            dv[:e].reshape(e, heads, head_dim),
            np.zeros((e,), dtype=jax.dtypes.float0))


_fused_sorted.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# fused per-node epilogue: skip projection + residual + BN statistics
# ---------------------------------------------------------------------------


def _epilogue_kernel(attn_ref, x_ref, w_ref, b_ref, mask_ref, y_ref,
                     stats_ref):
    """One node block: y = attn + x @ W_skip + b_skip, plus the masked
    per-feature (Σy, Σy²) partials MaskedBatchNorm's training pass needs
    — the per-node ops that otherwise round-trip HBM between the
    attention kernel and the rest of GraphTransformerLayer, done in ONE
    read of (attn, x) and one write of y. stats accumulate across the
    sequential TPU grid into a single revisited (2, HD) block."""
    t = pl.program_id(0)
    y = (attn_ref[:]
         + jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32,
                   precision=_HI)
         + b_ref[0, :][None, :])
    y_ref[:] = y

    @pl.when(t == 0)
    def _init():
        stats_ref[:] = jnp.zeros_like(stats_ref)

    m = mask_ref[0, :].astype(jnp.float32)[:, None]  # (BN, 1)
    ym = y * m
    stats_ref[0:1, :] += jnp.sum(ym, axis=0, keepdims=True)
    stats_ref[1:2, :] += jnp.sum(ym * y, axis=0, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _epilogue_padded(block_n, interpret, attn2, x2, w, b, mask_row):
    """Fused epilogue over PADDED inputs: attn2 (Np, HD), x2 (Np, F),
    w (F, HD), b (HD,), mask_row (1, Np) int32. Returns (y (Np, HD),
    stats (2, HD)) with stats = masked (Σy, Σy²) — feed them to
    MaskedBatchNorm(precomputed_sums=...) so its statistics reduction
    never re-reads y from HBM."""
    out, _ = _epilogue_fwd(block_n, interpret, attn2, x2, w, b, mask_row)
    return out


def _epilogue_run(block_n, interpret, attn2, x2, w, b, mask_row):
    n_pad, hd = attn2.shape
    f_in = x2.shape[1]
    grid = (n_pad // block_n,)
    y, stats = pl.pallas_call(
        _epilogue_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda t: (t, 0)),
            pl.BlockSpec((block_n, f_in), lambda t: (t, 0)),
            pl.BlockSpec((f_in, hd), lambda t: (0, 0)),
            pl.BlockSpec((1, hd), lambda t: (0, 0)),
            pl.BlockSpec((1, block_n), lambda t: (0, t)),
        ],
        out_specs=(
            pl.BlockSpec((block_n, hd), lambda t: (t, 0)),
            pl.BlockSpec((2, hd), lambda t: (0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((n_pad, hd), jnp.float32),
                   jax.ShapeDtypeStruct((2, hd), jnp.float32)),
        interpret=interpret,
    )(attn2, x2, w, b[None, :], mask_row)
    return y, stats


def _epilogue_fwd(block_n, interpret, attn2, x2, w, b, mask_row):
    y, stats = _epilogue_run(block_n, interpret, attn2, x2, w, b, mask_row)
    return (y, stats), (x2, w, y, mask_row)


def _epilogue_bwd(block_n, interpret, res, cts):
    """Plain-XLA backward (dense MXU math — nothing here needs a custom
    kernel): with (gy, gs) the cotangents of (y, stats),
        dy_total = gy + mask · (gs₀ + 2 y gs₁)      [stats are Σ my, Σ my²]
        dattn = dy_total;  dx = dy_total Wᵀ;  dW = xᵀ dy_total;
        db = Σ dy_total."""
    x2, w, y, mask_row = res
    gy, gs = cts
    m = mask_row[0].astype(jnp.float32)[:, None]
    dy = gy + m * (gs[0][None, :] + 2.0 * y * gs[1][None, :])
    dattn = dy
    dx = jnp.dot(dy, w.T, precision=_HI)
    dw = jnp.dot(x2.T, dy, precision=_HI)
    db = dy.sum(0)
    dmask = np.zeros(mask_row.shape, dtype=jax.dtypes.float0)
    return dattn, dx, dw, db, dmask


_epilogue_padded.defvjp(_epilogue_fwd, _epilogue_bwd)


def fused_epilogue(attn_out, x, w_skip, b_skip, node_mask, *,
                   block_n: int = 128, interpret: bool | None = None):
    """Fused per-node epilogue of a GraphTransformerLayer:
    y = attn_out + x @ w_skip + b_skip, plus the masked per-feature
    (Σy, Σy²) partials for the following MaskedBatchNorm — one fused
    pass over node blocks instead of separate skip-GEMM / residual /
    statistics HBM round-trips.

    attn_out (N, HD) from `edge_attention`; x (N, F) the layer input;
    w_skip (F, HD), b_skip (HD,) the skip-projection parameters;
    node_mask (N,) bool. Returns (y (N, HD) float32, stats (2, HD)).
    Fully differentiable (custom_vjp; backward is dense XLA math).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, hd = attn_out.shape
    n_pad = _round_up(max(n, block_n), block_n)
    attn2 = jnp.zeros((n_pad, hd), jnp.float32).at[:n].set(
        attn_out.astype(jnp.float32))
    x2 = jnp.zeros((n_pad, x.shape[1]), jnp.float32).at[:n].set(
        x.astype(jnp.float32))
    mask_row = jnp.zeros((1, n_pad), jnp.int32).at[0, :n].set(
        node_mask.astype(jnp.int32))
    y, stats = _epilogue_padded(block_n, interpret, attn2, x2,
                                w_skip.astype(jnp.float32),
                                b_skip.astype(jnp.float32), mask_row)
    return y[:n], stats


def _reference(q, k_e, v_e, receivers, edge_mask, num_nodes: int):
    """Float32 view of the segment path (the differentiable fallback)."""
    return segment_edge_attention(q, k_e, v_e, receivers, edge_mask,
                                  num_nodes).astype(jnp.float32)


def edge_attention(q, k_e, v_e, receivers, edge_mask, num_nodes: int,
                   *, block_n: int = 128, block_e: int = 128,
                   interpret: bool | None = None,
                   assume_sorted: bool = False):
    """Fused edge attention: q (N, H, C); k_e, v_e (E, H, C) edge-level
    (already source-gathered + edge-projected); receivers (E,) int;
    edge_mask (E,) bool. Returns (N, H*C) float32.

    `assume_sorted=True` skips the in-jit receiver sort; only pass it for
    batches whose edges are already receiver-sorted with masked edges at
    the tail (guaranteed by batching/pack.py). A runtime monotonicity guard
    falls back to the segment path for violating batches — slow but never
    wrong.

    Fully differentiable: forward AND backward run as fused Pallas kernels
    (flash-style recompute; no per-edge softmax residuals saved). The
    unsorted path's argsort/permutation sits OUTSIDE the custom_vjp, so
    autodiff routes dk/dv back through the gather for free.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n, heads, head_dim = q.shape
    e = k_e.shape[0]
    rcv_eff = jnp.where(edge_mask, receivers, num_nodes).astype(jnp.int32)

    def fused(q, k_s, v_s, rcv_sorted):
        return _fused_sorted(num_nodes, n, e, heads, head_dim, block_n,
                             block_e, interpret, q, k_s, v_s, rcv_sorted)

    if assume_sorted:
        is_sorted = jnp.all(jnp.diff(rcv_eff) >= 0) if e > 1 else True
        return jax.lax.cond(
            is_sorted,
            lambda q, k, v: fused(q, k, v, rcv_eff),
            lambda q, k, v: _reference(q, k, v, receivers, edge_mask,
                                       num_nodes),
            q, k_e, v_e)

    order = jnp.argsort(rcv_eff, stable=True)
    # the gathers below are differentiated by jax (scatter in reverse),
    # un-sorting dk/dv automatically
    return fused(q, k_e[order], v_e[order], rcv_eff[order])
