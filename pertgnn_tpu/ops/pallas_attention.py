"""Pallas fused edge-attention kernel (TPU).

The conv hot op is per-edge attention: score each edge against its
destination node, softmax over each destination's incoming edges, and
aggregate messages (the PyG `TransformerConv` inner loop the reference runs
on CUDA scatter kernels, /root/reference/model.py:100-104). The default XLA
path (pertgnn_tpu/models/layers.py) expresses it as gather → segment-softmax
→ segment-sum, which materializes per-edge q/k/v intermediates in HBM
between fusions.

This kernel does the whole pass in one HBM round-trip, gather-free, shaped
for the MXU:

- edges are sorted by destination (receiver) — legal because segment
  aggregation is order-free — and padded/masked edges are given receiver id
  N so they sort to the tail and can never match a real node row;
- the grid tiles (node blocks × edge blocks); for each tile the scores are a
  dense `q_block @ k_edge_blockᵀ` matmul (MXU) masked by the incidence
  `receiver[e] == node_id[n]` built from iota — the gather/scatter of the
  segment formulation becomes a masked dense matmul, the standard TPU trick
  for irregular access;
- per-destination softmax runs as FlashAttention-style online accumulation
  (running max / denominator / numerator in VMEM scratch) so nothing but
  the final (BN, H*C) output block leaves the chip;
- receiver-sorted order makes the incidence block-diagonal-ish: per node
  block, `searchsorted` bounds (prefetched scalars) skip edge blocks that
  cannot overlap, so work is O(E/N) blocks per node block, not O(E).

Backward: `jax.custom_vjp` whose bwd recomputes through the XLA segment-op
reference path (differentiable, numerically identical up to reduction
order) — fused forward, recomputed backward, no saved per-edge softmax.

Nodes with no (valid) incoming edges produce zeros, matching
`segment_softmax` (an absent destination never appears in the scatter).

When to use (measured on one TPU chip, f32): the kernel wins when
destination in-degree is high enough that a (block_n × block_e) tile is
densely populated — ~2.1x at N=512/E=1024/C=32 and ~1.5x at N=1k/E=4k —
and loses on the sparse packed-batch regime of the flagship model
(avg degree ~1.3, hidden 32: ~0.6x vs XLA's sorted-segment scatter, which
is why `ModelConfig.use_pallas_attention` defaults to False). It is the
right tool for the 5k-node giant-DAG stress shapes and wide-hidden
variants, not for the default benchmark config.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pertgnn_tpu.ops.segment import segment_edge_attention

_NEG = -1e30


def _attention_kernel(it_ref, jdx_ref, valid_ref, q_ref, k_ref, v_ref,
                      rcv_ref, out_ref, m_ref, l_ref, acc_ref, *, heads: int,
                      head_dim: int, block_n: int, block_e: int):
    t = pl.program_id(0)
    i = it_ref[t]

    # first step of a new node block → reset the online-softmax state
    @pl.when((t == 0) | (i != it_ref[jnp.maximum(t - 1, 0)]))
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(valid_ref[t] == 1)
    def _block():
        rcv = rcv_ref[0, :]  # (BE,)
        node_ids = i * block_n + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, block_e), 0)
        incidence = node_ids == rcv[None, :]  # (BN, BE)
        scale = 1.0 / float(np.sqrt(head_dim))
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            qh = q_ref[:, sl]  # (BN, C)
            kh = k_ref[:, sl]  # (BE, C)
            vh = v_ref[:, sl]
            scores = jax.lax.dot_general(
                qh, kh, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST) * scale  # (BN, BE)
            scores = jnp.where(incidence, scores, _NEG)
            m_prev = m_ref[:, h:h + 1]                         # (BN, 1)
            m_new = jnp.maximum(m_prev,
                                jnp.max(scores, axis=1, keepdims=True))
            # explicit re-mask: when a row has no incidence yet,
            # scores - m_new = 0 and exp would leak 1s
            p = jnp.where(incidence, jnp.exp(scores - m_new), 0.0)
            corr = jnp.exp(m_prev - m_new)                     # (BN, 1)
            l_ref[:, h:h + 1] = (l_ref[:, h:h + 1] * corr
                                 + jnp.sum(p, axis=1, keepdims=True))
            acc_ref[:, sl] = acc_ref[:, sl] * corr + jnp.dot(
                p, vh, preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            m_ref[:, h:h + 1] = m_new

    # last step of this node block (sentinel it[-1] = -1 closes the final
    # block) → normalize and emit
    @pl.when(it_ref[t + 1] != i)
    def _finalize():
        l = l_ref[:]  # (BN, H)
        denom = jnp.where(l > 0, l, 1.0)
        inv = (1.0 / denom)
        # broadcast per-head inverse denominator across its head_dim lanes
        out = acc_ref[:]
        for h in range(heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            out_ref[:, sl] = (out[:, sl] * inv[:, h:h + 1]).astype(
                out_ref.dtype)


def _round_up(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _pallas_forward(q, k_e, v_e, receivers, edge_mask, num_nodes: int,
                    block_n: int, block_e: int, interpret: bool,
                    assume_sorted: bool):
    """q: (N, H, C); k_e, v_e: (E, H, C); returns (N, H*C) float32."""
    n, heads, head_dim = q.shape
    e = k_e.shape[0]
    hd = heads * head_dim

    # masked edges → receiver id `num_nodes`: they sort to the tail and can
    # never equal a real node row in the incidence test
    rcv_eff = jnp.where(edge_mask, receivers, num_nodes).astype(jnp.int32)
    if assume_sorted:
        # the batch layer already receiver-sorted the edges (pack.flush)
        rcv_sorted = rcv_eff
        k_s = k_e.reshape(e, hd).astype(jnp.float32)
        v_s = v_e.reshape(e, hd).astype(jnp.float32)
    else:
        order = jnp.argsort(rcv_eff, stable=True)
        rcv_sorted = rcv_eff[order]
        k_s = k_e.reshape(e, hd)[order].astype(jnp.float32)
        v_s = v_e.reshape(e, hd)[order].astype(jnp.float32)

    n_pad = _round_up(max(n, block_n), block_n)
    e_pad = _round_up(max(e, block_e), block_e)
    q2 = jnp.zeros((n_pad, hd), jnp.float32).at[:n].set(
        q.reshape(n, hd).astype(jnp.float32))
    k_s = jnp.zeros((e_pad, hd), jnp.float32).at[:e].set(k_s)
    v_s = jnp.zeros((e_pad, hd), jnp.float32).at[:e].set(v_s)
    # pad edges also use receiver id num_nodes (matches nothing)
    rcv_row = jnp.full((1, e_pad), num_nodes, jnp.int32).at[0, :e].set(
        rcv_sorted)

    num_node_blocks = n_pad // block_n
    num_edge_blocks = e_pad // block_e
    # per node block, the edge-block range that can contain its receivers
    starts = jnp.arange(num_node_blocks, dtype=jnp.int32) * block_n
    lo = (jnp.searchsorted(rcv_sorted, starts, side="left")
          // block_e).astype(jnp.int32)
    hi_edge = jnp.searchsorted(rcv_sorted, starts + block_n, side="left")
    hi = ((hi_edge + block_e - 1) // block_e).astype(jnp.int32)
    spans = jnp.maximum(hi - lo, 0)

    # Flatten (node block, covered edge blocks) into ONE 1-D grid of active
    # steps — a node block with span s gets max(s, 1) consecutive steps (the
    # span-0 step still inits+finalizes its zero output). Total steps are
    # statically bounded: sum(spans) <= num_edge_blocks + num_node_blocks
    # (an edge block is covered once, +1 for each boundary/empty row), so
    # the grid is T = nNB + nEB with tail steps deduplicated (same block
    # indices → no DMA) and masked off via `valid`.
    steps = jnp.maximum(spans, 1)
    cum = jnp.cumsum(steps)
    total = cum[-1]
    t_max = num_node_blocks + num_edge_blocks
    t_arr = jnp.arange(t_max, dtype=jnp.int32)
    in_range = t_arr < total
    it = jnp.searchsorted(cum, t_arr, side="right").astype(jnp.int32)
    it = jnp.where(in_range, jnp.minimum(it, num_node_blocks - 1),
                   num_node_blocks - 1)
    jt = t_arr - (cum - steps)[it]                    # position within row
    jdx = jnp.clip(lo[it] + jnp.minimum(jt, jnp.maximum(spans[it] - 1, 0)),
                   0, num_edge_blocks - 1).astype(jnp.int32)
    valid = (in_range & (spans[it] > 0)
             & (jt < spans[it])).astype(jnp.int32)
    it_seq = jnp.concatenate(
        [it, jnp.full((1,), -1, jnp.int32)])          # sentinel closes last

    kernel = functools.partial(
        _attention_kernel, heads=heads, head_dim=head_dim, block_n=block_n,
        block_e=block_e)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((block_n, hd), lambda t, it, jdx, v: (it[t], 0)),
            pl.BlockSpec((block_e, hd), lambda t, it, jdx, v: (jdx[t], 0)),
            pl.BlockSpec((block_e, hd), lambda t, it, jdx, v: (jdx[t], 0)),
            pl.BlockSpec((1, block_e), lambda t, it, jdx, v: (0, jdx[t])),
        ],
        out_specs=pl.BlockSpec((block_n, hd),
                               lambda t, it, jdx, v: (it[t], 0)),
        scratch_shapes=[
            pltpu.VMEM((block_n, heads), jnp.float32),  # running max
            pltpu.VMEM((block_n, heads), jnp.float32),  # running denom
            pltpu.VMEM((block_n, hd), jnp.float32),     # running numerator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, hd), jnp.float32),
        interpret=interpret,
    )(it_seq, jdx, valid, q2, k_s, v_s, rcv_row)
    return out[:n]


def _reference(q, k_e, v_e, receivers, edge_mask, num_nodes: int):
    """Float32 view of the segment path, used for the fused bwd recompute."""
    return segment_edge_attention(q, k_e, v_e, receivers, edge_mask,
                                  num_nodes).astype(jnp.float32)


def edge_attention(q, k_e, v_e, receivers, edge_mask, num_nodes: int,
                   *, block_n: int = 128, block_e: int = 128,
                   interpret: bool | None = None,
                   assume_sorted: bool = False):
    """Fused edge attention: q (N, H, C); k_e, v_e (E, H, C) edge-level
    (already source-gathered + edge-projected); receivers (E,) int;
    edge_mask (E,) bool. Returns (N, H*C) float32.

    `assume_sorted=True` skips the in-jit receiver sort; only pass it for
    batches whose edges are already receiver-sorted with masked edges at
    the tail (guaranteed by batching/pack.py).

    Differentiable w.r.t. q/k_e/v_e; backward recomputes via the segment-op
    path (no per-edge softmax residuals saved).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    @jax.custom_vjp
    def _fused(q, k_e, v_e):
        if not assume_sorted:
            return _pallas_forward(q, k_e, v_e, receivers, edge_mask,
                                   num_nodes, block_n, block_e, interpret,
                                   assume_sorted=False)
        # Guard the PackedBatch invariant: the kernel's block-skipping
        # ranges silently drop edges on unsorted input, so verify
        # monotonicity on-device (O(E)) and fall back to the segment path
        # for violating batches — slow but never wrong.
        rcv_eff = jnp.where(edge_mask, receivers, num_nodes)
        is_sorted = jnp.all(jnp.diff(rcv_eff) >= 0)
        return jax.lax.cond(
            is_sorted,
            lambda q, k, v: _pallas_forward(
                q, k, v, receivers, edge_mask, num_nodes, block_n, block_e,
                interpret, assume_sorted=True),
            lambda q, k, v: _reference(q, k, v, receivers, edge_mask,
                                       num_nodes),
            q, k_e, v_e)

    def _fwd(q, k_e, v_e):
        return _fused(q, k_e, v_e), (q, k_e, v_e)

    def _bwd(res, g):
        q, k_e, v_e = res
        _, vjp = jax.vjp(
            lambda q, k, v: _reference(q, k, v, receivers, edge_mask,
                                       num_nodes), q, k_e, v_e)
        return vjp(g)

    _fused.defvjp(_fwd, _bwd)
    return _fused(q, k_e, v_e)
