"""Pallas fused edge-attention kernel (extension point).

The default conv hot path is gather → score → segment softmax → segment sum
(pertgnn_tpu/models/layers.py), which XLA already fuses well; this module
will hold the hand-fused Pallas TPU kernel that does the whole edge pass in
one HBM round-trip (dense-degree formulation: receiver-sorted incidence
padded to the batch max in-degree, node-blocked in VMEM).
"""

from __future__ import annotations


def edge_attention(q_e, k_e, v_e, senders, receivers, edge_mask, num_nodes):
    raise NotImplementedError(
        "the Pallas fused edge-attention kernel is not implemented yet; "
        "run with ModelConfig(use_pallas_attention=False)")
