"""XLA segment ops — the TPU replacement for PyG's CUDA scatter kernels.

The reference's hot device ops are the scatter/segment kernels behind PyG
message passing and pooling (/root/reference/model.py:100-107): per-edge
gather → per-destination softmax → scatter-add, and `global_add_pool`. On
TPU these become `jax.ops.segment_sum` / `segment_max`, which XLA lowers to
sorted-segment reductions that fuse with the surrounding elementwise work
(SURVEY.md §2.2).

All ops here are padding-aware: masked lanes cannot influence real outputs
(enforced by tests/test_model.py padding-invariance tests).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(data: jax.Array, segment_ids: jax.Array,
                num_segments: int) -> jax.Array:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_max(data: jax.Array, segment_ids: jax.Array,
                num_segments: int) -> jax.Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


def segment_softmax(scores: jax.Array, segment_ids: jax.Array,
                    num_segments: int,
                    mask: jax.Array | None = None) -> jax.Array:
    """Numerically-stable softmax over segments (e.g. per-destination-node
    over incoming edges) — the core of TransformerConv attention
    (/root/reference/model.py:100-104; PyG `softmax(alpha, index)`).

    `scores`: (E,) or (E, H). `segment_ids`: (E,) destination ids.
    `mask`: (E,) bool; masked lanes get zero weight. Segments with no valid
    lanes produce zeros (an isolated node receives no messages — matching
    PyG, where a destination with no incoming edges just never appears in
    the scatter).
    """
    neg = jnp.asarray(-jnp.inf, dtype=scores.dtype)
    if mask is not None:
        m = mask if scores.ndim == 1 else mask[:, None]
        scores = jnp.where(m, scores, neg)
    seg_max = segment_max(scores, segment_ids, num_segments)
    # empty segments have -inf max; clamp so the gather below stays finite
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = scores - seg_max[segment_ids]
    expd = jnp.exp(shifted)
    if mask is not None:
        m = mask if scores.ndim == 1 else mask[:, None]
        expd = jnp.where(m, expd, 0.0)
    denom = segment_sum(expd, segment_ids, num_segments)
    denom = jnp.where(denom > 0, denom, 1.0)
    return expd / denom[segment_ids]


def segment_edge_attention(q: jax.Array, k_e: jax.Array, v_e: jax.Array,
                           receivers: jax.Array, edge_mask: jax.Array,
                           num_nodes: int, alpha_fn=None) -> jax.Array:
    """The XLA segment-op formulation of edge attention — the single source
    of truth for the op's math (PyG TransformerConv semantics,
    /root/reference/model.py:100-104). Used by GraphTransformerLayer's
    default path AND as the recompute target of the fused Pallas kernel's
    backward (ops/pallas_attention.py), so the two can never drift apart.

    q: (N, H, C); k_e, v_e: (E, H, C) edge-level (source-gathered +
    edge-projected); returns (N, H*C). `alpha_fn` optionally transforms the
    (E, H) attention weights after the softmax (the layer passes attention
    dropout through it)."""
    n, heads, head_dim = q.shape
    q_e = q[receivers]
    scores = (q_e * k_e).sum(-1) / jnp.sqrt(
        jnp.asarray(head_dim, q.dtype))
    alpha = segment_softmax(scores, receivers, num_nodes, mask=edge_mask)
    if alpha_fn is not None:
        alpha = alpha_fn(alpha)
    msg = v_e * alpha[..., None]
    return segment_sum(msg.reshape(-1, heads * head_dim), receivers,
                       num_nodes)


def segment_mean_by_graph(node_values: jax.Array, node_graph: jax.Array,
                          weights: jax.Array, num_graphs: int) -> jax.Array:
    """Probability-weighted pooling: sum over nodes of value * weight per
    graph. With weight = pattern_prob / pattern_size this reproduces the
    reference's `x * pattern_probs / pattern_num_nodes` + `global_add_pool`
    (/root/reference/model.py:106-107) = the probability-weighted expected
    mean node embedding over the entry's topology mixture (SURVEY.md §2.3)."""
    weighted = node_values * weights[:, None]
    return segment_sum(weighted, node_graph, num_graphs)
