"""Blocked-dense edge attention — segment ops as masked dense matmuls.

"Fast Training of Sparse Graph Neural Networks on Dense Hardware"
(arXiv:1906.11786, PAPERS.md) observes that on systolic hardware the
sparse gather / segment-softmax / scatter formulation of message passing
should be recast as DENSE matmuls against an explicit (node, edge)
incidence mask: the MXU runs a masked `q @ k_edgeᵀ` at full tile
utilization, while a sorted-segment reduction serializes through the
VPU. For this workload's SMALL per-topology graphs (packed-batch node /
edge counts in the hundreds), the quadratic incidence matrix is tiny —
a few 128-aligned tiles — so the dense recast is a straight win; for
large batches it loses quadratically, which is why the layer gates this
impl on `ModelConfig.blocked_dense_max_cells` and falls back to the
segment path loudly above it.

Everything here is plain XLA (no Pallas): the point IS that dense
einsums + masks lower to stock MXU GEMMs, differentiable by autodiff
for free, with one compiled program per 128-aligned shape bucket
(`_pad_up` rounds node/edge counts so nearby request shapes share an
executable — the serve ladder's discipline applied to the op).

Numerics match `ops.segment.segment_edge_attention` exactly in
formulation: masked lanes get -inf scores, empty destinations produce
zeros (an isolated node never appears in the scatter), and padding can
never alias a real row (masked edges get receiver id -1, below any real
node id).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG = -1e30


def _pad_up(v: int, m: int) -> int:
    return ((max(v, 1) + m - 1) // m) * m


def dense_cells(num_nodes: int, num_edges: int, block_n: int = 128,
                block_e: int = 128) -> int:
    """Incidence-matrix cells (per head) the dense formulation would
    materialize for this shape bucket — the quantity
    `ModelConfig.blocked_dense_max_cells` bounds."""
    return _pad_up(num_nodes, block_n) * _pad_up(num_edges, block_e)


def fits(num_nodes: int, num_edges: int, max_cells: int,
         block_n: int = 128, block_e: int = 128) -> bool:
    """Whether the blocked-dense recast is admissible for this (static)
    shape bucket. The caller owns the fallback (log + count — never a
    silent swallow; tools/check_excepts.py discipline)."""
    return dense_cells(num_nodes, num_edges, block_n, block_e) <= max_cells


def blocked_dense_edge_attention(q: jax.Array, k_e: jax.Array,
                                 v_e: jax.Array, receivers: jax.Array,
                                 edge_mask: jax.Array, num_nodes: int,
                                 *, block_n: int = 128,
                                 block_e: int = 128) -> jax.Array:
    """Edge attention as masked dense matmuls over one shape bucket.

    q: (N, H, C); k_e, v_e: (E, H, C) edge-level (source-gathered +
    edge-projected); receivers (E,) int; edge_mask (E,) bool. Returns
    (N, H*C) in the COMPUTE dtype — f32 for f32 inputs (the same
    contract as `segment_edge_attention`, the single source of truth
    for the math, asserted by tests/test_pallas_attention.py parity
    and benchmarks/kernel_bench.py), bf16 for bf16 inputs: the
    quantized serve tiers run bf16 GEMMs through the MXU, and
    force-casting here would silently serve f32 matmuls at bf16's
    advertised cost (caught by graftaudit's dtype-flow pass — the
    first repo-wide run found exactly that).
    """
    n, heads, head_dim = q.shape
    e = k_e.shape[0]
    n_pad = _pad_up(n, block_n)
    e_pad = _pad_up(e, block_e)
    # bf16 stays bf16 (MXU-native); everything else computes f32 as
    # before — the segment path makes the same dtype choice via the
    # layer's Dense(dtype=...) projections
    cdt = q.dtype if q.dtype == jnp.bfloat16 else jnp.float32

    qf = jnp.zeros((n_pad, heads, head_dim), cdt).at[:n].set(
        q.astype(cdt))
    kf = jnp.zeros((e_pad, heads, head_dim), cdt).at[:e].set(
        k_e.astype(cdt))
    vf = jnp.zeros((e_pad, heads, head_dim), cdt).at[:e].set(
        v_e.astype(cdt))
    # masked/padding edges get receiver -1: no node id (0..n_pad-1) can
    # match, so they are unobservable by construction
    rcv = jnp.full((e_pad,), -1, jnp.int32).at[:e].set(
        jnp.where(edge_mask, receivers, -1).astype(jnp.int32))
    incidence = (jnp.arange(n_pad, dtype=jnp.int32)[:, None]
                 == rcv[None, :])  # (N_pad, E_pad)

    # in cdt: an f32 scale would re-promote the whole bf16 chain (and
    # with it the second GEMM) right after the bf16 score GEMM
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, cdt))
    # the dense recast: scores are ONE batched GEMM against every edge,
    # masked by incidence — gather/scatter becomes matmul + where
    scores = jnp.einsum("nhc,ehc->hne", qf, kf,
                        precision=jax.lax.Precision.HIGHEST) * scale
    scores = jnp.where(incidence[None], scores, _NEG)
    smax = jnp.max(scores, axis=2, keepdims=True)
    # empty destinations (all -inf/_NEG row): clamp like segment_softmax
    smax = jnp.where(smax > 0.5 * _NEG, smax, 0.0)
    p = jnp.where(incidence[None], jnp.exp(scores - smax), 0.0)
    denom = jnp.sum(p, axis=2, keepdims=True)
    alpha = p / jnp.where(denom > 0, denom, 1.0)  # (H, N_pad, E_pad)
    out = jnp.einsum("hne,ehc->nhc", alpha, vf,
                     precision=jax.lax.Precision.HIGHEST)
    return out[:n].reshape(n, heads * head_dim)
