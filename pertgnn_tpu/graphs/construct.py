"""Trace → graph construction (host-side, numpy).

Rebuilds the semantics of the reference's `GraphConstruct`
(/root/reference/misc.py:72-370) without torch:

- edge sanitizing: the exact order-sensitive sequence of
  self-loop removal → rpcid dedup (keep first) → drop edges into root →
  (um, dm) dedup (keep last) → unordered-pair dedup (keep first), a
  cycle-breaking heuristic (misc.py:87-105);
- root detection: um of the row with maximal |rt| AND minimal timestamp,
  evaluated on the UNsanitized trace (misc.py:74, 138-142);
- span graph: nodes = microservices compacted via sorted unique
  (misc.py:196-198), edge features [interface, rpctype] (misc.py:177-181);
- PERT graph: activity-on-node expansion — a caller with k outgoing calls
  becomes a chain of 2k+1 stage nodes joined by intra-ms edges with attr
  [0, 0, 1, 1] (misc.py:240-250); pure callees get one node (misc.py:251-257);
  per caller, call/return events sorted by time emit inter-ms edges
  (call: stages[um][i] → stages[dm][0], attr [iface, rpctype, 1, 0];
  return: stages[dm][-1] → stages[um][i+1], attr [iface, rpctype, 0, 0])
  (misc.py:272-302);
- node depth: min depth from the root, unreachable → 0, normalized by the
  max (misc.py:52-69, 144-175) — computed with an ITERATIVE BFS rather than
  the reference's recursive DFS, which would overflow the Python stack on
  the 5k-node synthetic DAG stress config.

Node-numbering notes (graph-isomorphic, features follow the ids, so these
choices are unobservable to the model): the PERT caller order follows the
reference's `value_counts()` (count-descending, first-appearance tie-break,
misc.py:240); leaf callees are emitted in sorted order where the reference
iterates a Python set (misc.py:251-254).

Depth-dtype divergence (documented in PARITY.md): the reference stores the
normalized min-depth as torch.long, truncating every value except the deepest
node's 1.0 to 0 (misc.py:173, 215, 368); since the released model never
consumes node_depth, we keep the float value so the `use_node_depth`
capability option receives real information.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np
import pandas as pd

from pertgnn_tpu.ingest.assemble import TraceTable
from pertgnn_tpu.ingest.preprocess import PreprocessResult


@dataclasses.dataclass
class GraphSpec:
    """One runtime pattern's structure, as flat numpy arrays.

    Node features are NOT stored — like the reference (preprocess.py:333-340
    persists only structure), features are attached at batch-build time from
    the resource table, because they depend on the trace's time bucket.
    """

    senders: np.ndarray     # (E,) int32 — edge source node
    receivers: np.ndarray   # (E,) int32 — edge destination node
    edge_attr: np.ndarray   # (E, 2) span / (E, 4) pert int32:
                            # [interface, rpctype(, call_ind, same_ms_ind)]
    ms_id: np.ndarray       # (N,) int32 — microservice id per node
    node_depth: np.ndarray  # (N,) float32 — normalized min depth from root
    num_nodes: int
    # (E,) float32 per-edge span duration |rt| in ms, or None (= zeros).
    # The reference computes these for span graphs but never persists them
    # (misc.py:183-186 vs preprocess.py:333-340 — dead output); here they
    # are carried through and exposed to the model behind
    # ModelConfig.use_edge_durations (SURVEY.md §2.3 "declared-but-dead").
    # PERT graphs get None: the reference's PERT duration machinery is
    # commented out in full (misc.py:259-269, 321-361).
    edge_durations: np.ndarray | None = None

    @property
    def num_edges(self) -> int:
        return len(self.senders)


def find_root(trace_df: pd.DataFrame):
    """Root microservice: um of the row with max |rt| and min timestamp
    (/root/reference/misc.py:138-142).

    Precondition (same as the reference's): such a row exists. Entry
    filtering guarantees it for every trace that reaches graph
    construction — traces whose min-timestamp row doesn't carry the max
    |rt| are dropped by `ingest.preprocess.detect_entries` (this repo's
    implementation of the reference's
    `filter_traces_with_missing_entry_and_get_delay`,
    preprocess.py:111-115); on raw unfiltered input this raises
    IndexError exactly where the reference would."""
    abs_rt = trace_df["rt"].abs()
    mask = (abs_rt == abs_rt.max()) & (
        trace_df["timestamp"] == trace_df["timestamp"].min())
    return trace_df.loc[mask, "um"].iloc[0]


def sanitize_edges(trace_df: pd.DataFrame, root) -> pd.DataFrame:
    """The reference's `drop_wrong_edges` sequence (misc.py:87-105)."""
    df = trace_df[trace_df["um"] != trace_df["dm"]]
    df = df.drop_duplicates(subset="rpcid", keep="first")
    df = df[df["dm"] != root]
    df = df.drop_duplicates(subset=["um", "dm"], keep="last")
    # unordered-pair dedup: keeps the first of any (a, b)/(b, a) pair
    a = df["um"].to_numpy()
    b = df["dm"].to_numpy()
    lo = np.minimum(a, b)
    hi = np.maximum(a, b)
    pair = pd.DataFrame({"lo": lo, "hi": hi}, index=df.index)
    keep = ~pair.duplicated(subset=["lo", "hi"], keep="first")
    return df[keep.values]


def find_roots_vectorized(spans: pd.DataFrame) -> pd.Series:
    """Per-trace root (find_root semantics) for all traces in one pass."""
    abs_rt = spans["rt"].abs()
    g = spans.groupby("traceid")
    is_cand = (abs_rt == abs_rt.groupby(spans["traceid"]).transform("max")) \
        & (spans["timestamp"] == g["timestamp"].transform("min"))
    cand = spans[is_cand]
    return cand.groupby("traceid")["um"].first()


def sanitize_traces(spans: pd.DataFrame) -> tuple[pd.DataFrame, pd.Series]:
    """`sanitize_edges` for MANY traces in vectorized passes.

    Returns (sanitized rows for all traces, per-trace root). Exact parity
    with the per-trace function (tested), each stage evaluated on the
    survivors of the previous one, as in the reference's sequential
    drop_wrong_edges (misc.py:87-105).
    """
    roots = find_roots_vectorized(spans)
    df = spans[spans["um"] != spans["dm"]]
    df = df[~df.duplicated(subset=["traceid", "rpcid"], keep="first")]
    df = df[df["dm"] != df["traceid"].map(roots)]
    df = df[~df.duplicated(subset=["traceid", "um", "dm"], keep="last")]
    lo = np.minimum(df["um"].to_numpy(), df["dm"].to_numpy())
    hi = np.maximum(df["um"].to_numpy(), df["dm"].to_numpy())
    pair = pd.DataFrame({"t": df["traceid"].to_numpy(), "lo": lo, "hi": hi})
    df = df[~pair.duplicated(keep="first").to_numpy()]
    return df, roots


def min_depth_from_root(num_nodes: int, senders: np.ndarray,
                        receivers: np.ndarray, root: int) -> np.ndarray:
    """Iterative BFS min-depth; unreachable nodes get 0
    (reference: inf → 0, misc.py:160)."""
    adj: list[list[int]] = [[] for _ in range(num_nodes)]
    for s, r in zip(senders.tolist(), receivers.tolist()):
        adj[s].append(r)
    depth = np.full(num_nodes, -1, dtype=np.int64)
    depth[root] = 0
    q = deque([root])
    while q:
        v = q.popleft()
        for w in adj[v]:
            if depth[w] < 0:
                depth[w] = depth[v] + 1
                q.append(w)
    depth[depth < 0] = 0
    return depth


def _normalized_depth(depth: np.ndarray) -> np.ndarray:
    denom = depth.max() if depth.max() > 0 else 1
    return (depth / denom).astype(np.float32)


def build_span_graph(trace_df: pd.DataFrame, *, sanitized: pd.DataFrame
                     | None = None, root=None) -> GraphSpec:
    """Span graph: one node per microservice (misc.py:190-219)."""
    if root is None:
        root = find_root(trace_df)
    df = sanitize_edges(trace_df, root) if sanitized is None else sanitized
    um = df["um"].to_numpy(dtype=np.int64)
    dm = df["dm"].to_numpy(dtype=np.int64)
    edge_nodes = np.stack([um, dm])
    # sorted unique compaction, same as torch.unique(return_inverse=True)
    # (misc.py:196-198)
    unique_ms, inverse = np.unique(edge_nodes, return_inverse=True)
    edge_index = inverse.reshape(edge_nodes.shape)
    num_nodes = len(unique_ms)
    # The sanitizer can drop every row mentioning the root (e.g. a duplicate
    # rpcid on the entry row); the reference crashes with KeyError there
    # (misc.py:204) — we degrade to all-zero depths instead (PARITY.md).
    root_pos = int(np.searchsorted(unique_ms, root))
    if root_pos < num_nodes and unique_ms[root_pos] == root:
        depth = min_depth_from_root(num_nodes, edge_index[0], edge_index[1],
                                    root_pos)
    else:
        depth = np.zeros(num_nodes, dtype=np.int64)
    edge_attr = df[["interface", "rpctype"]].to_numpy(dtype=np.int32)
    return GraphSpec(
        senders=edge_index[0].astype(np.int32),
        receivers=edge_index[1].astype(np.int32),
        edge_attr=edge_attr,
        ms_id=unique_ms.astype(np.int32),
        node_depth=_normalized_depth(depth),
        num_nodes=num_nodes,
        edge_durations=df["rt"].abs().to_numpy(
            dtype=np.float32),  # misc.py:183-186
    )


def _caller_order(um: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unique callers ordered like pandas `value_counts()`: count-descending
    with first-appearance tie-break (misc.py:240). Returns (callers, counts)."""
    first_order, counts_in_order = [], []
    seen: dict[int, int] = {}
    for v in um.tolist():
        if v in seen:
            seen[v] += 1
        else:
            seen[v] = 1
            first_order.append(v)
    counts = np.array([seen[v] for v in first_order], dtype=np.int64)
    order = np.argsort(-counts, kind="stable")
    callers = np.array(first_order, dtype=np.int64)[order]
    return callers, counts[order]


def build_pert_graph(trace_df: pd.DataFrame, *, sanitized: pd.DataFrame
                     | None = None, root=None) -> GraphSpec:
    """Activity-on-node PERT graph (misc.py:221-370).

    NOT guaranteed acyclic: when the sanitized call graph is non-tree
    (a callee with multiple callers), shared stage chains + call/return
    edges can form cycles — same as the reference, whose max-depth DFS is
    disabled precisely "due to cycles" (misc.py:119-134). Everything
    downstream is cycle-safe: min_depth_from_root is an iterative BFS and
    the model is attention message-passing (no topological order
    assumed). Pinned by tests/test_graphs_property.py."""
    if root is None:
        root = find_root(trace_df)
    df = sanitize_edges(trace_df, root) if sanitized is None else sanitized

    um = df["um"].to_numpy(dtype=np.int64)
    callers, counts = _caller_order(um)

    stages: dict[int, np.ndarray] = {}
    ms_id: list[int] = []
    senders: list[int] = []
    receivers: list[int] = []
    edge_attr: list[list[int]] = []
    num_nodes = 0
    for ms, k in zip(callers.tolist(), counts.tolist()):
        n_stages = 2 * k + 1
        stages[ms] = np.arange(n_stages) + num_nodes
        for prev, cur in zip(stages[ms], stages[ms][1:]):
            senders.append(int(prev))
            receivers.append(int(cur))
            edge_attr.append([0, 0, 1, 1])
        num_nodes += n_stages
        ms_id.extend([ms] * n_stages)
    leaves = sorted(set(df["dm"].tolist()) - set(df["um"].tolist()))
    for leaf in leaves:
        stages[leaf] = np.array([num_nodes])
        ms_id.append(leaf)
        num_nodes += 1

    # per-caller call/return events ordered by time (misc.py:272-302);
    # groupby("um") iterates callers in sorted order with rows in original
    # (timestamp) order, and Python's stable sort keeps ties in emission
    # order (start before end for the same row)
    for caller, group in df.groupby("um", sort=True):
        events = []
        for _, row in group.iterrows():
            events.append((row["timestamp"], 0, row["dm"],
                           int(row["interface"]), int(row["rpctype"])))
            events.append((row["endTimestamp"], 1, row["dm"], 0, 0))
        events.sort(key=lambda t: t[0])
        for i, (_, is_end, dm, iface, rpctype) in enumerate(events):
            if is_end:
                senders.append(int(stages[dm][-1]))
                receivers.append(int(stages[caller][i + 1]))
                edge_attr.append([iface, rpctype, 0, 0])
            else:
                senders.append(int(stages[caller][i]))
                receivers.append(int(stages[dm][0]))
                edge_attr.append([iface, rpctype, 1, 0])

    senders_a = np.array(senders, dtype=np.int32)
    receivers_a = np.array(receivers, dtype=np.int32)
    if root in stages:
        depth = min_depth_from_root(num_nodes, senders_a, receivers_a,
                                    int(stages[root][0]))
    else:
        # root sanitized away entirely; reference would KeyError (misc.py:311)
        depth = np.zeros(num_nodes, dtype=np.int64)
    return GraphSpec(
        senders=senders_a,
        receivers=receivers_a,
        edge_attr=np.array(edge_attr, dtype=np.int32).reshape(-1, 4),
        ms_id=np.array(ms_id, dtype=np.int32),
        node_depth=_normalized_depth(depth),
        num_nodes=num_nodes,
    )


def build_runtime_graphs(pre: PreprocessResult, table: TraceTable,
                         graph_type: str = "span",
                         use_native: bool | None = None,
                         ) -> dict[int, GraphSpec]:
    """One GraphSpec per runtime pattern, built from its representative trace
    (the reference builds each pattern's graph on first sight,
    preprocess.py:317-318, 343-344).

    `use_native`: force the C++ fast path on/off; None = auto (use it when
    the shared library is available).
    """
    if graph_type not in ("span", "pert"):
        raise ValueError(f"graph_type must be span|pert, got {graph_type!r}")
    if use_native is None or use_native:
        try:
            from pertgnn_tpu.native import bindings as native
            if native.available():
                return native.build_runtime_graphs(pre, table, graph_type)
            if use_native:
                raise RuntimeError("native library not available")
        except (ImportError, OSError, RuntimeError):
            if use_native:
                raise  # explicitly requested: surface the real error
    build = build_span_graph if graph_type == "span" else build_pert_graph
    # only representative traces are consumed — filter before the groupby
    # split so we never materialize per-trace frames for the other ~100k;
    # sanitize all of them in one vectorized pass
    reps = set(table.runtime2trace.values())
    rep_spans = pre.spans[pre.spans["traceid"].isin(reps)]
    sanitized, roots = sanitize_traces(rep_spans)
    by_trace = {tid: grp for tid, grp in sanitized.groupby("traceid")}
    empty = sanitized.iloc[:0]
    out: dict[int, GraphSpec] = {}
    for runtime_id, traceid in table.runtime2trace.items():
        out[runtime_id] = build(None, sanitized=by_trace.get(traceid, empty),
                                root=roots[traceid])
    return out
