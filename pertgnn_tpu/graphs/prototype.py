"""Cluster-prototype graphs (the reference's legacy kmeans pipeline).

The reference ships an abandoned clustering pipeline
(/root/reference/get_data_list.py, orphaned helpers misc.py:23-49) that
represented each trace *cluster* by a prototype DAG: the union of the
cluster's span edges, weighted by how often each (um, dm) edge occurs
across the cluster's traces (`get_dag_prototype_from_trace_cluster`,
misc.py:23-45 — only the "graph_union" merge method was ever implemented;
"graph_dtw" exits). Its inputs (`cluster2graph.pt`, `tr2data.joblib`) are
produced by no current code (SURVEY.md §2.1 "Dead legacy script"), so the
live pipeline never calls it — but it is a real capability of the codebase,
re-provided here in clean numpy for anyone migrating a clustering-based
workflow.

`merge_label_spaces` mirrors `update_max_kmeans_label` (misc.py:48-49): the
running offset used to keep per-entry kmeans label spaces disjoint when
clusters from several entries land in one table.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd


@dataclasses.dataclass(frozen=True)
class PrototypeGraph:
    """Weighted union DAG of a trace cluster's span edges."""

    senders: np.ndarray      # (E,) int64 — um of each distinct edge
    receivers: np.ndarray    # (E,) int64 — dm
    edge_weight: np.ndarray  # (E,) float32 — occurrence count over cluster

    @property
    def num_edges(self) -> int:
        return len(self.senders)


def dag_prototype_from_cluster(cluster_spans: pd.DataFrame,
                               merge_method: str = "graph_union",
                               ) -> PrototypeGraph:
    """Prototype DAG for one cluster of traces (misc.py:23-45 semantics).

    `cluster_spans`: span rows of every trace in the cluster (needs `um`,
    `dm` columns). Distinct (um, dm) edges are weighted by their occurrence
    count, ordered count-descending with first-appearance tie-break — the
    reference's `value_counts()` ordering.
    """
    if merge_method != "graph_union":
        # the reference sys.exit()s on anything else (misc.py:39-43)
        raise ValueError(
            f"merge method {merge_method!r} is not supported "
            "(only 'graph_union'; the reference's 'graph_dtw' was never "
            "implemented)")
    counts = cluster_spans[["um", "dm"]].value_counts()
    edges = counts.index.to_frame(index=False)
    return PrototypeGraph(
        senders=edges["um"].to_numpy(dtype=np.int64),
        receivers=edges["dm"].to_numpy(dtype=np.int64),
        edge_weight=counts.to_numpy(dtype=np.float32),
    )


def merge_label_spaces(kmeans_labels: np.ndarray,
                       max_label_so_far: int) -> int:
    """Next label offset after appending a cluster table whose labels are
    `kmeans_labels` (misc.py:48-49)."""
    return int(np.max(kmeans_labels)) + max_label_so_far + 1
