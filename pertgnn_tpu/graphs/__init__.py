from pertgnn_tpu.graphs.construct import (
    GraphSpec,
    sanitize_edges,
    find_root,
    build_span_graph,
    build_pert_graph,
    build_runtime_graphs,
    min_depth_from_root,
)
