"""MetricsWriter: append-only, line-buffered, schema-versioned JSONL.

The durability contract is one event per line with the file opened
line-buffered: every completed event reaches the OS on the newline, so a
SIGKILL'd run loses at most one partial final line (which the schema
reader skips as the crash tail). No background flusher thread, no
buffering policy to tune — crash-safety by construction.

Size-based rotation (``rotate_mb`` > 0): once the current file exceeds
the cap the writer switches to a fresh ``...partN.jsonl`` sibling —
NEVER renaming the old one (tail -f readers and the append-only contract
survive), keeping the ``.jsonl`` extension so every existing
glob-the-dir consumer (benches, tools/graftscope) still sees all parts.
Off by default: rotation trades one unbounded file for a part sequence,
which only long-lived fleet/stream runs need.

An optional TensorBoard sink mirrors scalar events (tensorboardX when
importable; absent -> the option is a logged no-op, never an import
error: the container may not ship it)."""

from __future__ import annotations

import json
import logging
import numbers
import os
import threading
import time

from pertgnn_tpu.telemetry.schema import SCHEMA_VERSION

log = logging.getLogger(__name__)


def _num(name: str, x):
    """Coerce a metric value to a plain int/float AT WRITE TIME — a
    numpy scalar must fail (or convert) at the emitting call site, not
    poison the stream for the strict reader (json default=str would
    silently stringify it)."""
    if isinstance(x, bool) or not isinstance(x, numbers.Real):
        raise TypeError(f"event {name!r}: non-numeric value {x!r}")
    return int(x) if isinstance(x, numbers.Integral) else float(x)


def _tag(v):
    """Tags are scalar dimensions: keep str/bool/None, normalize any
    Real (incl. numpy scalars) to int/float, stringify the rest."""
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, numbers.Real):
        return float(v)
    return str(v)


def _process_index() -> int:
    """jax.process_index() if a backend is already up, else 0. Never
    initializes a backend: telemetry must not be the first thing that
    dials a (possibly wedged) device transport — cli/common.py
    apply_platform_env owns backend selection."""
    try:
        import jax
        from jax._src import xla_bridge
        if getattr(xla_bridge, "_backends", None):
            return jax.process_index()
    except Exception:
        log.debug("no initialized backend to read a process index from; "
                  "stamping pi=0")
    return 0


class MetricsWriter:
    """Structured scalar events -> one pid-unique JSONL file.

    Thread-safe: the serve path writes from the microbatch worker and
    client threads concurrently; a lock serializes line emission (events
    are small — contention is negligible next to a device dispatch)."""

    def __init__(self, directory: str, *, tensorboard: bool = False,
                 run_meta: dict | None = None, rotate_mb: float = 0.0):
        os.makedirs(directory, exist_ok=True)
        self.pid = os.getpid()
        self.process_index = _process_index()
        # process_index + hostname + pid in the name: multi-host runs on
        # a shared telemetry_dir and supervisor restarts append to
        # distinct files, never interleave. The hostname keeps the
        # guarantee even if process-index detection degrades to 0 (it is
        # best-effort — _process_index): two hosts with equal pids still
        # get distinct files.
        import socket
        host = socket.gethostname().split(".")[0] or "host"
        self._stem = os.path.join(
            directory,
            f"telemetry-p{self.process_index}-{host}-{self.pid}")
        self.path = f"{self._stem}.jsonl"
        self._rotate_bytes = int(max(rotate_mb, 0.0) * 2 ** 20)
        self._part = 0
        self._bytes = 0
        self._f = open(self.path, "a", buffering=1)
        self._lock = threading.Lock()
        self._closed = False
        self._tb = None
        self._tb_steps: dict[str, int] = {}
        if tensorboard:
            self._tb = self._open_tensorboard(directory)
        self.write("meta", "run_start", fields={
            "schema_version": SCHEMA_VERSION,
            "argv": list(__import__("sys").argv),
            "start_unix_time": time.time(),
            **(run_meta or {}),
        })

    @staticmethod
    def _open_tensorboard(directory: str):
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            log.warning("tensorboard sink requested but tensorboardX is "
                        "not installed — JSONL only")
            return None
        return SummaryWriter(logdir=os.path.join(directory, "tb"))

    def write(self, kind: str, name: str, value: float | None = None,
              dur_ms: float | None = None, tags: dict | None = None,
              fields: dict | None = None,
              trace: dict | None = None) -> None:
        """One event. ``trace`` (spans only) carries the v2 trace
        identity: ``trace_id`` / ``span_id`` / ``parent_span_id`` plus
        the span-start monotonic stamp ``tm0`` (telemetry/tracing.py
        builds it; graftscope consumes it)."""
        ev: dict = {"v": SCHEMA_VERSION, "t": time.time(),
                    "tm": time.monotonic(), "pid": self.pid,
                    "pi": self.process_index, "kind": kind, "name": name}
        if value is not None:
            ev["value"] = _num(name, value)
        if dur_ms is not None:
            ev["dur_ms"] = _num(name, dur_ms)
        if trace:
            ev.update(trace)
        if tags:
            ev["tags"] = {k: _tag(v) for k, v in tags.items()}
        if fields is not None:
            ev["fields"] = fields
        line = json.dumps(ev, default=str)
        with self._lock:
            if self._closed:
                return
            self._f.write(line + "\n")
            if self._rotate_bytes:
                self._bytes += len(line) + 1
                if self._bytes >= self._rotate_bytes:
                    self._rotate_locked()
            if self._tb is not None:
                self._to_tensorboard(kind, name, value, dur_ms)

    def _rotate_locked(self) -> None:
        """Switch to the next ``.partN.jsonl`` sibling (caller holds the
        lock). The closed part keeps its name — append-only means no
        renames, ever — and the fresh part opens with a ``rotate`` meta
        stamping its index so a reader can order a part sequence without
        trusting filesystem mtimes."""
        self._f.flush()
        self._f.close()
        self._part += 1
        self._bytes = 0
        self.path = f"{self._stem}.part{self._part}.jsonl"
        self._f = open(self.path, "a", buffering=1)
        ev = {"v": SCHEMA_VERSION, "t": time.time(),
              "tm": time.monotonic(), "pid": self.pid,
              "pi": self.process_index, "kind": "meta", "name": "rotate",
              "fields": {"part": self._part,
                         "schema_version": SCHEMA_VERSION}}
        line = json.dumps(ev, default=str)
        self._f.write(line + "\n")
        self._bytes += len(line) + 1

    def _to_tensorboard(self, kind, name, value, dur_ms) -> None:
        scalar = dur_ms if kind == "span" else value
        if scalar is None:
            return
        step = self._tb_steps.get(name, 0)
        self._tb_steps[name] = step + 1
        try:
            self._tb.add_scalar(name, float(scalar), step)
        except Exception:
            log.exception("tensorboard sink failed for %s; disabling", name)
            self._tb = None

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._f.flush()
                if self._tb is not None:
                    self._tb.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._f.flush()
            self._f.close()
            if self._tb is not None:
                self._tb.close()
