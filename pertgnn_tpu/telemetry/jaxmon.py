"""Forward jax.monitoring events (compiles, tracing) onto the bus.

XLA compilation is the serving engine's tail-latency enemy and the train
loop's startup cost; jax already announces every compile through
``jax.monitoring`` — this module is the listener that turns those
announcements into schema events instead of letting them evaporate.

Event mapping (names keep jax's own event keys, prefixed ``jax``):

- plain events      -> counter  ``jax<event>``  (value 1)
- duration events   -> histogram ``jax<event>`` in the event's NATIVE
  units — true durations are seconds (jax's ``*_duration_secs`` keys say
  so in the name), but jax also routes non-durations (bytes_per_sec,
  future counts) through the same listener, so no unit rewrite is safe

jax.monitoring has no public unregister; the installer returns an
``uninstall()`` that uses the private helpers when present and otherwise
flips a dead-switch flag so a stale listener never writes to a closed
bus (listener registries are process-global)."""

from __future__ import annotations

import contextlib
import logging

log = logging.getLogger(__name__)

# jax.monitoring event keys announcing persistent-compilation-cache
# behavior (jax/_src/compilation_cache.py) — the ground truth for
# "did this process actually compile, or replay from disk?".
XLA_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
XLA_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


@contextlib.contextmanager
def watch_xla_cache():
    """Count XLA persistent-compilation-cache hits/misses inside a
    ``with`` block: yields a dict whose ``hits``/``misses`` are live.

    This is how the AOT layer (pertgnn_tpu/aot/) distinguishes a fresh
    XLA compile from a disk replay — jit's API looks identical either
    way. Only meaningful when the persistent cache is enabled
    (aot.enable_compile_cache); with it off, neither event ever fires
    and both counts stay 0."""
    import jax.monitoring as mon

    counts = {"hits": 0, "misses": 0}
    alive = {"on": True}

    def on_event(event, **kw):
        if not alive["on"]:
            return
        if event == XLA_CACHE_HIT_EVENT:
            counts["hits"] += 1
        elif event == XLA_CACHE_MISS_EVENT:
            counts["misses"] += 1

    mon.register_event_listener(on_event)
    try:
        yield counts
    finally:
        alive["on"] = False
        try:
            from jax._src import monitoring as _m
            _m._unregister_event_listener_by_callback(on_event)
        except Exception:
            # same story as uninstall() below: the dead-switch already
            # guarantees the counts stop moving
            log.debug("could not unregister xla cache watcher; listener "
                      "left registered but disabled")


def install_jax_monitoring(bus):
    """Register bus-forwarding listeners; returns uninstall()."""
    import jax.monitoring as mon

    alive = {"on": True}

    def on_event(event, **kw):
        if alive["on"]:
            # dynamic by nature: jax.monitoring enumerates the event
            # names upstream (docs/OBSERVABILITY.md "jax internals" —
            # all land under the `jax/...` prefix)
            bus.counter("jax" + str(event))  # graftlint: allow-telemetry-drift

    def on_duration(event, duration_secs, **kw):
        if alive["on"]:
            bus.histogram("jax" + str(event),  # graftlint: allow-telemetry-drift
                          float(duration_secs))

    mon.register_event_listener(on_event)
    mon.register_event_duration_secs_listener(on_duration)

    def uninstall():
        alive["on"] = False
        try:
            from jax._src import monitoring as _m
            _m._unregister_event_listener_by_callback(on_event)
            _m._unregister_event_duration_listener_by_callback(on_duration)
        except Exception:
            # private helpers moved: the dead-switch above already
            # guarantees no further writes — leaking two inert closures
            # in a process-global list is acceptable
            log.debug("jax.monitoring unregister helpers unavailable; "
                      "listeners left registered but disabled")

    return uninstall
