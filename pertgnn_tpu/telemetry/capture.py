"""graftprobe — the journaled sub-minute capture state machine.

The axon relay grants TPU windows measured in SECONDS (BENCH r03–r05:
every gated round fell back to CPU; the one tunnel window of round 5
closed in under a minute with the bench wedged inside its first device
ops). A monolithic run-or-wedge capture cannot land a measurement in
that regime, so the capture decomposes into a PLAN of small resumable
stages — backend probe → arena warm → precompile → cost analysis →
torch baseline → per-window measured fit/ceiling/compact steps — and
every completed stage persists one atomic record to an append-only
journal. A window closing mid-stage loses only the in-flight step;
``bench.py --capture`` re-enters at the first incomplete stage and
never re-runs a journaled one; ``benchmarks/adjudicate.py --stitch``
assembles a valid interleaved measurement out of the fragments.

The journal rides the telemetry schema-v2 event format (one JSON
object per line, ``validate_event``-clean, wall + monotonic stamps,
pid): the same crash-at-line-granularity durability contract as
telemetry/writer.py, at a FIXED path so re-entry can find it. Record
names:

- ``capture.run``   — one per process entry: commit, config
  fingerprint, backend/device_kind (the stitch-compatibility identity).
- ``capture.stage`` — the state machine: ``fields.stage`` +
  ``fields.status`` in {``started``, ``done``, ``aborted``,
  ``wedged``}; ``done`` records carry the stage's metrics (and, for
  measured windows, a per-window roofline attribution row + a
  ``device.mem.*`` sample).
- ``capture.probe`` — one per watcher probe attempt (timestamp,
  outcome, latency) so adjudicate.py can report tunnel-availability
  statistics instead of folklore.

Wedge diagnosis: each stage runs under a watchdog — SIGALRM at
``watchdog_s`` journals the stage ``wedged`` with an all-thread
``faulthandler`` dump and raises (the interruptible case), and a
C-level ``faulthandler.dump_traceback_later(2x, exit=True)`` backstop
dumps and kills the process when the main thread is stuck inside an
uninterruptible PJRT call (the observed relay failure mode — a blocked
C call never runs Python signal handlers). A process killed that hard
leaves a ``started`` record with no terminal status; the next entry
journals it ``wedged`` (``reason="orphaned_start"``) so the stage name
survives for the watcher's log and the stage re-runs.

Stage catalog order note: the ISSUE names "probe → precompile → arena
warm", but ``aot.precompile.precompile_train`` consumes the built
dataset, so the executable order is probe → arena_warm → precompile —
the precompile stage compiles against the warmed arena.

Pure-host module: no jax / numpy at import time (tpu_watch.sh journals
probe attempts from a bare python one-liner between polls).
"""

from __future__ import annotations

import faulthandler
import json
import logging
import os
import signal
import sys
import time

from pertgnn_tpu.store import durable
from pertgnn_tpu.store.durable import StoreLock
from pertgnn_tpu.telemetry.schema import SCHEMA_VERSION, validate_event

log = logging.getLogger(__name__)


def record_crc(ev: dict) -> int:
    """CRC32C of the canonical dump of ``ev`` minus its ``crc`` key —
    what ``append`` stamps into each journal record and ``records()``
    / graftvault scrub verify, so interior bit-rot (as opposed to the
    expected torn final line) is detected instead of stitched."""
    body = {k: v for k, v in ev.items() if k != "crc"}
    return durable.crc32c(durable.canonical_body_bytes(body))


def verify_record_crc(ev: dict) -> bool:
    """True when ``ev`` carries no crc (legacy pre-graftvault record)
    or its crc matches; False on a mismatch."""
    if "crc" not in ev:
        return True
    try:
        return int(ev["crc"]) == record_crc(ev)
    except (TypeError, ValueError):
        return False

RUN_EVENT = "capture.run"
STAGE_EVENT = "capture.stage"
PROBE_EVENT = "capture.probe"

STATUS_STARTED = "started"
STATUS_DONE = "done"
STATUS_ABORTED = "aborted"   # clean budget close — the window ended
STATUS_WEDGED = "wedged"     # watchdog fired, or an orphaned start

OUTCOME_COMPLETE = "complete"
OUTCOME_WINDOW_CLOSED = "window_closed"
OUTCOME_WEDGED = "wedged"

# bench.py --capture exit codes: distinct from generic failure (1) so
# tpu_watch.sh can tell "resumable, re-enter next window" from "broken"
EXIT_WINDOW_CLOSED = 3
EXIT_WEDGED = 4

# pre-window stages, in executable order (see module docstring)
SETUP_STAGES = ("probe", "arena_warm", "precompile", "cost", "baseline")
_WINDOW_KINDS = ("fit", "ceiling", "compact")


class CaptureWedged(RuntimeError):
    """A stage's watchdog fired: the device op wedged past its deadline
    but the wait was signal-interruptible, so the process survives to
    journal the diagnosis and exit resumable."""


class StitchRefused(ValueError):
    """The journal's fragments cannot honestly form one measurement
    (mixed commits/configs/backends, too few windows, no identity)."""


def stage_plan(windows: int) -> list[str]:
    """The full ordered stage list for a capture of `windows` measured
    windows. Every entry of a resumed capture runs THIS plan and skips
    what the journal already holds."""
    plan = list(SETUP_STAGES)
    for i in range(windows):
        for kind in _WINDOW_KINDS:
            plan.append(f"window:{i:02d}:{kind}")
    return plan


def window_of(stage: str) -> tuple[int, str] | None:
    """(window id, kind) for a ``window:NN:kind`` stage, else None."""
    parts = stage.split(":")
    if len(parts) != 3 or parts[0] != "window":
        return None
    try:
        return int(parts[1]), parts[2]
    except ValueError:
        return None


class CaptureJournal:
    """Append-only JSONL journal of schema-v2 meta events at a fixed
    path. One ``write()`` of one full line per record (flushed — the
    MetricsWriter durability contract: a kill loses at most the final
    partial line), reader skips undecodable/invalid lines LOUDLY but
    never fatally."""

    def __init__(self, path: str):
        self.path = path
        self.skipped_lines = 0

    def append(self, name: str, fields: dict) -> dict:
        ev = {
            "v": SCHEMA_VERSION,
            "t": time.time(),
            "tm": time.monotonic(),
            "pid": os.getpid(),
            # single-host bench machinery: the journal is written by the
            # capture process and the watcher's helper one-liners, never
            # by a multi-host mesh run
            "pi": 0,
            "kind": "meta",
            "name": name,
            "fields": fields,
        }
        validate_event(ev)
        ev["crc"] = record_crc(ev)
        # durable append (store/durable.py): full line + fsync, under
        # the journal lock so the capture process and the watcher's
        # helper one-liners never interleave mid-line
        line = (json.dumps(ev) + "\n").encode("utf-8")
        with StoreLock(f"{self.path}.lock", store="journal"):
            durable.append_line(self.path, line, store="journal")
        return ev

    def stage(self, stage: str, status: str, *, window: int | None = None,
              **fields) -> dict:
        payload: dict = {"stage": stage, "status": status}
        if window is None:
            win = window_of(stage)
            if win is not None:
                window = win[0]
        if window is not None:
            payload["window"] = window
        payload.update(fields)
        return self.append(STAGE_EVENT, payload)

    def records(self) -> list[dict]:
        """Every valid journal record, in order. Corrupt or truncated
        lines are counted + warned about (``self.skipped_lines``) and
        skipped — a torn final line is the expected signature of a
        window that closed mid-write, never a reason to lose the
        journal."""
        try:
            with open(self.path) as f:
                lines = f.read().splitlines()
        except OSError:
            return []
        out: list[dict] = []
        skipped = 0
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                ev = validate_event(json.loads(line))
            except (ValueError, TypeError) as e:
                skipped += 1
                log.warning("capture journal %s: skipping bad line %d "
                            "(%s)", self.path, i + 1, e)
                continue
            if not verify_record_crc(ev):
                skipped += 1
                log.warning("capture journal %s: skipping line %d — "
                            "record crc mismatch (bit-rot or a torn "
                            "interior write)", self.path, i + 1)
                continue
            out.append(ev)
        self.skipped_lines = skipped
        return out


def stage_records(records: list[dict]) -> list[dict]:
    return [r for r in records if r.get("name") == STAGE_EVENT]


def completed_stages(records: list[dict]) -> dict[str, dict]:
    """stage -> the FIELDS of its first ``done`` record. First wins:
    the runner never re-runs a done stage, so duplicates would mean a
    corrupted journal — the earliest record is the real measurement."""
    done: dict[str, dict] = {}
    for r in stage_records(records):
        f = r.get("fields") or {}
        if f.get("status") == STATUS_DONE and f.get("stage"):
            done.setdefault(f["stage"], f)
    return done


def _last_status(records: list[dict]) -> dict[str, str]:
    last: dict[str, str] = {}
    for r in stage_records(records):
        f = r.get("fields") or {}
        if f.get("stage") and f.get("status"):
            last[f["stage"]] = f["status"]
    return last


def first_incomplete(plan: list[str], records: list[dict]) -> str | None:
    """The re-entry point: the first plan stage with no ``done``
    record, or None when the capture is complete."""
    done = completed_stages(records)
    for stage in plan:
        if stage not in done:
            return stage
    return None


def orphaned_stages(records: list[dict]) -> list[str]:
    """Stages whose LAST record is ``started`` — the process died (or
    was killed by the faulthandler backstop / the watcher's outer
    timeout) inside them with no chance to journal an outcome."""
    return [s for s, st in _last_status(records).items()
            if st == STATUS_STARTED]


def wedged_stages(records: list[dict]) -> list[str]:
    """Every stage ever journaled ``wedged``, in journal order (the
    watcher logs the tail of this on its next poll)."""
    out = []
    for r in stage_records(records):
        f = r.get("fields") or {}
        if f.get("status") == STATUS_WEDGED and f.get("stage"):
            out.append(f["stage"])
    return out


def journal_probe(path: str, *, ok: bool, latency_s: float,
                  source: str = "tpu_watch") -> dict:
    """One watcher probe attempt (timestamp rides the envelope). Called
    by tpu_watch.sh between polls so 'the tunnel never opened' becomes
    a measured claim."""
    return CaptureJournal(path).append(PROBE_EVENT, {
        "ok": bool(ok), "latency_s": float(latency_s), "source": source})


def probe_availability(records: list[dict]) -> dict:
    """Tunnel-availability statistics from the journaled probe
    attempts: healthy-window count + duration histogram (consecutive
    ``ok`` probes form one window; its duration is last-ok minus
    first-ok wall time, so a lone healthy probe counts as a sub-minute
    window)."""
    probes = [(r["t"], bool((r.get("fields") or {}).get("ok")),
               (r.get("fields") or {}).get("latency_s"))
              for r in records if r.get("name") == PROBE_EVENT]
    attempts = len(probes)
    ok_n = sum(1 for _, ok, _ in probes if ok)
    durations: list[float] = []
    start = last = None
    for t, ok, _ in probes:
        if ok:
            start = t if start is None else start
            last = t
        elif start is not None:
            durations.append(last - start)
            start = last = None
    if start is not None:
        durations.append(last - start)
    hist = {"lt_60s": 0, "60_300s": 0, "300_1800s": 0, "gt_1800s": 0}
    for d in durations:
        if d < 60:
            hist["lt_60s"] += 1
        elif d < 300:
            hist["60_300s"] += 1
        elif d < 1800:
            hist["300_1800s"] += 1
        else:
            hist["gt_1800s"] += 1
    lats = sorted(x for _, _, x in probes if isinstance(x, (int, float)))
    return {
        "probe_attempts": attempts,
        "probe_ok": ok_n,
        "availability_pct": (round(100.0 * ok_n / attempts, 1)
                             if attempts else None),
        "healthy_windows": len(durations),
        "window_durations_s": [round(d, 1) for d in durations],
        "window_histogram": hist,
        "median_probe_latency_s": (lats[len(lats) // 2]
                                   if lats else None),
    }


class StageWatchdog:
    """Wedge diagnosis around one capture stage (its first device op
    included). Two layers:

    - SIGALRM at ``timeout_s`` (main thread, interruptible waits):
      dumps every thread's stack via faulthandler, journals the stage
      ``wedged``, raises CaptureWedged — the process survives and exits
      resumable.
    - ``faulthandler.dump_traceback_later(2 x timeout_s, exit=True)``
      (C-level watchdog thread): when the main thread is stuck inside
      an uninterruptible PJRT call and the SIGALRM handler can never
      run, this still dumps all threads and hard-exits; the orphaned
      ``started`` record gets journaled ``wedged`` by the next entry.

    Both are cancelled on clean stage completion."""

    def __init__(self, journal: CaptureJournal, stage: str,
                 timeout_s: float, dump_path: str | None = None):
        self.journal = journal
        self.stage_name = stage
        self.timeout_s = timeout_s
        self.dump_path = dump_path
        self._dump_file = None
        self._prev_handler = None
        self._armed_sigalrm = False

    def _sink(self):
        return self._dump_file if self._dump_file is not None else sys.stderr

    def __enter__(self):
        if self.dump_path:
            try:
                os.makedirs(os.path.dirname(os.path.abspath(self.dump_path)),
                            exist_ok=True)
                # crash-diagnostic side channel, not store state — the
                # faulthandler C writer needs a raw fd, not the vault
                self._dump_file = open(  # graftlint: allow-durable-write
                    self.dump_path, "a")
                self._dump_file.write(
                    f"# stage {self.stage_name} armed at {time.time():.3f} "
                    f"(timeout {self.timeout_s}s)\n")
                self._dump_file.flush()
            except OSError as e:
                log.warning("watchdog dump file %s unavailable (%s); "
                            "dumping to stderr", self.dump_path, e)
                self._dump_file = None
        try:
            faulthandler.dump_traceback_later(
                2 * self.timeout_s, exit=True, file=self._sink())
        except (ValueError, OSError, RuntimeError) as e:
            log.warning("faulthandler backstop unavailable: %s", e)
        if hasattr(signal, "SIGALRM"):
            try:
                self._prev_handler = signal.signal(signal.SIGALRM,
                                                   self._on_alarm)
                signal.setitimer(signal.ITIMER_REAL, self.timeout_s)
                self._armed_sigalrm = True
            except ValueError as e:
                # not the main thread — the faulthandler backstop still
                # covers the hard-wedge case
                log.warning("SIGALRM watchdog unavailable: %s", e)
        return self

    def _on_alarm(self, signum, frame):
        try:
            faulthandler.dump_traceback(file=self._sink(), all_threads=True)
        except (ValueError, OSError) as e:  # sink closed under us
            log.warning("watchdog stack dump failed: %s", e)
        self.journal.stage(self.stage_name, STATUS_WEDGED,
                           reason="watchdog_sigalrm",
                           timeout_s=self.timeout_s,
                           dump_path=self.dump_path)
        raise CaptureWedged(
            f"stage {self.stage_name!r} wedged past {self.timeout_s}s")

    def __exit__(self, exc_type, exc, tb):
        if self._armed_sigalrm:
            signal.setitimer(signal.ITIMER_REAL, 0.0)
            signal.signal(signal.SIGALRM, self._prev_handler)
        try:
            faulthandler.cancel_dump_traceback_later()
        except (ValueError, RuntimeError) as e:  # pragma: no cover
            log.warning("cancel_dump_traceback_later failed: %s", e)
        if self._dump_file is not None:
            self._dump_file.close()
            self._dump_file = None
        return False


class CaptureRunner:
    """Drive the stage plan against the journal: skip every journaled
    ``done`` stage, run the rest in order under the watchdog + window
    budget, journal each outcome atomically.

    ``runners`` maps stage name -> zero-arg callable returning the
    stage's metrics dict (journaled on the ``done`` record). Budgets
    model the sub-minute window: ``budget_s`` (wall seconds for this
    entry, via the injectable ``clock``) and ``budget_stages`` (close
    after N completed stages — the deterministic kill the tests and the
    CI ``--simulate-windows`` dryrun use). Either budget fires AFTER
    the next stage journals ``started``: the journal always shows
    exactly which in-flight step the closing window cost, and resume
    re-enters at that stage."""

    def __init__(self, journal: CaptureJournal, plan: list[str],
                 runners: dict, *, budget_stages: int | None = None,
                 budget_s: float | None = None, clock=time.monotonic,
                 watchdog_s: float = 0.0, dump_path: str | None = None):
        self.journal = journal
        self.plan = plan
        self.runners = runners
        self.budget_stages = budget_stages
        self.budget_s = budget_s
        self.clock = clock
        self.watchdog_s = watchdog_s
        self.dump_path = dump_path
        self.stages_run: list[str] = []

    def _bus(self):
        from pertgnn_tpu import telemetry
        return telemetry.get_bus()

    def _diagnose_orphans(self, records: list[dict]) -> None:
        for stage in orphaned_stages(records):
            log.warning("previous capture entry died inside stage %r "
                        "with no journaled outcome — marking it wedged",
                        stage)
            self.journal.stage(stage, STATUS_WEDGED,
                               reason="orphaned_start")
            self._bus().counter("capture.stage_wedged", 1, stage=stage)

    def run(self) -> str:
        records = self.journal.records()
        self._diagnose_orphans(records)
        done = set(completed_stages(records))
        bus = self._bus()
        done_this_entry = 0
        t0 = self.clock()
        for stage in self.plan:
            if stage in done:
                continue
            self.journal.stage(stage, STATUS_STARTED,
                               watchdog_s=self.watchdog_s or None)
            over_stages = (self.budget_stages is not None
                           and done_this_entry >= self.budget_stages)
            over_wall = (self.budget_s is not None
                         and self.clock() - t0 >= self.budget_s)
            if over_stages or over_wall:
                reason = "stage_budget" if over_stages else "wall_budget"
                self.journal.stage(stage, STATUS_ABORTED, reason=reason)
                bus.counter("capture.window_closed", 1, stage=stage)
                log.info("capture window closed (%s) with stage %r "
                         "in flight — journal is resumable", reason, stage)
                return OUTCOME_WINDOW_CLOSED
            t_stage = self.clock()
            try:
                if self.watchdog_s > 0:
                    with StageWatchdog(self.journal, stage, self.watchdog_s,
                                       dump_path=self.dump_path):
                        fields = self.runners[stage]() or {}
                else:
                    fields = self.runners[stage]() or {}
            except CaptureWedged:
                # the watchdog already journaled the wedge record
                bus.counter("capture.stage_wedged", 1, stage=stage)
                return OUTCOME_WEDGED
            self.stages_run.append(stage)
            dt = self.clock() - t_stage
            self.journal.stage(stage, STATUS_DONE, seconds=round(dt, 3),
                               **fields)
            bus.counter("capture.stage_done", 1, stage=stage)
            bus.gauge("capture.stage_seconds", dt, stage=stage)
            done_this_entry += 1
        return OUTCOME_COMPLETE


def run_fingerprint(records: list[dict]) -> tuple | None:
    """(commit, canonical-config-json) of the journal's LAST run
    record, or None for a virgin journal — what bench.py --capture
    compares against to decide resume vs rotate."""
    fp = None
    for r in records:
        if r.get("name") == RUN_EVENT:
            f = r.get("fields") or {}
            fp = (f.get("commit"),
                  json.dumps(f.get("config") or {}, sort_keys=True),
                  f.get("backend"))
    return fp


def stitch_windows(records: list[dict], *,
                   min_fit_windows: int | None = None,
                   max_staleness_s: float = 48 * 3600.0) -> dict:
    """Assemble one interleaved fit/ceiling measurement out of the
    journal's window fragments.

    Refusals (StitchRefused): no run-identity record, fragments
    spanning >1 (commit, config) identity, windows spanning >1 backend,
    no baseline stage, fewer than ``min_fit_windows`` fit windows after
    the staleness bound. Windows older than ``max_staleness_s`` behind
    the newest are DROPPED loudly (counted in the result), not fatal —
    the spread is computed over the kept union by the caller.

    Pure over decoded records (no jax): adjudicate.py calls this from
    a host-only context."""
    runs = [r.get("fields") or {} for r in records
            if r.get("name") == RUN_EVENT]
    if not runs:
        raise StitchRefused("journal has no capture.run identity record")
    idents = {(f.get("commit"),
               json.dumps(f.get("config") or {}, sort_keys=True))
              for f in runs}
    if len(idents) > 1:
        raise StitchRefused(
            f"fragments span {len(idents)} incompatible commit/config "
            f"identities — a stitched number must come from ONE tree: "
            f"{sorted(str(i) for i in idents)}")
    run0 = runs[0]
    cfg = run0.get("config") or {}
    planned = int(cfg.get("windows") or 0)
    if min_fit_windows is None:
        min_fit_windows = max(1, min(3, planned or 3))

    done_env: dict[str, dict] = {}
    for r in stage_records(records):
        f = r.get("fields") or {}
        if f.get("status") == STATUS_DONE and f.get("stage"):
            done_env.setdefault(f["stage"], r)

    wins: dict[int, dict[str, dict]] = {}
    for stage, env in done_env.items():
        win = window_of(stage)
        if win is not None:
            wins.setdefault(win[0], {})[win[1]] = env
    if not wins:
        raise StitchRefused("no completed capture windows in journal")

    newest = max(env["t"] for parts in wins.values()
                 for env in parts.values())
    stale = [i for i, parts in wins.items()
             if max(env["t"] for env in parts.values())
             < newest - max_staleness_s]
    for i in stale:
        log.warning("stitch: dropping window %02d — %.1fh older than the "
                    "newest fragment (staleness bound %.1fh)", i,
                    (newest - max(env["t"]
                                  for env in wins[i].values())) / 3600,
                    max_staleness_s / 3600)
    kept = sorted(i for i in wins if i not in stale)

    backends = {(env.get("fields") or {}).get("backend")
                for i in kept for env in wins[i].values()
                if (env.get("fields") or {}).get("backend")}
    if len(backends) > 1:
        raise StitchRefused(
            f"windows span multiple backends {sorted(backends)} — "
            f"fragments from different chips cannot form one number")

    baseline_f = completed_stages(records).get("baseline")
    if not baseline_f or baseline_f.get(
            "baseline_torch_cpu_graphs_per_s") is None:
        raise StitchRefused("no journaled baseline stage — vs_baseline "
                            "would be unfounded")

    def _series(kind: str) -> list[float]:
        out = []
        for i in kept:
            env = wins[i].get(kind)
            if env is not None:
                g = (env.get("fields") or {}).get("graphs_per_s")
                if g is not None:
                    out.append(g)
        return out

    fit_w = _series("fit")
    if len(fit_w) < min_fit_windows:
        raise StitchRefused(f"only {len(fit_w)} stitched fit windows "
                            f"(< {min_fit_windows})")

    provenance = []
    attribution = []
    for i in kept:
        for kind in _WINDOW_KINDS:
            env = wins[i].get(kind)
            if env is None:
                continue
            f = env.get("fields") or {}
            provenance.append({
                "window": i, "stage": kind, "t": round(env["t"], 3),
                "pid": env["pid"],
                "graphs_per_s": f.get("graphs_per_s"),
            })
            if kind == "fit" and f.get("roofline") is not None:
                attribution.append({"window": i, **f["roofline"]})

    arena = completed_stages(records).get("arena_warm") or {}
    cost = completed_stages(records).get("cost") or {}
    complete = (planned > 0 and len(kept) == planned and not stale
                and all(k in wins[i] for i in kept for k in _WINDOW_KINDS))
    return {
        "fit_w": fit_w,
        "ceil_w": _series("ceiling"),
        "cceil_w": _series("compact"),
        "baseline": baseline_f["baseline_torch_cpu_graphs_per_s"],
        "flops_per_graph": cost.get("flops_per_graph"),
        "bytes_per_graph": cost.get("bytes_per_graph"),
        "peak_flops": cost.get("peak_flops_per_chip"),
        "peak_bw": cost.get("peak_hbm_bytes_per_s"),
        "device_kind": (cost.get("device_kind")
                        or arena.get("device_kind")
                        or run0.get("device_kind")),
        "backend": (backends.pop() if backends
                    else run0.get("backend", "unknown")),
        "fallback": bool(run0.get("backend_fallback")),
        "attention_impl": arena.get("attention_impl",
                                    cfg.get("attention_impl", "segment")),
        "serve_dtype": arena.get("serve_dtype", "f32"),
        "train_graphs": arena.get("train_graphs_per_epoch"),
        "commit": run0.get("commit"),
        "dirty": run0.get("dirty_worktree"),
        "provenance": provenance,
        "window_attribution": attribution,
        "stale_windows_dropped": len(stale),
        "n_entries": len(runs),
        "complete": complete,
        "wedged_stages": wedged_stages(records),
    }
