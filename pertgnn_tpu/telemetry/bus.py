"""The telemetry bus: counters, gauges, histograms, spans — or nothing.

Two implementations share one interface:

- ``NoopBus`` — the default. Every method is an attribute lookup + an
  immediate return; ``span`` hands back a shared do-nothing context
  manager. Instrumentation in hot paths (the packer, the serve request
  loop) therefore costs nanoseconds when telemetry is off — pinned by
  benchmarks/telemetry_overhead.py (< 1% of a CPU train step) and the
  bound test in tests/test_telemetry.py.
- ``TelemetryBus`` — a MetricsWriter-backed bus with a verbosity
  ``level``: 1 ("basic") records run/epoch-granularity events, 2
  ("trace") additionally records per-chunk / per-request events. Call
  sites mark hot events with ``level=2`` and the bus drops them below
  that verbosity without allocating a span object.

Levels: "off"=0, "basic"=1, "trace"=2 (ints accepted)."""

from __future__ import annotations

import functools
import time

from pertgnn_tpu.telemetry.tracing import TraceContext, new_span_id

LEVELS = {"off": 0, "basic": 1, "trace": 2}


def parse_level(level: int | str) -> int:
    if isinstance(level, int):
        return level
    try:
        return LEVELS[level.lower()]
    except KeyError:
        raise ValueError(
            f"unknown telemetry level {level!r} (want one of "
            f"{sorted(LEVELS)} or an int)") from None


class _NullSpan:
    """Shared, reusable no-op context manager (no per-call allocation)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class NoopBus:
    """The disabled bus — also the interface definition. All kwargs
    beyond the named ones are tags."""

    enabled = False
    level = 0
    trace_sample_rate = 0.0
    trace_slow_ms = 0.0

    def counter(self, name: str, value: float = 1, *, level: int = 1,
                **tags) -> None:
        pass

    def gauge(self, name: str, value: float, *, level: int = 1,
              **tags) -> None:
        pass

    def histogram(self, name: str, value: float, *, level: int = 1,
                  **tags) -> None:
        pass

    def event(self, name: str, fields: dict | None = None, *,
              level: int = 1, **tags) -> None:
        pass

    def span(self, name: str, *, level: int = 1, **tags):
        return NULL_SPAN

    def wrap(self, name: str, *, level: int = 1, **tags):
        """Decorator form of ``span``: times every call of the wrapped
        function. On the noop bus the function is returned UNCHANGED —
        zero per-call overhead, not even a frame."""
        return lambda fn: fn

    # -- distributed request tracing (telemetry/tracing.py) --------------

    def start_trace(self) -> TraceContext | None:
        """Head-sampling decision for one request entering the stack.
        None (tracing off) on the noop bus and below trace verbosity."""
        return None

    def adopt_trace(self, trace_id, parent_span_id) -> TraceContext | None:
        """A context propagated over the transport (worker side)."""
        return None

    def trace_span(self, name: str, ctx: TraceContext | None,
                   tm0: float, tm1: float, *, span_id: str | None = None,
                   parent_id: str | None = None, **tags) -> str | None:
        """One explicitly-timed stage span of a traced request
        (monotonic stamps; the caller owns the clock reads so a span
        can start on one thread and end on another). Returns the
        span id used, for parenting children across the transport."""
        return None

    def finish_trace(self, name: str, ctx: TraceContext | None,
                     tm0: float, tm1: float, **tags) -> None:
        """Emit the trace's ROOT span and settle the sampling verdict:
        a head-sampled trace writes the root; an unsampled one flushes
        its buffered spans only if the total crossed trace_slow_ms
        (the tail-exemplar always-keep), else drops them."""

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NOOP_BUS = NoopBus()


class _Span:
    __slots__ = ("_bus", "_name", "_tags", "_t0")

    def __init__(self, bus, name, tags):
        self._bus = bus
        self._name = name
        self._tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        self._bus._writer.write("span", self._name, dur_ms=dur_ms,
                                tags=self._tags or None)
        return False


class TelemetryBus(NoopBus):
    """MetricsWriter-backed bus. Construct via telemetry.configure()."""

    enabled = True

    def __init__(self, writer, level: int | str = "basic", *,
                 trace_sample_rate: float = 0.0,
                 trace_slow_ms: float = 0.0):
        self._writer = writer
        self.level = parse_level(level)
        self.trace_sample_rate = float(trace_sample_rate)
        self.trace_slow_ms = float(trace_slow_ms)

    def counter(self, name, value=1, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("counter", name, value=value,
                               tags=tags or None)

    def gauge(self, name, value, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("gauge", name, value=value, tags=tags or None)

    def histogram(self, name, value, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("histogram", name, value=value,
                               tags=tags or None)

    def event(self, name, fields=None, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("meta", name, fields=fields or {},
                               tags=tags or None)

    def span(self, name, *, level=1, **tags):
        if level <= self.level:
            return _Span(self, name, tags)
        return NULL_SPAN

    def wrap(self, name, *, level=1, **tags):
        def deco(fn):
            @functools.wraps(fn)
            def timed(*a, **kw):
                with self.span(name, level=level, **tags):
                    return fn(*a, **kw)
            return timed
        return deco

    # -- distributed request tracing -------------------------------------

    def start_trace(self):
        """Per-request head sampling. Request tracing is trace-level
        instrumentation: below "trace" verbosity every request runs
        untraced regardless of the sample rate (the same gate the
        per-request histograms use)."""
        if self.level < 2:
            return None
        ctx = TraceContext.start(self.trace_sample_rate)
        if ctx is not None and not ctx.sampled and self.trace_slow_ms <= 0:
            return None  # nothing could ever flush the buffer
        return ctx

    def adopt_trace(self, trace_id, parent_span_id):
        if self.level < 2:
            # a router tracing at "trace" against a worker at "basic":
            # the worker contributes no spans (graftscope reports the
            # transport leg as opaque) rather than half a chain per
            # mismatched process
            return None
        return TraceContext.adopt(trace_id, parent_span_id)

    def trace_span(self, name, ctx, tm0, tm1, *, span_id=None,
                   parent_id=None, **tags):
        if ctx is None:
            return None
        sid = span_id or new_span_id()
        pid_ = parent_id or ctx.root_id
        if ctx.sampled:
            self._writer.write(
                "span", name, dur_ms=(tm1 - tm0) * 1e3,
                tags=tags or None,
                trace={"trace_id": ctx.trace_id, "span_id": sid,
                       "parent_span_id": pid_, "tm0": tm0})
        elif ctx.buffer is not None:
            ctx.buffer.append((name, tm0, tm1, sid, pid_, tags))
        return sid

    def finish_trace(self, name, ctx, tm0, tm1, **tags):
        if ctx is None:
            return
        total_ms = (tm1 - tm0) * 1e3
        if not ctx.sampled:
            buffered, ctx.buffer = ctx.buffer, None
            if self.trace_slow_ms <= 0 or total_ms < self.trace_slow_ms:
                return  # the head said no and the tail agreed: drop
            tags["sampled"] = "slow"
            for b_name, b_tm0, b_tm1, b_sid, b_pid, b_tags in buffered:
                self._writer.write(
                    "span", b_name, dur_ms=(b_tm1 - b_tm0) * 1e3,
                    tags=b_tags or None,
                    trace={"trace_id": ctx.trace_id, "span_id": b_sid,
                           "parent_span_id": b_pid, "tm0": b_tm0})
        # the root: trace_id + span_id, NO parent — how graftscope
        # recognizes a tree's anchor
        self._writer.write(
            "span", name, dur_ms=total_ms, tags=tags or None,
            trace={"trace_id": ctx.trace_id, "span_id": ctx.root_id,
                   "tm0": tm0})

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()

    @property
    def path(self) -> str:
        return self._writer.path
