"""The telemetry bus: counters, gauges, histograms, spans — or nothing.

Two implementations share one interface:

- ``NoopBus`` — the default. Every method is an attribute lookup + an
  immediate return; ``span`` hands back a shared do-nothing context
  manager. Instrumentation in hot paths (the packer, the serve request
  loop) therefore costs nanoseconds when telemetry is off — pinned by
  benchmarks/telemetry_overhead.py (< 1% of a CPU train step) and the
  bound test in tests/test_telemetry.py.
- ``TelemetryBus`` — a MetricsWriter-backed bus with a verbosity
  ``level``: 1 ("basic") records run/epoch-granularity events, 2
  ("trace") additionally records per-chunk / per-request events. Call
  sites mark hot events with ``level=2`` and the bus drops them below
  that verbosity without allocating a span object.

Levels: "off"=0, "basic"=1, "trace"=2 (ints accepted)."""

from __future__ import annotations

import functools
import time

LEVELS = {"off": 0, "basic": 1, "trace": 2}


def parse_level(level: int | str) -> int:
    if isinstance(level, int):
        return level
    try:
        return LEVELS[level.lower()]
    except KeyError:
        raise ValueError(
            f"unknown telemetry level {level!r} (want one of "
            f"{sorted(LEVELS)} or an int)") from None


class _NullSpan:
    """Shared, reusable no-op context manager (no per-call allocation)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class NoopBus:
    """The disabled bus — also the interface definition. All kwargs
    beyond the named ones are tags."""

    enabled = False
    level = 0

    def counter(self, name: str, value: float = 1, *, level: int = 1,
                **tags) -> None:
        pass

    def gauge(self, name: str, value: float, *, level: int = 1,
              **tags) -> None:
        pass

    def histogram(self, name: str, value: float, *, level: int = 1,
                  **tags) -> None:
        pass

    def event(self, name: str, fields: dict | None = None, *,
              level: int = 1, **tags) -> None:
        pass

    def span(self, name: str, *, level: int = 1, **tags):
        return NULL_SPAN

    def wrap(self, name: str, *, level: int = 1, **tags):
        """Decorator form of ``span``: times every call of the wrapped
        function. On the noop bus the function is returned UNCHANGED —
        zero per-call overhead, not even a frame."""
        return lambda fn: fn

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NOOP_BUS = NoopBus()


class _Span:
    __slots__ = ("_bus", "_name", "_tags", "_t0")

    def __init__(self, bus, name, tags):
        self._bus = bus
        self._name = name
        self._tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        self._bus._writer.write("span", self._name, dur_ms=dur_ms,
                                tags=self._tags or None)
        return False


class TelemetryBus(NoopBus):
    """MetricsWriter-backed bus. Construct via telemetry.configure()."""

    enabled = True

    def __init__(self, writer, level: int | str = "basic"):
        self._writer = writer
        self.level = parse_level(level)

    def counter(self, name, value=1, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("counter", name, value=value,
                               tags=tags or None)

    def gauge(self, name, value, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("gauge", name, value=value, tags=tags or None)

    def histogram(self, name, value, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("histogram", name, value=value,
                               tags=tags or None)

    def event(self, name, fields=None, *, level=1, **tags):
        if level <= self.level:
            self._writer.write("meta", name, fields=fields or {},
                               tags=tags or None)

    def span(self, name, *, level=1, **tags):
        if level <= self.level:
            return _Span(self, name, tags)
        return NULL_SPAN

    def wrap(self, name, *, level=1, **tags):
        def deco(fn):
            @functools.wraps(fn)
            def timed(*a, **kw):
                with self.span(name, level=level, **tags):
                    return fn(*a, **kw)
            return timed
        return deco

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()

    @property
    def path(self) -> str:
        return self._writer.path
