"""Process-wide telemetry bus: structured metrics, spans, profiler wiring.

The durable, machine-readable record of what a run did — the reference
artifact has only tqdm bars (SURVEY.md §5.1) and the repro previously had
point solutions (a StepTimer EMA here, a serve-only LatencyRecorder
there). One bus now carries:

- **scalar events** (counters / gauges / histograms with tags) to
  append-only schema-versioned JSONL (telemetry/schema.py,
  telemetry/writer.py), optionally mirrored to TensorBoard;
- **spans** (``telemetry.span("pack")`` context manager / decorator)
  through the hot paths: ingest, packing, host->device staging, train &
  eval chunks, and the serve request lifecycle (queue wait -> pack ->
  dispatch -> compute);
- **jax.monitoring** forwarding, so every XLA compile lands in the same
  stream as the request that paid for it (telemetry/jaxmon.py).

Default state is a NoopBus whose per-call cost is nanoseconds
(benchmarks/telemetry_overhead.py pins < 1% of a CPU train step), so
instrumentation stays in the code unconditionally. CLIs call
``configure()`` from the ``--telemetry_dir`` / ``--telemetry_level``
flags; library code reads ``get_bus()`` or accepts an injected bus
(train/loop.fit, serve/engine.InferenceEngine).

Usage::

    from pertgnn_tpu import telemetry
    telemetry.configure("runs/t1", level="basic")
    telemetry.get_bus().counter("serve.cache_hit", bucket=2)
    with telemetry.span("pack"):
        ...

Schema + analysis workflow: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from pertgnn_tpu.telemetry.bus import (NOOP_BUS, NULL_SPAN, NoopBus,
                                       TelemetryBus, parse_level)
from pertgnn_tpu.telemetry.devmem import (device_memory_stats,
                                          sample_device_memory)
from pertgnn_tpu.telemetry.jaxmon import (install_jax_monitoring,
                                          watch_xla_cache)
from pertgnn_tpu.telemetry.schema import (SCHEMA_VERSION, SchemaError,
                                          iter_events, load_events,
                                          validate_event)
from pertgnn_tpu.telemetry.tracing import (TraceContext, new_span_id,
                                           new_trace_id)
from pertgnn_tpu.telemetry.writer import MetricsWriter

__all__ = [
    "NOOP_BUS", "NULL_SPAN", "NoopBus", "TelemetryBus", "MetricsWriter",
    "SCHEMA_VERSION", "SchemaError", "validate_event", "iter_events",
    "load_events", "parse_level", "install_jax_monitoring",
    "device_memory_stats", "sample_device_memory",
    "watch_xla_cache", "configure", "configure_from_config", "get_bus",
    "set_bus", "span", "shutdown", "TraceContext", "new_trace_id",
    "new_span_id",
]

_bus: NoopBus = NOOP_BUS
_uninstall_jaxmon = None


def get_bus() -> NoopBus:
    """The process-wide bus (NoopBus until configure()/set_bus())."""
    return _bus


def set_bus(bus) -> NoopBus:
    """Install `bus` as the process-wide bus; returns the previous one.
    Tests use this to inject a scratch bus and restore the old."""
    global _bus
    prev, _bus = _bus, bus
    return prev


def span(name: str, *, level: int = 1, **tags):
    """Module-level convenience: a span on the current global bus."""
    return _bus.span(name, level=level, **tags)


def configure(telemetry_dir: str, level: int | str = "basic", *,
              tensorboard: bool = False, run_meta: dict | None = None,
              jax_monitoring: bool = True, trace_sample_rate: float = 0.0,
              trace_slow_ms: float = 0.0, rotate_mb: float = 0.0):
    """Build + install the process-wide bus from CLI/Config knobs.

    Empty ``telemetry_dir`` or level "off" installs the NoopBus (and
    tears down any previous real bus). Returns the installed bus.
    ``trace_sample_rate`` / ``trace_slow_ms`` arm distributed request
    tracing (telemetry/tracing.py — effective at "trace" level only);
    ``rotate_mb`` > 0 rotates the JSONL into ``.partN`` siblings."""
    global _uninstall_jaxmon
    shutdown()
    lvl = parse_level(level)
    if not telemetry_dir or lvl <= 0:
        return _bus
    writer = MetricsWriter(telemetry_dir, tensorboard=tensorboard,
                           run_meta=run_meta, rotate_mb=rotate_mb)
    bus = TelemetryBus(writer, level=lvl,
                       trace_sample_rate=trace_sample_rate,
                       trace_slow_ms=trace_slow_ms)
    set_bus(bus)
    if jax_monitoring:
        _uninstall_jaxmon = install_jax_monitoring(bus)
    return bus


def configure_from_config(cfg, run_meta: dict | None = None):
    """configure() from a config.TelemetryConfig (or a full Config —
    its `.telemetry` is used). The CLIs route through this
    (cli/common.setup_telemetry) so the flag mapping lives in one place."""
    t = getattr(cfg, "telemetry", cfg)
    return configure(t.telemetry_dir, t.telemetry_level,
                     tensorboard=t.tensorboard, run_meta=run_meta,
                     trace_sample_rate=getattr(t, "trace_sample_rate",
                                               0.0),
                     trace_slow_ms=getattr(t, "trace_slow_ms", 0.0),
                     rotate_mb=getattr(t, "telemetry_rotate_mb", 0.0))


def shutdown() -> None:
    """Close the active bus (if real) and restore the NoopBus."""
    global _uninstall_jaxmon
    if _uninstall_jaxmon is not None:
        _uninstall_jaxmon()
        _uninstall_jaxmon = None
    prev = set_bus(NOOP_BUS)
    if prev is not NOOP_BUS:
        prev.close()
