"""Distributed request tracing: trace/span identity + head sampling.

One request entering the serving stack (fleet/router.py submit, or a
standalone serve/queue.py submit) gets ONE ``TraceContext``; every
stage it passes through — router queue, transport, worker queue, pack,
dispatch, compute, complete — emits a v2 span event carrying the
context's ``trace_id`` and a parent/child ``span_id`` chain, so
tools/graftscope can reassemble the request's life across the router
and worker processes from their per-process JSONL files
(docs/OBSERVABILITY.md "Distributed request tracing").

Sampling is HEAD-based: the dice roll happens once, at the front door,
and the verdict propagates (an unsampled request costs nothing
downstream — the worker never even sees a trace id). The one exception
is the ALWAYS-KEEP override for tail exemplars: an unsampled request's
front-door process buffers its own spans in the context instead of
writing them, and flushes them (tagged ``sampled="slow"``) only if the
request's total latency crosses ``trace_slow_ms`` — so at a 1% sample
rate the p99.9 stragglers still land in the stream with router-side
stage attribution, while the 99% fast path pays list appends, not disk
writes. Worker-side detail exists only for head-sampled requests
(buffering across the transport would need a second round trip);
graftscope marks slow-kept traces partial instead of calling them
incomplete.

Identity scheme: ``trace_id`` is 8 random bytes hex (globally unique
across hosts/restarts — it names the request forever); ``span_id`` is
``<pid hex>.<counter hex>`` (unique across the processes of one run at
~100 ns per id — span ids only need to be unique within the files one
graftscope invocation merges, and the pid prefix plus a per-process
counter guarantees that without entropy reads on the hot path).
"""

from __future__ import annotations

import itertools
import os
import random

__all__ = ["TraceContext", "new_trace_id", "new_span_id"]

_counter = itertools.count(1)
_counter_pid = os.getpid()


def new_trace_id() -> str:
    """8 random bytes, hex — the request's globally unique name."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """``<pid>.<n>`` in hex — unique across this run's processes."""
    global _counter, _counter_pid
    pid = os.getpid()
    if pid != _counter_pid:  # forked child: restart the counter stream
        _counter, _counter_pid = itertools.count(1), pid
    return f"{pid:x}.{next(_counter):x}"


class TraceContext:
    """One request's trace identity, threaded through its lifecycle.

    ``sampled`` requests write spans straight to the bus's writer;
    unsampled ones append pending spans to ``buffer`` for the
    slow-exemplar flush decision at finish. A context lives in exactly
    one stage owner at a time (submit thread -> dispatcher -> sender),
    so the buffer needs no lock — emit spans BEFORE handing the request
    to the next owner (fleet/router.py does).
    """

    __slots__ = ("trace_id", "root_id", "sampled", "buffer")

    def __init__(self, trace_id: str, root_id: str, sampled: bool):
        self.trace_id = trace_id
        self.root_id = root_id
        self.sampled = sampled
        # (name, tm0, tm1, span_id, parent_id, tags) pending rows
        self.buffer: list | None = None if sampled else []

    @classmethod
    def start(cls, sample_rate: float) -> "TraceContext | None":
        """Head decision for a request entering the stack: a sampled
        context, an unsampled (buffer-only) one, or None when tracing
        is off entirely (rate <= 0)."""
        if sample_rate <= 0.0:
            return None
        sampled = sample_rate >= 1.0 or random.random() < sample_rate
        return cls(new_trace_id(), new_span_id(), sampled)

    @classmethod
    def adopt(cls, trace_id: str, parent_span_id: str) -> "TraceContext":
        """A propagated context on the worker side of the transport:
        always sampled (only head-sampled requests propagate), parented
        under the router's transport span."""
        return cls(str(trace_id), str(parent_span_id), True)
