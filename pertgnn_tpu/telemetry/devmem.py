"""Device memory gauges from ``Device.memory_stats()``.

PJRT backends that track allocator state (TPU, GPU) expose
``memory_stats()`` on each device; CPU typically returns None or lacks
the method entirely. These helpers normalize that into an
all-or-nothing sample — a dict of the three canonical fields when the
backend publishes them, None otherwise — and optionally publish the
sample as ``device.mem.*`` gauges on the telemetry bus. Every caller
(engine warmup, per fit epoch, per capture window, precompile,
kernel_bench) goes through here so None-safety lives in one place.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)

# PJRT stat key -> canonical gauge suffix (TPU publishes
# peak_bytes_in_use; keep our name stable across backends)
_STAT_KEYS = (
    ("bytes_in_use", "bytes_in_use"),
    ("peak_bytes_in_use", "peak_bytes"),
    ("bytes_limit", "bytes_limit"),
)


def device_memory_stats(device=None) -> dict | None:
    """The canonical memory sample for `device` (default: first local
    jax device), or None when the backend doesn't publish stats. Never
    raises — a missing/broken stats surface is the CPU norm, not an
    error."""
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception as e:  # pragma: no cover - no backend at all
            log.debug("no jax device for memory stats: %s", e)
            return None
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is None:
        return None
    try:
        raw = stats_fn()
    except Exception as e:  # some PJRT clients raise instead of None
        log.debug("memory_stats() unavailable on %r: %s", device, e)
        return None
    if not raw:
        return None
    out = {}
    for src, dst in _STAT_KEYS:
        v = raw.get(src)
        if isinstance(v, (int, float)):
            out[dst] = int(v)
    return out or None


def sample_device_memory(bus=None, device=None, **tags) -> dict | None:
    """Sample `device` memory and publish ``device.mem.*`` gauges on
    `bus` (default: the process bus). Returns the sample dict, or None
    (with nothing emitted) when the backend publishes no stats."""
    stats = device_memory_stats(device)
    if not stats:
        return None
    if bus is None:
        from pertgnn_tpu import telemetry
        bus = telemetry.get_bus()
    if "bytes_in_use" in stats:
        bus.gauge("device.mem.bytes_in_use", stats["bytes_in_use"], **tags)
    if "peak_bytes" in stats:
        bus.gauge("device.mem.peak_bytes", stats["peak_bytes"], **tags)
    if "bytes_limit" in stats:
        bus.gauge("device.mem.bytes_limit", stats["bytes_limit"], **tags)
    return stats
