"""The telemetry JSONL event schema (versioned, validated).

One event per line, append-only, crash-safe at line granularity: a run
killed mid-write loses at most its final partial line, which the reader
skips. Every event is stamped with the schema version, wall time, pid and
jax process index so streams from a multi-host run can be concatenated
and still attributed.

Event kinds:

- ``meta``      — run-level context (argv, jax version, config dir);
                  carries a free-form ``fields`` dict.
- ``counter``   — monotonic increment (``value`` = the delta).
- ``gauge``     — point-in-time level (``value`` = the reading).
- ``histogram`` — one observation of a distribution (``value``).
- ``span``      — one timed region (``dur_ms``); emitted at exit.

``tags`` is an optional flat dict of scalar dimensions (bucket index,
epoch, split, ...). Loading into pandas is one call:
``pd.read_json(path, lines=True)`` — see docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator

SCHEMA_VERSION = 1

KINDS = ("meta", "counter", "gauge", "histogram", "span")

# kinds that must carry a numeric "value"
_VALUE_KINDS = ("counter", "gauge", "histogram")

_TAG_SCALARS = (str, int, float, bool, type(None))


class SchemaError(ValueError):
    """An event violates the telemetry JSONL schema."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SchemaError(msg)


def validate_event(ev: dict) -> dict:
    """Validate one decoded event against the schema; returns it.

    Raises SchemaError naming the first violated constraint — the
    round-trip test (tests/test_telemetry.py) feeds every writer-emitted
    event through this, so writer and schema cannot drift apart.
    """
    _require(isinstance(ev, dict), f"event is not an object: {type(ev)}")
    _require(ev.get("v") == SCHEMA_VERSION,
             f"schema version {ev.get('v')!r} != {SCHEMA_VERSION}")
    _require(isinstance(ev.get("t"), (int, float)),
             f"missing/non-numeric timestamp 't': {ev.get('t')!r}")
    _require(isinstance(ev.get("pid"), int),
             f"missing/non-int 'pid': {ev.get('pid')!r}")
    _require(isinstance(ev.get("pi"), int),
             f"missing/non-int process index 'pi': {ev.get('pi')!r}")
    kind = ev.get("kind")
    _require(kind in KINDS, f"unknown kind {kind!r} (want one of {KINDS})")
    name = ev.get("name")
    _require(isinstance(name, str) and name != "",
             f"missing/empty 'name': {name!r}")
    if kind in _VALUE_KINDS:
        _require(isinstance(ev.get("value"), (int, float))
                 and not isinstance(ev.get("value"), bool),
                 f"{kind} {name!r} needs a numeric 'value': "
                 f"{ev.get('value')!r}")
    if kind == "span":
        _require(isinstance(ev.get("dur_ms"), (int, float))
                 and not isinstance(ev.get("dur_ms"), bool),
                 f"span {name!r} needs a numeric 'dur_ms': "
                 f"{ev.get('dur_ms')!r}")
    if kind == "meta":
        _require(isinstance(ev.get("fields"), dict),
                 f"meta {name!r} needs a 'fields' object")
    tags = ev.get("tags")
    if tags is not None:
        _require(isinstance(tags, dict), f"'tags' is not an object: {tags!r}")
        for k, v in tags.items():
            _require(isinstance(k, str), f"non-string tag key {k!r}")
            _require(isinstance(v, _TAG_SCALARS),
                     f"tag {k!r} has non-scalar value {v!r}")
    return ev


def iter_events(lines: Iterable[str], strict: bool = True) -> Iterator[dict]:
    """Decode + validate a JSONL stream line by line.

    A trailing UNDECODABLE line (truncated JSON — the crash-mid-write
    signature) is always skipped. A line that decodes but violates the
    schema is never a crash tail — a partial write cannot produce valid
    JSON with wrong fields — so it raises (strict) or is skipped
    (strict=False) wherever it appears."""
    pending_decode: Exception | None = None
    for line in lines:
        line = line.strip()
        if not line:
            continue
        # an earlier line failed to DECODE but was not the last line —
        # that is corruption, not a crash tail
        if pending_decode is not None and strict:
            raise pending_decode
        pending_decode = None
        try:
            ev = json.loads(line)
        except ValueError as e:
            pending_decode = SchemaError(f"undecodable line: {e}")
            continue
        try:
            yield validate_event(ev)
        except SchemaError:
            if strict:
                raise
    # swallow pending_decode: the stream ended on it -> crash tail


def load_events(path: str, strict: bool = True) -> list[dict]:
    """All validated events from one telemetry JSONL file."""
    with open(path) as f:
        return list(iter_events(f, strict=strict))
