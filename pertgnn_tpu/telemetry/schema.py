"""The telemetry JSONL event schema (versioned, validated).

One event per line, append-only, crash-safe at line granularity: a run
killed mid-write loses at most its final partial line, which the reader
skips. Every event is stamped with the schema version, wall time, pid and
jax process index so streams from a multi-host run can be concatenated
and still attributed.

Event kinds:

- ``meta``      — run-level context (argv, jax version, config dir);
                  carries a free-form ``fields`` dict.
- ``counter``   — monotonic increment (``value`` = the delta).
- ``gauge``     — point-in-time level (``value`` = the reading).
- ``histogram`` — one observation of a distribution (``value``).
- ``span``      — one timed region (``dur_ms``); emitted at exit.

``tags`` is an optional flat dict of scalar dimensions (bucket index,
epoch, split, ...). Loading into pandas is one call:
``pd.read_json(path, lines=True)`` — see docs/OBSERVABILITY.md.

Schema v2 (additive — v1 files stay readable) is the distributed-tracing
extension (telemetry/tracing.py, tools/graftscope/):

- ``tm``   — per-event CLOCK_MONOTONIC stamp next to the wall ``t``:
  wall clocks step (NTP) mid-run; cross-file span merging needs a clock
  that only ever moves forward. Required on every v2 event.
- spans may carry ``trace_id`` / ``span_id`` / ``parent_span_id`` (the
  request-tree identity) and ``tm0`` (the span's START on the emitting
  process's monotonic clock; the end is ``tm0 + dur_ms/1e3`` — NOT
  ``tm``, which stamps the WRITE: a slow-kept trace's buffered spans
  are all written at the flush, long after they ended). A span with
  ``trace_id`` but no ``parent_span_id`` is a trace ROOT.
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator

SCHEMA_VERSION = 2

# versions this reader accepts; writers always emit SCHEMA_VERSION
READABLE_VERSIONS = (1, 2)

KINDS = ("meta", "counter", "gauge", "histogram", "span")

# kinds that must carry a numeric "value"
_VALUE_KINDS = ("counter", "gauge", "histogram")

_TAG_SCALARS = (str, int, float, bool, type(None))

# v2 trace-identity fields (optional; span events only for the ids)
TRACE_FIELDS = ("trace_id", "span_id", "parent_span_id")


class SchemaError(ValueError):
    """An event violates the telemetry JSONL schema."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SchemaError(msg)


def validate_event(ev: dict) -> dict:
    """Validate one decoded event against the schema; returns it.

    Raises SchemaError naming the first violated constraint — the
    round-trip test (tests/test_telemetry.py) feeds every writer-emitted
    event through this, so writer and schema cannot drift apart.
    """
    _require(isinstance(ev, dict), f"event is not an object: {type(ev)}")
    v = ev.get("v")
    _require(v in READABLE_VERSIONS,
             f"schema version {v!r} not in {READABLE_VERSIONS}")
    _require(isinstance(ev.get("t"), (int, float)),
             f"missing/non-numeric timestamp 't': {ev.get('t')!r}")
    if v >= 2:
        _require(isinstance(ev.get("tm"), (int, float))
                 and not isinstance(ev.get("tm"), bool),
                 f"v2 event needs a numeric monotonic stamp 'tm': "
                 f"{ev.get('tm')!r}")
    _require(isinstance(ev.get("pid"), int),
             f"missing/non-int 'pid': {ev.get('pid')!r}")
    _require(isinstance(ev.get("pi"), int),
             f"missing/non-int process index 'pi': {ev.get('pi')!r}")
    kind = ev.get("kind")
    _require(kind in KINDS, f"unknown kind {kind!r} (want one of {KINDS})")
    name = ev.get("name")
    _require(isinstance(name, str) and name != "",
             f"missing/empty 'name': {name!r}")
    if kind in _VALUE_KINDS:
        _require(isinstance(ev.get("value"), (int, float))
                 and not isinstance(ev.get("value"), bool),
                 f"{kind} {name!r} needs a numeric 'value': "
                 f"{ev.get('value')!r}")
    if kind == "span":
        _require(isinstance(ev.get("dur_ms"), (int, float))
                 and not isinstance(ev.get("dur_ms"), bool),
                 f"span {name!r} needs a numeric 'dur_ms': "
                 f"{ev.get('dur_ms')!r}")
    if kind == "meta":
        _require(isinstance(ev.get("fields"), dict),
                 f"meta {name!r} needs a 'fields' object")
    for f in TRACE_FIELDS:
        if f in ev:
            _require(kind == "span",
                     f"{kind} {name!r} carries {f!r} — trace identity "
                     f"belongs to span events only")
            _require(isinstance(ev[f], str) and ev[f] != "",
                     f"span {name!r} has non-string/empty {f!r}: "
                     f"{ev[f]!r}")
    if "span_id" in ev or "parent_span_id" in ev:
        _require("trace_id" in ev,
                 f"span {name!r} has span ids but no 'trace_id'")
    if "tm0" in ev:
        _require(kind == "span"
                 and isinstance(ev["tm0"], (int, float))
                 and not isinstance(ev["tm0"], bool),
                 f"{kind} {name!r}: 'tm0' must be a numeric span-start "
                 f"monotonic stamp on a span event: {ev.get('tm0')!r}")
    tags = ev.get("tags")
    if tags is not None:
        _require(isinstance(tags, dict), f"'tags' is not an object: {tags!r}")
        for k, v in tags.items():
            _require(isinstance(k, str), f"non-string tag key {k!r}")
            _require(isinstance(v, _TAG_SCALARS),
                     f"tag {k!r} has non-scalar value {v!r}")
    return ev


def iter_events(lines: Iterable[str], strict: bool = True) -> Iterator[dict]:
    """Decode + validate a JSONL stream line by line.

    A trailing UNDECODABLE line (truncated JSON — the crash-mid-write
    signature) is always skipped. A line that decodes but violates the
    schema is never a crash tail — a partial write cannot produce valid
    JSON with wrong fields — so it raises (strict) or is skipped
    (strict=False) wherever it appears."""
    pending_decode: Exception | None = None
    for line in lines:
        line = line.strip()
        if not line:
            continue
        # an earlier line failed to DECODE but was not the last line —
        # that is corruption, not a crash tail
        if pending_decode is not None and strict:
            raise pending_decode
        pending_decode = None
        try:
            ev = json.loads(line)
        except ValueError as e:
            pending_decode = SchemaError(f"undecodable line: {e}")
            continue
        try:
            yield validate_event(ev)
        except SchemaError:
            if strict:
                raise
    # swallow pending_decode: the stream ended on it -> crash tail


def load_events(path: str, strict: bool = True) -> list[dict]:
    """All validated events from one telemetry JSONL file."""
    with open(path) as f:
        return list(iter_events(f, strict=strict))
