"""ctypes bindings for the native data-path library (native/pert_native.cpp).

The shared library is built on demand with `make -C native` (g++ is in the
image; pybind11 is not, hence ctypes over a C ABI). Everything degrades
gracefully: `available()` is False when the toolchain or library is missing
and callers fall back to the pure-numpy path in graphs/construct.py.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpertnative.so")
_lib = None
_build_attempted = False


def _ensure_built() -> bool:
    global _build_attempted
    if os.path.isfile(_LIB_PATH):
        return True
    if _build_attempted:
        return False
    _build_attempted = True
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR],
                       check=True, capture_output=True, timeout=120)
        return os.path.isfile(_LIB_PATH)
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native library build failed (%s); using numpy path", e)
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not _ensure_built():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:  # stale/corrupt/wrong-arch .so
        log.warning("native library load failed (%s); using numpy path", e)
        return None
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.pert_build_batch.argtypes = [
        i64p, i64p, i64p, i64p, f64p, f64p,   # rows
        i64p, i64p, ctypes.c_int64,           # offsets, roots, n_traces
        i32p, i32p, i32p, i32p, f32p,         # outputs
        i64p, i64p,                           # node/edge offsets
    ]
    lib.pert_build_batch.restype = ctypes.c_int
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def build_runtime_graphs(pre, table, graph_type: str):
    """Native-accelerated drop-in for construct.build_runtime_graphs.

    Sanitization stays in pandas (vectorized already); the per-trace PERT
    expansion — the reference's Python-loop hot spot — runs in C++ over all
    representative traces in one call. Span graphs use the numpy path (its
    work is a vectorized np.unique; nothing to win).
    """
    from pertgnn_tpu.graphs import construct as C

    if graph_type != "pert":
        return C.build_runtime_graphs(pre, table, graph_type,
                                      use_native=False)
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")

    reps = set(table.runtime2trace.values())
    rep_spans = pre.spans[pre.spans["traceid"].isin(reps)]
    sanitized, tr_roots = C.sanitize_traces(rep_spans)
    # order rows by the runtime2trace iteration order, one block per trace
    runtime_ids = list(table.runtime2trace.keys())
    trace_order = [table.runtime2trace[r] for r in runtime_ids]
    pos = {t: i for i, t in enumerate(trace_order)}
    order_key = sanitized["traceid"].map(pos).to_numpy()
    perm = np.argsort(order_key, kind="stable")
    s = sanitized.iloc[perm]
    sizes = np.bincount(order_key, minlength=len(trace_order)).astype(np.int64)
    row_offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    total = int(sizes.sum())

    def col(name, dtype):
        return np.ascontiguousarray(s[name].to_numpy(), dtype)

    um = col("um", np.int64)
    dm = col("dm", np.int64)
    iface = col("interface", np.int64)
    rpctype = col("rpctype", np.int64)
    ts = col("timestamp", np.float64)
    end_ts = col("endTimestamp", np.float64)
    roots_a = np.asarray([tr_roots[t] for t in trace_order], dtype=np.int64)

    cap_e = 4 * total
    cap_n = 4 * total + len(trace_order)
    senders = np.empty(cap_e, np.int32)
    receivers = np.empty(cap_e, np.int32)
    edge_attr = np.empty(cap_e * 4, np.int32)
    ms_id = np.empty(cap_n, np.int32)
    node_depth = np.empty(cap_n, np.float32)
    node_off = np.empty(len(trace_order) + 1, np.int64)
    edge_off = np.empty(len(trace_order) + 1, np.int64)

    rc = lib.pert_build_batch(
        um, dm, iface, rpctype, ts, end_ts, row_offsets, roots_a,
        len(trace_order), senders, receivers, edge_attr, ms_id, node_depth,
        node_off, edge_off)
    if rc != 0:
        raise RuntimeError(f"pert_build_batch failed with {rc}")

    out = {}
    for i, runtime_id in enumerate(runtime_ids):
        nlo, nhi = int(node_off[i]), int(node_off[i + 1])
        elo, ehi = int(edge_off[i]), int(edge_off[i + 1])
        # edges within a trace are local; offsets already per-trace (each
        # pert_build call numbers nodes from 0)
        out[runtime_id] = C.GraphSpec(
            senders=senders[elo:ehi].copy(),
            receivers=receivers[elo:ehi].copy(),
            edge_attr=edge_attr[elo * 4:ehi * 4].reshape(-1, 4).copy(),
            ms_id=ms_id[nlo:nhi].copy(),
            node_depth=node_depth[nlo:nhi].copy(),
            num_nodes=nhi - nlo,
        )
    return out
