from pertgnn_tpu.native import bindings
