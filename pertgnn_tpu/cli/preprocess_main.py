"""Offline preprocessing CLI (the reference's `python preprocess.py`).

    python -m pertgnn_tpu.cli.preprocess_main --data_dir data --artifact_dir processed
    python -m pertgnn_tpu.cli.preprocess_main --synthetic --min_traces_per_entry 10

Idempotent: a complete artifact cache is reused (reference idiom,
preprocess.py:192-199).
"""

from __future__ import annotations

import argparse

from pertgnn_tpu.cli.common import (add_aot_flags, add_ingest_flags,
                                    add_telemetry_flags, get_frames,
                                    setup_compile_cache, setup_telemetry)
from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.io import artifacts_present, preprocess_cached
from pertgnn_tpu.utils.logging import setup_logging


def main(argv=None) -> None:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    add_telemetry_flags(p)
    add_aot_flags(p)
    args = p.parse_args(argv)
    bus = setup_telemetry(args, "preprocess_main")
    # ingest itself never compiles, but a shared --compile_cache_dir in a
    # pipeline script must not be a parse error on this CLI
    setup_compile_cache(args)
    if args.arena_cache_dir:
        # the arena store keys on model/graph fields this CLI does not
        # parse; the dataset-building CLIs populate it on their first
        # (cold) run — accepting the flag here keeps one shared flag set
        # valid across a whole pipeline script
        print("note: --arena_cache_dir is populated by the first "
              "train/serve/predict run over these artifacts (this CLI "
              "only produces the L0-L2 artifacts)")
    cfg = IngestConfig(min_traces_per_entry=args.min_traces_per_entry,
                       min_resource_coverage=args.min_resource_coverage)
    if artifacts_present(args.artifact_dir):
        print(f"artifact cache complete at {args.artifact_dir}; nothing to do")
        return
    from pertgnn_tpu.cli.common import get_frames_with_ingest_cfg
    from pertgnn_tpu.ingest.io import save_stream_vocabs
    spans, resources, cfg, vocabs = get_frames_with_ingest_cfg(args, cfg)
    if vocabs is not None:
        save_stream_vocabs(args.artifact_dir, vocabs)
    pre, table = preprocess_cached(args.artifact_dir, spans, resources,
                                   cfg=cfg)
    print(f"preprocessed: {pre.stats}")
    print(f"traces: {len(table.meta)}, entries: {len(table.entry2runtimes)}, "
          f"runtime patterns: {len(table.runtime2trace)}")
    bus.flush()


if __name__ == "__main__":
    main()
