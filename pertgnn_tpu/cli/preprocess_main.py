"""Offline preprocessing CLI (the reference's `python preprocess.py`).

    python -m pertgnn_tpu.cli.preprocess_main --data_dir data --artifact_dir processed
    python -m pertgnn_tpu.cli.preprocess_main --synthetic --min_traces_per_entry 10

Idempotent: a complete artifact cache is reused (reference idiom,
preprocess.py:192-199).
"""

from __future__ import annotations

import argparse

from pertgnn_tpu.cli.common import add_ingest_flags, get_frames
from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.io import artifacts_present, preprocess_cached
from pertgnn_tpu.utils.logging import setup_logging


def main(argv=None) -> None:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    args = p.parse_args(argv)
    cfg = IngestConfig(min_traces_per_entry=args.min_traces_per_entry,
                       min_resource_coverage=args.min_resource_coverage)
    if artifacts_present(args.artifact_dir):
        print(f"artifact cache complete at {args.artifact_dir}; nothing to do")
        return
    if getattr(args, "stream_factorize", False):
        if args.synthetic:
            p.error("--stream_factorize reads on-disk shards; it cannot "
                    "combine with --synthetic (write the synthetic corpus "
                    "to CSVs and pass --data_dir instead)")
        import numpy as np

        from pertgnn_tpu.ingest.io import load_raw_csvs_streaming
        spans, resources, cfg, vocabs = load_raw_csvs_streaming(
            args.data_dir, cfg)
        # persist code -> raw-string recovery next to the artifacts —
        # without it the cached ids are permanently unmappable back to
        # the real dataset identifiers
        import os
        os.makedirs(args.artifact_dir, exist_ok=True)
        np.savez(os.path.join(args.artifact_dir, "stream_vocabs.npz"),
                 **{name: np.asarray(v.items, dtype=object)
                    for name, v in vocabs.items()})
    else:
        spans, resources = get_frames(args)
    pre, table = preprocess_cached(args.artifact_dir, spans, resources,
                                   cfg=cfg)
    print(f"preprocessed: {pre.stats}")
    print(f"traces: {len(table.meta)}, entries: {len(table.entry2runtimes)}, "
          f"runtime patterns: {len(table.runtime2trace)}")


if __name__ == "__main__":
    main()
