"""Inference CLI: per-trace latency predictions from a trained checkpoint.

    python -m pertgnn_tpu.cli.predict_main --artifact_dir processed \
        --graph_type pert --checkpoint_dir ckpts --out predictions.csv
    python -m pertgnn_tpu.cli.predict_main --synthetic ... --split all

Writes one CSV row per trace: traceid (factorized code — joinable back to
raw ids via the persisted stream vocabs when --stream_factorize was used),
entry_id, runtime_id, ts_bucket, split, y_true, y_pred. The reference has
no inference path at all — its predictions exist only inside test()'s
metric loop (/root/reference/pert_gnn.py:254-294).

The restore target comes from train/loop.restore_target_state, the same
construction fit() checkpoints — tree-shape compatibility by shared code,
not by parallel maintenance.
"""

from __future__ import annotations

import argparse

import numpy as np
import pandas as pd

from pertgnn_tpu.batching.dataset import split_indices
from pertgnn_tpu.cli.common import (add_aot_flags, add_ingest_flags,
                                    add_lens_flags, add_model_train_flags,
                                    add_serve_flags,
                                    add_telemetry_flags, apply_platform_env,
                                    build_dataset_cached, config_from_args,
                                    load_or_ingest_artifacts,
                                    setup_compile_cache, setup_telemetry)
from pertgnn_tpu.train.loop import restore_target_state
from pertgnn_tpu.train.predict import (make_predict_step, predict_split,
                                       predict_split_served)
from pertgnn_tpu.utils.logging import setup_logging

_SPLITS = ("train", "valid", "test")


def _check_train_config(p, ckpt, cfg, allow_mismatch: bool) -> None:
    """Cross-check output-critical fields against the sidecar the
    training CLI saved: a label_scale / graph_type / architecture /
    featurization mismatch restores CLEANLY (tree shapes are blind to
    semantics) and then silently mis-predicts — exactly the failure this
    turns into an error. Older checkpoints (no sidecar, or predating a
    config field) get warnings, not walls; --allow_config_mismatch
    downgrades everything to warnings."""
    import logging

    from pertgnn_tpu.train.checkpoint import config_mismatches

    log = logging.getLogger(__name__)
    saved = ckpt.load_config_dict()
    if saved is None:
        log.warning(
            "checkpoint has no train_config.json sidecar (pre-sidecar "
            "run?) — cannot verify label_scale/graph_type/model flags "
            "match training; predictions are silently wrong if they "
            "don't")
        return
    mism, unknown = config_mismatches(saved, cfg)
    for key in unknown:
        log.warning("sidecar predates config field %s — cannot verify it "
                    "matches training", key)
    # Split-layout drift is a WARNING, not a wall: max_traces / split
    # change WHICH traces land in which positional split, so rows tagged
    # "test" here may have been training rows — per-trace predictions
    # stay valid, but any held-out-metric claim over them does not.
    saved_data = saved.get("data") or {}
    for field, ours_val in (("max_traces", cfg.data.max_traces),
                            ("split", list(cfg.data.split))):
        if field in saved_data:
            theirs = saved_data[field]
            theirs_n = list(theirs) if isinstance(theirs, (list, tuple)) \
                else theirs
            if theirs_n != ours_val:
                log.warning(
                    "data.%s differs from the training run (trained=%r "
                    "vs now=%r): the positional splits no longer match "
                    "— split labels in the output CSV are NOT the "
                    "training run's held-out sets", field, theirs,
                    ours_val)
    if mism:
        detail = "; ".join(f"{k}: trained={a!r} vs now={b!r}"
                           for k, a, b in mism)
        if allow_mismatch:
            log.warning("config mismatch overridden "
                        "(--allow_config_mismatch): %s", detail)
        else:
            p.error("flags differ from the checkpoint's training run — "
                    f"predictions would be silently wrong: {detail} "
                    "(pass the training-time flags, or "
                    "--allow_config_mismatch to proceed anyway)")


def main(argv=None) -> None:
    setup_logging()
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    add_model_train_flags(p)
    add_serve_flags(p)
    add_lens_flags(p)
    add_telemetry_flags(p)
    add_aot_flags(p)
    p.add_argument("--split", default="test",
                   choices=(*_SPLITS, "all"),
                   help="which positional split(s) to predict")
    p.add_argument("--out", default="predictions.csv",
                   help="output CSV path")
    p.add_argument("--serve_bucketed", action="store_true",
                   help="route prediction through the serving engine's "
                        "bucketed AOT request path (serve/engine.py) "
                        "instead of the epoch packer — exercises exactly "
                        "what serve_main serves")
    args = p.parse_args(argv)
    if not args.checkpoint_dir:
        p.error("--checkpoint_dir is required: predictions come from a "
                "trained checkpoint (run train_main with --checkpoint_dir "
                "first)")
    bus = setup_telemetry(args, "predict_main")
    setup_compile_cache(args)
    cfg = config_from_args(args)

    # fail in seconds on a missing/typo'd checkpoint dir, BEFORE minutes
    # of ingest + dataset build + model init (latest_step is orbax's own
    # answer — no hand-rolled layout knowledge)
    from pertgnn_tpu.train.checkpoint import CheckpointManager
    ckpt = CheckpointManager(args.checkpoint_dir,
                             keep=args.checkpoint_keep)
    if ckpt.latest_step() is None:
        p.error(f"no checkpoint steps in {args.checkpoint_dir!r}")
    _check_train_config(p, ckpt, cfg, args.allow_config_mismatch)

    # the trace table is needed for the output rows (traceid/runtime_id)
    # regardless, so prediction loads the L0-L2 artifacts either way;
    # --arena_cache_dir still skips graph construction + mixture
    # collation + featurization on a warm hit
    pre, table = load_or_ingest_artifacts(args, cfg.ingest)
    dataset = build_dataset_cached(args, cfg, pre_table=(pre, table))

    model, state = restore_target_state(dataset, cfg)
    state, start_epoch = ckpt.maybe_restore(state)
    if start_epoch == 0:
        p.error(f"no checkpoint found in {args.checkpoint_dir}")

    # positional split ranges over the SAME meta slice build_dataset used
    meta = table.meta.iloc[:cfg.data.max_traces]
    parts = dict(zip(_SPLITS, split_indices(len(meta), cfg.data.split)))
    wanted = _SPLITS if args.split == "all" else (args.split,)
    engine = step = None
    if args.serve_bucketed:
        # one engine (= one warmed executable cache) for every split
        from pertgnn_tpu.serve.engine import InferenceEngine
        engine = InferenceEngine.from_dataset(dataset, cfg, state)
        if cfg.serve.warmup:
            engine.warmup()
    else:
        step = make_predict_step(model, cfg)  # one compile for every split
    frames = []
    for split in wanted:
        if engine is not None:
            pred = predict_split_served(dataset, cfg, state, split,
                                        engine=engine)
        else:
            pred = predict_split(dataset, cfg, state, split, step=step)
        rows = meta.iloc[parts[split]].copy()
        # the one link predict_split's internal assertion cannot see:
        # these meta rows must BE the rows build_dataset split — pin it
        if not np.array_equal(rows["y"].to_numpy(np.float32),
                              np.asarray(dataset.splits[split].ys,
                                         np.float32)):
            raise AssertionError(
                f"meta rows for '{split}' no longer match the dataset "
                "split — build_dataset's meta slicing changed without "
                "this CLI following")
        rows["split"] = split
        pred = np.asarray(pred, np.float32)
        if pred.ndim == 2:
            # multi-quantile head (ModelConfig.quantile_taus, lens/):
            # y_pred carries the PRIMARY column, plus one labeled
            # column per quantile level so the CSV keeps the vector
            from pertgnn_tpu.config import (primary_tau_index,
                                            resolve_quantile_taus)
            taus = resolve_quantile_taus(cfg.model, cfg.train.tau)
            pi = primary_tau_index(taus, cfg.train.tau)
            for i, t in enumerate(taus):
                rows[f"y_pred_q{t:g}"] = pred[:, i]
            rows["y_pred"] = pred[:, pi]
        else:
            rows["y_pred"] = pred
        frames.append(rows.rename(columns={"y": "y_true"}))
    out = pd.concat(frames, ignore_index=True)
    out.to_csv(args.out, index=False)
    print(f"wrote {len(out)} predictions "
          f"(epochs trained: {start_epoch}) to {args.out}")
    if engine is not None:
        import json
        print(json.dumps({"serve_stats": engine.publish_stats()}))
    bus.flush()


if __name__ == "__main__":
    main()
