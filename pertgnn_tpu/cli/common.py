"""Shared CLI plumbing: flags → Config.

Flag names track the reference's argparse block (pert_gnn.py:15-33) so
configs transfer verbatim; the three flags the reference declares but never
uses (`--log_steps`, `--use_sage`, `--runs` — SURVEY.md §5.6) are dropped.
New capability flags are grouped after the parity flags.
"""

from __future__ import annotations

import argparse
import os

from pertgnn_tpu.config import (ATTENTION_IMPLS, SERVE_DTYPES,
                                CompileCacheConfig, Config, DataConfig,
                                FleetConfig, IngestConfig, LensConfig,
                                ModelConfig, ParallelConfig, ScaleConfig,
                                ServeConfig, StreamConfig, TelemetryConfig,
                                TrainConfig)


def apply_platform_env() -> None:
    """Honor JAX_PLATFORMS even when a device plugin (e.g. the axon TPU
    tunnel) takes precedence over the env var — needed for virtual-device
    mesh runs (`JAX_PLATFORMS=cpu` +
    `--xla_force_host_platform_device_count=N`). No-op once a backend is
    initialized.

    When cpu is requested, the tunnel plugin's backend factory is also
    REMOVED: the plugin re-sets jax_platforms at interpreter start and
    its get_backend hook has been observed (round 5) initializing the
    tunnel backend anyway — which blocks forever inside the PJRT client
    constructor whenever the relay is half-open. A cpu-intended process
    must have no path that can dial the relay."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
        if all(p.strip() == "cpu" for p in want.split(",")):
            drop_relay_backend_factory()


def probe_backend_or_fallback(cache_path: str | None = None,
                              reprobe: bool = False) -> bool:
    """Poll the default accelerator backend (subprocess + timeout per
    attempt, pauses between — the relay flaps on minute timescales, so a
    single probe under-samples) and, on persistent failure, fall back to
    JAX_PLATFORMS=cpu with the full anti-hang hardening. Returns True if
    the fallback engaged.

    Guards only the flaky DEFAULT (JAX_PLATFORMS unset or the axon
    relay, which this environment presets); an explicit NON-axon choice
    is honored untouched — if it is broken the caller should fail
    loudly, not silently remeasure on CPU. Knobs: BENCH_PROBE_TIMEOUT /
    BENCH_PROBE_TRIES / BENCH_PROBE_PAUSE (shared with bench.py).

    With `cache_path`, the verdict is persisted and a FALLBACK verdict
    is reused for the round: BENCH_r05 burned 4x75 s re-timing-out
    IDENTICAL dead-relay probes before every fallback run in the same
    round. A cached fallback verdict younger than
    BENCH_PROBE_CACHE_TTL_S (default 3600 s) is adopted without
    probing; a cached healthy verdict never short-circuits (the backend
    is re-probed — success is cheap and staleness means a hang);
    `reprobe=True` (bench.py --reprobe) forces a fresh probe and
    overwrites the cache.

    A successful probe narrows but cannot close the hang window: the
    parent's own first backend touch can still catch a flap. Callers
    that must never block (the driver) should also run under a hard
    external timeout."""
    import json
    import subprocess
    import sys
    import time

    if os.environ.get("JAX_PLATFORMS", "axon") not in ("", "axon"):
        return False
    if cache_path and not reprobe:
        ttl = float(os.environ.get("BENCH_PROBE_CACHE_TTL_S", "3600"))
        try:
            with open(cache_path) as f:
                cached = json.load(f)
            age = time.time() - cached["probed_unix_time"]
            verdict = bool(cached["fallback"])
        except (OSError, ValueError, KeyError, TypeError):
            age = None  # absent/corrupt/foreign cache: probe fresh
        # Only a FALLBACK verdict is reusable: it short-circuits the
        # tries x timeout re-probe of a backend already known dead (the
        # BENCH_r05 4x75 s burn). A cached HEALTHY verdict is ignored —
        # the relay flaps on minute timescales, and adopting an hour-old
        # success would reopen the unbounded first-touch hang this probe
        # exists to prevent; re-verifying a live backend costs seconds.
        if age is not None and age < ttl and verdict:
            print(f"NOTE: backend-probe verdict reused from {cache_path} "
                  f"(fallback=True, {age:.0f}s old; "
                  f"--reprobe to force)", file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"
            apply_platform_env()
            return True
    timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
    tries = int(os.environ.get("BENCH_PROBE_TRIES", "4"))
    last = None
    fallback = False
    for attempt in range(tries):
        if attempt:
            time.sleep(int(os.environ.get("BENCH_PROBE_PAUSE", "10")))
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            break
        except Exception as e:
            last = e
            print(f"WARNING: accelerator backend probe "
                  f"{attempt + 1}/{tries} failed ({e!r})", file=sys.stderr)
    else:
        print(f"WARNING: all {tries} backend probes failed "
              f"(last: {last!r}); falling back to JAX_PLATFORMS=cpu",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        apply_platform_env()
        fallback = True
    if cache_path:
        try:
            os.makedirs(os.path.dirname(os.path.abspath(cache_path)),
                        exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"fallback": fallback,
                           "probed_unix_time": time.time(),
                           "tries": tries, "timeout_s": timeout_s}, f)
            os.replace(tmp, cache_path)
        except OSError as e:
            print(f"WARNING: could not cache probe verdict at "
                  f"{cache_path}: {e}", file=sys.stderr)
    return fallback


def drop_relay_backend_factory() -> None:
    """Remove the axon relay plugin's backend factory so a cpu-intended
    process has NO path that can dial the (possibly half-open) relay.
    Only the relay: popping built-in names (tpu, cuda) breaks later MLIR
    lowering-rule registration, which validates platforms against this
    registry. Shared by apply_platform_env and tests/conftest.py."""
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        # jax internals moved — the config-level platform selection
        # still applies, but it alone has NOT been sufficient against
        # the plugin's get_backend hook (round-5 observation), so say so
        import warnings
        warnings.warn(
            "could not remove the axon backend factory (jax internals "
            "changed?) — cpu-intended runs may hang if the relay plugin "
            "dials a wedged tunnel", RuntimeWarning, stacklevel=2)


def add_model_train_flags(p: argparse.ArgumentParser) -> None:
    # parity flags (reference defaults, pert_gnn.py:15-33)
    p.add_argument("--num_layers", type=int, default=1)
    p.add_argument("--hidden_channels", type=int, default=32)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--tau", type=float, default=0.5,
                   help="pinball-loss quantile level in (0, 1)")
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--batch_size", type=int, default=170)
    p.add_argument("--graph_type", choices=("span", "pert"), default="span")
    p.add_argument("--max_traces", type=int, default=100_000)
    # capability flags
    p.add_argument("--num_heads", type=int, default=1)
    p.add_argument("--label_scale", type=float, default=1.0)
    p.add_argument("--use_node_depth", action="store_true")
    p.add_argument("--use_edge_durations", action="store_true")
    p.add_argument("--nonnegative_pred", action="store_true")
    p.add_argument("--local_loss_weight", type=float, default=0.0)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--attn_dropout", type=float, default=0.0,
                   help="dropout on attention weights inside the conv")
    p.add_argument("--init_scheme", choices=("torch", "torch_full", "flax"),
                   default="torch",
                   help="Linear-kernel init: torch kaiming-uniform "
                        "(reference-faithful, default) or flax defaults")
    p.add_argument("--use_pallas_attention", action="store_true",
                   help="DEPRECATED alias for --attention_impl pallas")
    p.add_argument("--attention_impl", choices=ATTENTION_IMPLS,
                   default=ModelConfig.attention_impl,
                   help="conv hot-op implementation: segment (XLA "
                        "reference), pallas (fused flash-style kernel), "
                        "pallas_fused (+ fused skip/residual/BN-stats "
                        "epilogue), blocked_dense (masked dense matmuls "
                        "for small shape buckets; docs/GUIDE.md)")
    p.add_argument("--kernel_block_n", type=int,
                   default=ModelConfig.kernel_block_n,
                   help="Pallas kernel node-block tile size (128 = MXU "
                        "lane width; baked into compiled programs)")
    p.add_argument("--kernel_block_e", type=int,
                   default=ModelConfig.kernel_block_e,
                   help="Pallas kernel edge-block tile size")
    p.add_argument("--blocked_dense_max_cells", type=int,
                   default=ModelConfig.blocked_dense_max_cells,
                   help="blocked_dense admissibility: max (padded nodes x "
                        "padded edges) incidence cells per head before "
                        "the layer falls back to the segment path "
                        "(logged + counted)")
    p.add_argument("--vocab_headroom_entries", type=int,
                   default=ModelConfig.vocab_headroom_entries,
                   help="round the entry-embedding capacity UP to the "
                        "next multiple of this, so new entries arriving "
                        "on the stream (pertgnn_tpu/stream/) fit the "
                        "checkpointed embedding and continual training "
                        "warm-restarts; 0 = exact sizing (reference "
                        "parity)")
    p.add_argument("--quantile_taus", default="0.5",
                   help="comma-separated quantile levels of the global "
                        "head (e.g. 0.5,0.95,0.99 = p50/p95/p99 in one "
                        "forward, non-crossing by construction; "
                        "pertgnn_tpu/lens/). The default 0.5 is the "
                        "legacy single-tau mode where --tau is the "
                        "quantile level, byte-identical to pre-lens "
                        "behavior")
    p.add_argument("--missing_indicator_is_zero", action="store_true",
                   help="preprocess-time indicator convention (1=present) "
                        "instead of the live get_x convention (1=missing)")
    p.add_argument("--max_nodes_per_batch", type=int, default=0,
                   help="packed-batch node budget; 0 = derived from data")
    p.add_argument("--max_edges_per_batch", type=int, default=0,
                   help="packed-batch edge budget; 0 = derived from data")
    p.add_argument("--budget_headroom", type=float, default=1.1,
                   help="derived-budget head-room over mean-mixture * "
                        "batch_size (batching/pack.py derive_budget)")
    p.add_argument("--no_device_materialize", action="store_true",
                   help="disable chip-resident arenas + device-side batch "
                        "materialization (host-packed streaming instead)")
    p.add_argument("--arena_hbm_budget_gb", type=float, default=4.0,
                   help="HBM budget for chip-resident arenas; exceeding it "
                        "falls back to host packing; <=0 = unlimited")
    p.add_argument("--feature_all_stage_copies", action="store_true",
                   help="feature every PERT stage-copy of a microservice "
                        "(the reference's live get_x features only the "
                        "last copy — PARITY.md)")
    p.add_argument("--staged_epochs", choices=("auto", "on", "off"),
                   default="auto",
                   help="epoch-level recipe staging (one H2D per epoch): "
                        "auto = on for accelerator backends, off on CPU "
                        "where it measured slower (BENCH_r05 "
                        "staged_over_unstaged 0.956); on/off force it — "
                        "the resolved decision is logged and counted "
                        "(train.staging_decision)")
    p.add_argument("--no_stage_epoch_recipes", action="store_true",
                   help="back-compat alias for --staged_epochs off")
    p.add_argument("--prefetch_depth", type=int, default=2,
                   help="bounded double-buffered input prefetch depth "
                        "(batching/prefetch.py) on per-chunk streaming "
                        "paths; 0 = fully synchronous transfers")
    p.add_argument("--shard_edges", action="store_true",
                   help="giant-graph mode: shard each batch's edge set "
                        "over the mesh data axis (nodes replicated)")
    p.add_argument("--data_parallel", type=int, default=1,
                   help="mesh data axis size (1 = single device)")
    p.add_argument("--model_parallel", type=int, default=1)
    # multi-host (SURVEY.md §5.8): every process runs the same command with
    # its own --process_id; the mesh then spans all processes' devices
    p.add_argument("--coordinator_address", default="",
                   help="host:port of process 0 (multi-host runs)")
    p.add_argument("--num_processes", type=int, default=0,
                   help="total process count (0/1 = single-process)")
    p.add_argument("--process_id", type=int, default=-1,
                   help="this process's rank in a multi-host run")
    p.add_argument("--checkpoint_dir", default="")
    p.add_argument("--checkpoint_keep", type=int, default=3)
    p.add_argument("--allow_config_mismatch", action="store_true",
                   help="downgrade the checkpoint config-sidecar "
                        "cross-check (label_scale/graph_type/model "
                        "fields at resume and inference) from an error "
                        "to a warning")
    p.add_argument("--profile_dir", default="",
                   help="write a jax.profiler trace of epoch 2 here")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--scan_chunk", type=int, default=16,
                   help="train/eval steps fused into one dispatched "
                        "lax.scan program; 1 disables scan fusion")


def add_serve_flags(p: argparse.ArgumentParser) -> None:
    """Serving-engine knobs (ServeConfig) — serve_main and predict_main's
    bucketed path."""
    p.add_argument("--bucket_growth", type=float,
                   default=ServeConfig.bucket_growth,
                   help="geometric growth of the serving bucket ladder "
                        "(serve/buckets.py); 2.0 = powers-of-two rungs")
    p.add_argument("--min_bucket_nodes", type=int,
                   default=ServeConfig.min_bucket_nodes,
                   help="smallest ladder rung's node capacity (rounded "
                        "up to multiples of 128 for TPU lane alignment; "
                        "was config-only until the graftlint "
                        "flag-config-drift pass flagged it)")
    p.add_argument("--min_bucket_edges", type=int,
                   default=ServeConfig.min_bucket_edges,
                   help="smallest ladder rung's edge capacity")
    p.add_argument("--max_graphs_per_batch", type=int,
                   default=ServeConfig.max_graphs_per_batch,
                   help="graph slots per serving microbatch")
    p.add_argument("--flush_deadline_ms", type=float,
                   default=ServeConfig.flush_deadline_ms,
                   help="microbatch queue: max wait for co-arriving "
                        "requests before a batch is flushed; 0 = dispatch "
                        "per request")
    p.add_argument("--no_serve_warmup", action="store_true",
                   help="skip AOT-compiling the bucket ladder at engine "
                        "construction (first request per bucket then pays "
                        "the compile)")
    # fault-tolerance knobs (serve/queue.py, docs/RELIABILITY.md)
    p.add_argument("--max_pending", type=int,
                   default=ServeConfig.max_pending,
                   help="admission control: max queued requests; submit "
                        "past it fast-fails with QueueFull (serve.shed)")
    p.add_argument("--request_deadline_ms", type=float,
                   default=ServeConfig.request_deadline_ms,
                   help="per-request deadline: undispatched past it, the "
                        "future resolves with DeadlineExceeded; 0 = none")
    p.add_argument("--dispatch_timeout_s", type=float,
                   default=ServeConfig.dispatch_timeout_s,
                   help="dispatch watchdog: abandon an engine call wedged "
                        "past this, mark the engine unhealthy, attempt "
                        "one rebuild-from-AOT-store recovery; 0 = no "
                        "watchdog (engine calls run inline)")
    p.add_argument("--quarantine_threshold", type=int,
                   default=ServeConfig.quarantine_threshold,
                   help="reject an entry at submit after it poisoned this "
                        "many microbatches (bisect-isolated)")
    p.add_argument("--no_overlap_dispatch", action="store_true",
                   help="disable overlapped serve dispatch (pack the "
                        "next microbatch while the device computes the "
                        "current one, one batch in flight); dispatches "
                        "then wait synchronously")
    p.add_argument("--serve_dtype", choices=SERVE_DTYPES,
                   default=ServeConfig.serve_dtype,
                   help="quantized serve tier: f32 (as trained), bf16 "
                        "(bf16 activations), int8 (bf16 activations + "
                        "int8 weights dequantized in-graph); quality "
                        "exit-code-gated by benchmarks/serve_bench.py "
                        "(docs/GUIDE.md)")
    p.add_argument("--transport", choices=("json", "binary", "shm"),
                   default=FleetConfig.transport,
                   help="router<->worker wire (fleet/wire.py, "
                        "docs/GUIDE.md §14): json = the legacy "
                        "JSON-over-HTTP wire (default, byte-identical "
                        "behavior), binary = the versioned graftwire "
                        "frame codec over pooled HTTP, shm = binary "
                        "frames over same-host shared-memory rings "
                        "(negotiated at probe time; skewed/cross-host "
                        "workers degrade loudly to HTTP — counter "
                        "transport.fallback); predictions are "
                        "bit-identical across all three "
                        "(benchmarks/wire_bench.py exit-asserts it)")


def add_lens_flags(p: argparse.ArgumentParser) -> None:
    """Distributional / what-if serving knobs (LensConfig,
    pertgnn_tpu/lens/) — the serving CLIs' lens surface (serve_main,
    fleet_main, predict_main)."""
    p.add_argument("--lens_local", action="store_true",
                   default=LensConfig.lens_local,
                   help="warm + serve the attribution (local-pred-"
                        "returning) rung programs next to the standard "
                        "ladder; off = attribution requests are refused "
                        "at submit (LensDisabled) so nothing compiles "
                        "on the request path (docs/GUIDE.md §13)")
    p.add_argument("--lens_top_k", type=int,
                   default=LensConfig.lens_top_k,
                   help="cap on per-request top-k attribution rows "
                        "(larger requests are clamped, never refused)")


def lens_config_from_args(args: argparse.Namespace) -> LensConfig:
    """The ONE flags -> LensConfig mapping (same pattern as
    telemetry_config_from_args); config_from_args embeds it so the
    sidecar provenance and the live engine cannot drift."""
    return LensConfig(
        lens_local=getattr(args, "lens_local", LensConfig.lens_local),
        lens_top_k=getattr(args, "lens_top_k", LensConfig.lens_top_k))


def parse_quantile_taus(spec: str) -> tuple[float, ...]:
    """--quantile_taus "0.5,0.95,0.99" -> (0.5, 0.95, 0.99). Validation
    (ascending, in (0,1)) happens at the single resolution point
    (config.resolve_quantile_taus), not here — a config file can carry
    the same tuple without passing through this parser."""
    try:
        taus = tuple(float(t) for t in spec.split(",") if t.strip())
    except ValueError:
        raise SystemExit(f"--quantile_taus must be comma-separated "
                         f"floats; got {spec!r}")
    if not taus:
        raise SystemExit("--quantile_taus must name at least one level")
    return taus


def add_fleet_flags(p: argparse.ArgumentParser) -> None:
    """Replicated-fleet knobs (FleetConfig, pertgnn_tpu/fleet/) —
    cli/fleet_main.py's router/launcher surface."""
    p.add_argument("--num_workers", type=int,
                   default=FleetConfig.num_workers,
                   help="serve workers the launcher spawns (one "
                        "engine+queue stack each, warm from the shared "
                        "--compile_cache_dir/--arena_cache_dir)")
    p.add_argument("--worker_base_port", type=int,
                   default=FleetConfig.worker_base_port,
                   help="first worker HTTP port (worker i listens on "
                        "base+i); 0 = pick free ephemeral ports")
    p.add_argument("--router_flush_deadline_ms", type=float,
                   default=FleetConfig.router_flush_deadline_ms,
                   help="router-side microbatch coalescing window "
                        "(fleet twin of --flush_deadline_ms)")
    p.add_argument("--router_max_pending", type=int,
                   default=FleetConfig.max_pending,
                   help="front-door admission control: max queued "
                        "requests before submit fast-fails with "
                        "QueueFull (router.shed)")
    p.add_argument("--router_request_deadline_ms", type=float,
                   default=FleetConfig.request_deadline_ms,
                   help="per-request deadline at the door: shed at "
                        "submit when no worker's predicted completion "
                        "can meet it (router.shed_infeasible); 0 = off")
    p.add_argument("--router_dispatch_timeout_s", type=float,
                   default=FleetConfig.dispatch_timeout_s,
                   help="per-dispatch worker-call timeout; past it the "
                        "worker counts as lost and its batch requeues")
    p.add_argument("--worker_slots", type=int,
                   default=FleetConfig.worker_slots,
                   help="outstanding microbatches per worker before the "
                        "router stops assigning it more")
    p.add_argument("--health_poll_interval_s", type=float,
                   default=FleetConfig.health_poll_interval_s,
                   help="membership: worker /healthz poll cadence")
    p.add_argument("--probe_lost_after", type=int,
                   default=FleetConfig.probe_lost_after,
                   help="consecutive failed probes before a member is "
                        "excluded (transport failures exclude "
                        "immediately)")
    p.add_argument("--latency_ewma_alpha", type=float,
                   default=FleetConfig.latency_ewma_alpha,
                   help="EWMA smoothing of the per-worker batch-latency "
                        "estimate feeding least-loaded dispatch")
    p.add_argument("--max_requeues", type=int,
                   default=FleetConfig.max_requeues,
                   help="times one request may requeue (worker loss) "
                        "before the router fails it with the last error")
    p.add_argument("--hedge_quantile_ms", type=float,
                   default=FleetConfig.hedge_quantile_ms,
                   help="hedged dispatch: re-dispatch a microbatch "
                        "still running past this many ms to a second "
                        "worker (first answer wins — bit-safe); 0 "
                        "defers to --hedge_quantile")
    p.add_argument("--hedge_quantile", type=float,
                   default=FleetConfig.hedge_quantile,
                   help="adaptive hedge threshold: hedge past the "
                        "rolling q-quantile of recent batch round "
                        "trips (in (0,1); both hedge flags 0 = "
                        "hedging off)")
    p.add_argument("--brownout_enter_ratio", type=float,
                   default=FleetConfig.brownout_enter_ratio,
                   help="pending-occupancy ratio at which the router "
                        "browns out best-effort traffic (downgraded "
                        "to the cheapest ladder rung before anything "
                        "is shed); <= 0 disables brownout")
    p.add_argument("--brownout_exit_ratio", type=float,
                   default=FleetConfig.brownout_exit_ratio,
                   help="occupancy below which brownout exits "
                        "(hysteresis); <= 0 = half the enter ratio")
    p.add_argument("--autoscale_max_spares", type=int,
                   default=FleetConfig.autoscale_max_spares,
                   help="elastic warm spares: max spare workers the "
                        "autoscale controller may spawn warm from the "
                        "shared AOT/arena stores; 0 = autoscale off")
    p.add_argument("--autoscale_up_ms", type=float,
                   default=FleetConfig.autoscale_up_ms,
                   help="router.queue_wait (ms) above which a spare "
                        "spawns (after --autoscale_hold_s of signal)")
    p.add_argument("--autoscale_down_ms", type=float,
                   default=FleetConfig.autoscale_down_ms,
                   help="router.queue_wait (ms) below which the newest "
                        "spare retires after --autoscale_cooldown_s "
                        "of sustained calm")
    p.add_argument("--autoscale_hold_s", type=float,
                   default=FleetConfig.autoscale_hold_s,
                   help="seconds the up-signal must hold before a "
                        "spare spawns")
    p.add_argument("--autoscale_cooldown_s", type=float,
                   default=FleetConfig.autoscale_cooldown_s,
                   help="seconds of calm before the newest spare "
                        "retires")
    p.add_argument("--shm_ring_slots", type=int,
                   default=FleetConfig.shm_ring_slots,
                   help="slots per shared-memory ring direction "
                        "(--transport shm; fleet/shmring.py)")
    p.add_argument("--shm_slot_bytes", type=int,
                   default=FleetConfig.shm_slot_bytes,
                   help="payload budget per ring slot; an oversize "
                        "frame falls back to HTTP for that call "
                        "(counter transport.fallback)")
    p.add_argument("--memo_capacity_bytes", type=int,
                   default=FleetConfig.memo_capacity_bytes,
                   help="router prediction-memo byte budget "
                        "(fleet/memo.py: content-keyed LRU over "
                        "wire-encoded rows, retired atomically at a "
                        "rollout flip; counters memo.*); 0 = memo off")


def fleet_config_from_args(args: argparse.Namespace) -> FleetConfig:
    """The ONE flags -> FleetConfig mapping (same pattern as
    telemetry_config_from_args); config_from_args embeds it so the
    sidecar provenance and the live router cannot drift."""
    return FleetConfig(
        num_workers=getattr(args, "num_workers",
                            FleetConfig.num_workers),
        worker_base_port=getattr(args, "worker_base_port",
                                 FleetConfig.worker_base_port),
        router_flush_deadline_ms=getattr(
            args, "router_flush_deadline_ms",
            FleetConfig.router_flush_deadline_ms),
        max_pending=getattr(args, "router_max_pending",
                            FleetConfig.max_pending),
        request_deadline_ms=getattr(args, "router_request_deadline_ms",
                                    FleetConfig.request_deadline_ms),
        dispatch_timeout_s=getattr(args, "router_dispatch_timeout_s",
                                   FleetConfig.dispatch_timeout_s),
        worker_slots=getattr(args, "worker_slots",
                             FleetConfig.worker_slots),
        health_poll_interval_s=getattr(
            args, "health_poll_interval_s",
            FleetConfig.health_poll_interval_s),
        probe_lost_after=getattr(args, "probe_lost_after",
                                 FleetConfig.probe_lost_after),
        latency_ewma_alpha=getattr(args, "latency_ewma_alpha",
                                   FleetConfig.latency_ewma_alpha),
        max_requeues=getattr(args, "max_requeues",
                             FleetConfig.max_requeues),
        hedge_quantile_ms=getattr(args, "hedge_quantile_ms",
                                  FleetConfig.hedge_quantile_ms),
        hedge_quantile=getattr(args, "hedge_quantile",
                               FleetConfig.hedge_quantile),
        brownout_enter_ratio=getattr(args, "brownout_enter_ratio",
                                     FleetConfig.brownout_enter_ratio),
        brownout_exit_ratio=getattr(args, "brownout_exit_ratio",
                                    FleetConfig.brownout_exit_ratio),
        autoscale_max_spares=getattr(args, "autoscale_max_spares",
                                     FleetConfig.autoscale_max_spares),
        autoscale_up_ms=getattr(args, "autoscale_up_ms",
                                FleetConfig.autoscale_up_ms),
        autoscale_down_ms=getattr(args, "autoscale_down_ms",
                                  FleetConfig.autoscale_down_ms),
        autoscale_hold_s=getattr(args, "autoscale_hold_s",
                                 FleetConfig.autoscale_hold_s),
        autoscale_cooldown_s=getattr(args, "autoscale_cooldown_s",
                                     FleetConfig.autoscale_cooldown_s),
        transport=getattr(args, "transport", FleetConfig.transport),
        shm_ring_slots=getattr(args, "shm_ring_slots",
                               FleetConfig.shm_ring_slots),
        shm_slot_bytes=getattr(args, "shm_slot_bytes",
                               FleetConfig.shm_slot_bytes),
        memo_capacity_bytes=getattr(args, "memo_capacity_bytes",
                                    FleetConfig.memo_capacity_bytes))


def add_aot_flags(p: argparse.ArgumentParser) -> None:
    """Cold-start / compile-cache knobs (CompileCacheConfig,
    pertgnn_tpu/aot/) — shared by ALL CLIs and bench.py: any entry point
    that compiles can persist and replay its executables."""
    p.add_argument("--compile_cache_dir", default="",
                   help="persist compiled executables here (xla/ = JAX's "
                        "persistent compilation cache; exe/ = serialized "
                        "serve-rung executables) so later processes skip "
                        "cold-start compilation; empty = off "
                        "(docs/GUIDE.md 'Precompile workflow')")
    p.add_argument("--aot_min_compile_time_s", type=float, default=0.0,
                   help="only persist XLA cache entries whose compile "
                        "took at least this long; 0 caches everything")
    p.add_argument("--no_serialize_executables", action="store_true",
                   help="skip the serialized serve-executable store "
                        "(persistent XLA cache only)")


def aot_config_from_args(args: argparse.Namespace) -> CompileCacheConfig:
    """The ONE flags -> CompileCacheConfig mapping (same pattern as
    telemetry_config_from_args): config_from_args embeds it and
    setup_compile_cache enables the live cache from it."""
    return CompileCacheConfig(
        cache_dir=getattr(args, "compile_cache_dir", ""),
        min_compile_time_s=getattr(args, "aot_min_compile_time_s", 0.0),
        serialize_executables=not getattr(args, "no_serialize_executables",
                                          False))


def setup_compile_cache(args: argparse.Namespace) -> CompileCacheConfig:
    """Enable the persistent compilation cache from parsed flags (no-op
    when --compile_cache_dir is empty). Call AFTER apply_platform_env
    and BEFORE anything compiles — cache entries are keyed per backend,
    so the platform decision must already be final."""
    from pertgnn_tpu.aot import enable_compile_cache

    cfg = aot_config_from_args(args)
    enable_compile_cache(cfg)
    return cfg


def add_telemetry_flags(p: argparse.ArgumentParser) -> None:
    """Telemetry-bus + logging knobs — shared by ALL CLIs (the bus is
    process-wide; any entry point can produce a JSONL stream)."""
    p.add_argument("--telemetry_dir", default="",
                   help="write schema-versioned telemetry JSONL here "
                        "(docs/OBSERVABILITY.md); empty = telemetry off")
    p.add_argument("--telemetry_level", default="basic",
                   choices=("off", "basic", "trace"),
                   help="bus verbosity: basic = run/epoch granularity, "
                        "trace adds per-chunk / per-request events")
    p.add_argument("--tensorboard", action="store_true",
                   help="mirror scalar telemetry to a TensorBoard sink "
                        "under <telemetry_dir>/tb (needs tensorboardX)")
    p.add_argument("--trace_sample_rate", type=float, default=0.1,
                   help="distributed request tracing: head-sampling "
                        "probability per request (trace level only; "
                        "tools/graftscope merges the spans)")
    p.add_argument("--trace_slow_ms", type=float, default=250.0,
                   help="always-keep override: an unsampled request "
                        "slower than this flushes its spans anyway "
                        "(tail exemplars survive low sample rates); "
                        "<= 0 disables")
    p.add_argument("--telemetry_rotate_mb", type=float, default=0.0,
                   help="rotate the telemetry JSONL into .partN.jsonl "
                        "siblings past this many MiB (long-lived "
                        "fleet/stream runs); 0 = one unbounded file")
    p.add_argument("--log_level", default="",
                   help="logging level name (DEBUG/INFO/...); default: "
                        "$PERTGNN_LOG_LEVEL or INFO")


def telemetry_config_from_args(args: argparse.Namespace) -> TelemetryConfig:
    """The ONE flags -> TelemetryConfig mapping: config_from_args embeds
    it in the Config (checkpoint-sidecar provenance) and setup_telemetry
    configures the live bus from it, so the two cannot drift."""
    return TelemetryConfig(
        telemetry_dir=getattr(args, "telemetry_dir", ""),
        telemetry_level=getattr(args, "telemetry_level", "basic"),
        tensorboard=getattr(args, "tensorboard", False),
        trace_sample_rate=getattr(args, "trace_sample_rate", 0.1),
        trace_slow_ms=getattr(args, "trace_slow_ms", 250.0),
        telemetry_rotate_mb=getattr(args, "telemetry_rotate_mb", 0.0))


def setup_telemetry(args: argparse.Namespace, cli: str):
    """Install the process-wide bus from parsed flags (and apply
    --log_level). Returns the bus. Call AFTER apply_platform_env so the
    writer's process-index stamp can see an initialized backend."""
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.utils.logging import set_level

    if getattr(args, "log_level", ""):
        set_level(args.log_level)
    return telemetry.configure_from_config(
        telemetry_config_from_args(args), run_meta={"cli": cli})


def add_ingest_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--min_traces_per_entry", type=int, default=100)
    p.add_argument("--min_resource_coverage", type=float, default=0.6)
    p.add_argument("--stream_factorize", action="store_true",
                   help="200GB-scale loader: factorize strings per shard "
                        "against incremental vocabularies so RAM holds "
                        "only numeric columns; ids are isomorphic (not "
                        "equal) to the exact path's (ingest/io.py)")
    p.add_argument("--ingest_workers", type=int, default=1,
                   help="worker processes for the --stream_factorize "
                        "shard parse+factorize fan-out; the parent's "
                        "shard-order vocab merge keeps results identical "
                        "to workers=1")
    p.add_argument("--synthetic", action="store_true",
                   help="use the synthetic generator instead of raw CSVs")
    p.add_argument("--synthetic_entries", type=int, default=8)
    p.add_argument("--synthetic_traces_per_entry", type=int, default=300)
    p.add_argument("--data_dir", default="data",
                   help="raw dataset root (MSCallGraph/ + MSResource/)")
    p.add_argument("--artifact_dir", default="processed",
                   help="idempotent L0-L2 artifact cache directory")
    p.add_argument("--arena_cache_dir", default="",
                   help="persistent arena store "
                        "(batching/arena_store.py): mmap .npy "
                        "persistence of the dataset arenas + pack "
                        "metadata, content-hash keyed; a warm process "
                        "reconstructs the dataset from disk and skips "
                        "ingest + graph construction + featurization "
                        "entirely. Empty = off. TRUST: write access to "
                        "this dir controls every later run's "
                        "features/labels (docs/GUIDE.md §8)")
    p.add_argument("--fingerprint_mode", choices=("stat", "content"),
                   default=DataConfig.fingerprint_mode,
                   help="how the arena/delta stores key raw input "
                        "trees: stat = (path, size, mtime) — cheap but "
                        "a touch-without-change rebuilds everything; "
                        "content = (path, size, sha256) — immune to "
                        "mtime churn at the cost of hashing the tree "
                        "once per process")


def add_stream_flags(p: argparse.ArgumentParser) -> None:
    """Streaming-ingest / continual-training knobs (StreamConfig,
    pertgnn_tpu/stream/) — train_main's continual surface."""
    p.add_argument("--delta_store_dir", default="",
                   help="append-only delta arena store root "
                        "(stream/store.py): per-shard ingest results, "
                        "content-keyed; empty = streaming off. TRUST: "
                        "same boundary as --arena_cache_dir")
    p.add_argument("--window_shards", type=int,
                   default=StreamConfig.window_shards,
                   help="sliding continual-training window: fine-tune "
                        "on the examples of the last this-many shards "
                        "(<= 0 = all shards)")
    p.add_argument("--finetune_epochs", type=int,
                   default=StreamConfig.finetune_epochs,
                   help="epochs per warm-restart continual fine-tune "
                        "round (stream/continual.py)")


def stream_config_from_args(args: argparse.Namespace) -> StreamConfig:
    """The ONE flags -> StreamConfig mapping (same pattern as
    telemetry_config_from_args); config_from_args embeds it so the
    sidecar provenance and the live stream cannot drift."""
    return StreamConfig(
        delta_store_dir=getattr(args, "delta_store_dir", ""),
        window_shards=getattr(args, "window_shards",
                              StreamConfig.window_shards),
        finetune_epochs=getattr(args, "finetune_epochs",
                                StreamConfig.finetune_epochs))


def add_scale_flags(p: argparse.ArgumentParser) -> None:
    """Giant-corpus scale-out knobs (ScaleConfig,
    pertgnn_tpu/parallel/scale.py) — train_main's scale surface."""
    p.add_argument("--scale_hosts", type=int,
                   default=ScaleConfig.scale_hosts,
                   help="partition the delta shard set over this many "
                        "logical hosts (content-key-ordered assignment; "
                        "each host mmaps only its slice and the merge "
                        "statistics ride mesh collectives). 1 = the "
                        "single-host merge path")
    p.add_argument("--accum_buckets", type=int,
                   default=ScaleConfig.accum_buckets,
                   help="topology-bucket capacity of the SAR "
                        "rematerialized train step (one optimizer "
                        "update per scan over this many bucket slots; "
                        "gradients bit-identical to the "
                        "aggregation-held step at bounded peak HBM). "
                        "<= 1 = the monolithic per-batch step")


def scale_config_from_args(args: argparse.Namespace) -> ScaleConfig:
    """The ONE flags -> ScaleConfig mapping (same pattern as
    stream_config_from_args)."""
    return ScaleConfig(
        scale_hosts=getattr(args, "scale_hosts",
                            ScaleConfig.scale_hosts),
        accum_buckets=getattr(args, "accum_buckets",
                              ScaleConfig.accum_buckets))


def config_from_args(args: argparse.Namespace) -> Config:
    # staging tri-state: --staged_epochs {auto,on,off}; the legacy
    # --no_stage_epoch_recipes alias forces off
    staged = {"auto": None, "on": True, "off": False}[
        getattr(args, "staged_epochs", "auto")]
    if getattr(args, "no_stage_epoch_recipes", False):
        staged = False
    return Config(
        ingest=IngestConfig(
            min_traces_per_entry=args.min_traces_per_entry,
            min_resource_coverage=args.min_resource_coverage),
        data=DataConfig(max_traces=args.max_traces,
                        batch_size=args.batch_size,
                        max_nodes_per_batch=args.max_nodes_per_batch or None,
                        max_edges_per_batch=args.max_edges_per_batch or None,
                        budget_headroom=args.budget_headroom,
                        arena_cache_dir=getattr(args, "arena_cache_dir",
                                                ""),
                        fingerprint_mode=getattr(args, "fingerprint_mode",
                                                 "stat")),
        model=ModelConfig(
            hidden_channels=args.hidden_channels,
            num_layers=args.num_layers,
            num_heads=args.num_heads,
            dropout=args.dropout,
            attn_dropout=args.attn_dropout,
            init_scheme=args.init_scheme,
            use_node_depth=args.use_node_depth,
            use_edge_durations=args.use_edge_durations,
            nonnegative_pred=args.nonnegative_pred,
            local_loss_weight=args.local_loss_weight,
            missing_indicator_is_one=not args.missing_indicator_is_zero,
            feature_all_stage_copies=args.feature_all_stage_copies,
            use_pallas_attention=args.use_pallas_attention,
            attention_impl=args.attention_impl,
            vocab_headroom_entries=getattr(args, "vocab_headroom_entries",
                                           0),
            quantile_taus=parse_quantile_taus(
                getattr(args, "quantile_taus", "0.5")),
            kernel_block_n=args.kernel_block_n,
            kernel_block_e=args.kernel_block_e,
            blocked_dense_max_cells=args.blocked_dense_max_cells,
            bf16_activations=args.bf16),
        train=TrainConfig(
            lr=args.lr, tau=args.tau, epochs=args.epochs,
            label_scale=args.label_scale, seed=args.seed,
            scan_chunk=args.scan_chunk,
            device_materialize=not args.no_device_materialize,
            arena_hbm_budget_gb=(args.arena_hbm_budget_gb
                                 if args.arena_hbm_budget_gb > 0 else None),
            stage_epoch_recipes=staged,
            prefetch_depth=getattr(args, "prefetch_depth", 2),
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_keep=args.checkpoint_keep),
        parallel=ParallelConfig(data_parallel=args.data_parallel,
                                model_parallel=args.model_parallel,
                                shard_edges=args.shard_edges),
        # getattr falls back to the DATACLASS defaults: only parsers that
        # call add_serve_flags carry these (train_main does not serve)
        serve=ServeConfig(
            bucket_growth=getattr(args, "bucket_growth",
                                  ServeConfig.bucket_growth),
            min_bucket_nodes=getattr(args, "min_bucket_nodes",
                                     ServeConfig.min_bucket_nodes),
            min_bucket_edges=getattr(args, "min_bucket_edges",
                                     ServeConfig.min_bucket_edges),
            max_graphs_per_batch=getattr(args, "max_graphs_per_batch",
                                         ServeConfig.max_graphs_per_batch),
            flush_deadline_ms=getattr(args, "flush_deadline_ms",
                                      ServeConfig.flush_deadline_ms),
            warmup=not getattr(args, "no_serve_warmup", False),
            max_pending=getattr(args, "max_pending",
                                ServeConfig.max_pending),
            request_deadline_ms=getattr(args, "request_deadline_ms",
                                        ServeConfig.request_deadline_ms),
            dispatch_timeout_s=getattr(args, "dispatch_timeout_s",
                                       ServeConfig.dispatch_timeout_s),
            quarantine_threshold=getattr(
                args, "quarantine_threshold",
                ServeConfig.quarantine_threshold),
            overlap_dispatch=not getattr(args, "no_overlap_dispatch",
                                         False),
            serve_dtype=getattr(args, "serve_dtype",
                                ServeConfig.serve_dtype)),
        fleet=fleet_config_from_args(args),
        stream=stream_config_from_args(args),
        scale=scale_config_from_args(args),
        lens=lens_config_from_args(args),
        telemetry=telemetry_config_from_args(args),
        aot=aot_config_from_args(args),
        graph_type=args.graph_type,
    )


def get_frames(args: argparse.Namespace):
    """(spans, resources) raw frames per the flags."""
    if args.synthetic:
        from pertgnn_tpu.ingest import synthetic
        data = synthetic.generate(synthetic.SyntheticSpec(
            num_entries=args.synthetic_entries,
            traces_per_entry=args.synthetic_traces_per_entry,
            seed=getattr(args, "seed", 0)))
        return data.spans, data.resources
    from pertgnn_tpu.ingest.io import load_raw_csvs
    return load_raw_csvs(args.data_dir)


def load_or_ingest_artifacts(args: argparse.Namespace, ingest_cfg):
    """(pre, table) from the artifact cache if complete, else ingest +
    persist (including stream vocabs when --stream_factorize produced
    them). Shared by train_main and predict_main so the two CLIs cannot
    drift — notably the vocab persistence, which a predict-first
    workflow would otherwise silently drop."""
    from pertgnn_tpu.ingest.io import (artifacts_present, load_artifacts,
                                       preprocess_cached,
                                       save_stream_vocabs)

    if artifacts_present(args.artifact_dir):
        return load_artifacts(args.artifact_dir)
    spans, resources, ingest_cfg, vocabs = get_frames_with_ingest_cfg(
        args, ingest_cfg)
    if vocabs is not None:
        save_stream_vocabs(args.artifact_dir, vocabs)
    return preprocess_cached(args.artifact_dir, spans, resources,
                             cfg=ingest_cfg)


def _walk_fingerprint(root: str, suffixes: tuple[str, ...],
                      measure) -> list:
    """(relpath, *measure(path)) per matching file under `root` in
    deterministic walk order — the ONE traversal both fingerprint
    modes share, so a future skip rule or ordering tweak cannot apply
    to one mode and not the other. Files that vanish or error mid-walk
    are skipped (the next keying sees the change)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(suffixes):
                continue
            path = os.path.join(dirpath, name)
            try:
                row = measure(path)
            except OSError:
                continue
            out.append([os.path.relpath(path, root), *row])
    return out


def _stat_fingerprint(root: str, suffixes: tuple[str, ...]) -> list:
    """(relpath, size, mtime) per matching file under `root`, sorted —
    a cheap content proxy for multi-GB raw trees where hashing every
    byte would cost more than the ingest the arena cache is skipping.
    An edited/added/removed file changes the fingerprint; an in-place
    same-size same-mtime rewrite is the accepted blind spot (same
    trade artifact caches and build systems make)."""
    def measure(path):
        st = os.stat(path)
        return st.st_size, round(st.st_mtime, 3)

    return _walk_fingerprint(root, suffixes, measure)


def _content_fingerprint(root: str, suffixes: tuple[str, ...]) -> list:
    """(relpath, size, sha256-prefix) per matching file under `root`,
    sorted — the --fingerprint_mode=content alternative to
    `_stat_fingerprint`: immune to mtime churn (rsync, container image
    layers, CI checkouts touch files without changing bytes, and under
    stat keying every touch rebuilds the whole arena), at the cost of
    reading the tree once per keying process."""
    import hashlib

    def measure(path):
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return os.stat(path).st_size, f"sha256:{h.hexdigest()[:20]}"

    return _walk_fingerprint(root, suffixes, measure)


def _tree_fingerprint(args: argparse.Namespace, root: str,
                      suffixes: tuple[str, ...]) -> list:
    mode = getattr(args, "fingerprint_mode", "stat")
    if mode == "content":
        return _content_fingerprint(root, suffixes)
    if mode != "stat":
        raise SystemExit(f"unknown --fingerprint_mode {mode!r} "
                         f"(choose stat or content)")
    return _stat_fingerprint(root, suffixes)


def raw_input_fingerprint(args: argparse.Namespace) -> dict:
    """What the arena/delta stores key the RAW INPUT by (the stores'
    args component) — which must mirror `load_or_ingest_artifacts`'
    PRECEDENCE exactly: an existing artifact cache wins over everything
    (including --synthetic flags: the ingest loads the artifacts, so
    keying the spec would let a stale artifact dir be cached under a
    key that claims fresh synthetic data), then the synthetic spec,
    then the raw CSV tree's files — stat-keyed by default,
    content-hash-keyed under --fingerprint_mode=content (a
    touch-without-change then changes nothing)."""
    from pertgnn_tpu.ingest.io import artifacts_present

    artifact_dir = getattr(args, "artifact_dir", "")
    if artifact_dir and artifacts_present(artifact_dir):
        return {"kind": "artifacts", "dir": os.path.abspath(artifact_dir),
                "files": _tree_fingerprint(args, artifact_dir,
                                           (".npz", ".parquet", ".json"))}
    if getattr(args, "synthetic", False):
        return {"kind": "synthetic",
                "entries": args.synthetic_entries,
                "traces_per_entry": args.synthetic_traces_per_entry,
                "seed": getattr(args, "seed", 0)}
    data_dir = getattr(args, "data_dir", "data")
    return {"kind": "raw_csvs", "dir": os.path.abspath(data_dir),
            "stream_factorize": getattr(args, "stream_factorize", False),
            "files": _tree_fingerprint(args, data_dir, (".csv",))}


def build_dataset_cached(args: argparse.Namespace, cfg: Config,
                         pre_table: tuple | None = None):
    """The Dataset, through the persistent arena store when
    --arena_cache_dir is set: a warm hit reconstructs it from mmap'd
    arrays and SKIPS ingest + graph construction + featurization
    entirely; a miss (or no cache dir) runs the full path and persists.
    `pre_table` short-circuits the ingest when the caller already holds
    (pre, table) — predict_main needs the trace table for its output
    rows regardless."""
    from pertgnn_tpu.batching import build_dataset

    def build():
        pt = (pre_table if pre_table is not None
              else load_or_ingest_artifacts(args, cfg.ingest))
        return build_dataset(pt[0], cfg, pt[1])

    if not cfg.data.arena_cache_dir:
        return build()
    if pre_table is None:
        from pertgnn_tpu.ingest.io import artifacts_present

        if not artifacts_present(getattr(args, "artifact_dir", "")):
            # materialize the L0-L2 artifacts BEFORE fingerprinting:
            # the key fingerprints the artifact cache (every ingest
            # flavor, synthetic included, persists artifacts there and
            # PREFERS them on later runs), so keying run 1 on the
            # pre-artifact source would flip the key once the artifacts
            # exist — a guaranteed miss plus a misleading invalidation
            # warning on the first warm run
            pre_table = load_or_ingest_artifacts(args, cfg.ingest)
    from pertgnn_tpu.batching.arena_store import ArenaStore

    return ArenaStore(cfg.data.arena_cache_dir).load_or_build(
        cfg, raw_input_fingerprint(args), build)


def get_frames_with_ingest_cfg(args: argparse.Namespace, ingest_cfg):
    """(spans, resources, ingest_cfg, stream_vocabs|None) honoring
    --stream_factorize — shared by BOTH CLIs so the flag cannot be
    silently ignored. Streaming translates the config's special tokens
    to codes; the returned vocabs must be persisted next to any artifact
    cache (io.save_stream_vocabs) or the ids are unrecoverable."""
    if getattr(args, "stream_factorize", False):
        if args.synthetic:
            raise SystemExit(
                "--stream_factorize reads on-disk shards; it cannot "
                "combine with --synthetic (write the synthetic corpus to "
                "CSVs and pass --data_dir instead)")
        from pertgnn_tpu.ingest.io import load_raw_csvs_streaming
        return load_raw_csvs_streaming(
            args.data_dir, ingest_cfg,
            workers=getattr(args, "ingest_workers", 1))
    spans, resources = get_frames(args)
    return spans, resources, ingest_cfg, None
