"""Shared CLI plumbing: flags → Config.

Flag names track the reference's argparse block (pert_gnn.py:15-33) so
configs transfer verbatim; the three flags the reference declares but never
uses (`--log_steps`, `--use_sage`, `--runs` — SURVEY.md §5.6) are dropped.
New capability flags are grouped after the parity flags.
"""

from __future__ import annotations

import argparse
import os

from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                IngestConfig, ModelConfig, ParallelConfig,
                                ServeConfig, TelemetryConfig, TrainConfig)


def apply_platform_env() -> None:
    """Honor JAX_PLATFORMS even when a device plugin (e.g. the axon TPU
    tunnel) takes precedence over the env var — needed for virtual-device
    mesh runs (`JAX_PLATFORMS=cpu` +
    `--xla_force_host_platform_device_count=N`). No-op once a backend is
    initialized.

    When cpu is requested, the tunnel plugin's backend factory is also
    REMOVED: the plugin re-sets jax_platforms at interpreter start and
    its get_backend hook has been observed (round 5) initializing the
    tunnel backend anyway — which blocks forever inside the PJRT client
    constructor whenever the relay is half-open. A cpu-intended process
    must have no path that can dial the relay."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
        if all(p.strip() == "cpu" for p in want.split(",")):
            drop_relay_backend_factory()


def probe_backend_or_fallback() -> bool:
    """Poll the default accelerator backend (subprocess + timeout per
    attempt, pauses between — the relay flaps on minute timescales, so a
    single probe under-samples) and, on persistent failure, fall back to
    JAX_PLATFORMS=cpu with the full anti-hang hardening. Returns True if
    the fallback engaged.

    Guards only the flaky DEFAULT (JAX_PLATFORMS unset or the axon
    relay, which this environment presets); an explicit NON-axon choice
    is honored untouched — if it is broken the caller should fail
    loudly, not silently remeasure on CPU. Knobs: BENCH_PROBE_TIMEOUT /
    BENCH_PROBE_TRIES / BENCH_PROBE_PAUSE (shared with bench.py).

    A successful probe narrows but cannot close the hang window: the
    parent's own first backend touch can still catch a flap. Callers
    that must never block (the driver) should also run under a hard
    external timeout."""
    import subprocess
    import sys
    import time

    if os.environ.get("JAX_PLATFORMS", "axon") not in ("", "axon"):
        return False
    timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
    tries = int(os.environ.get("BENCH_PROBE_TRIES", "4"))
    last = None
    for attempt in range(tries):
        if attempt:
            time.sleep(int(os.environ.get("BENCH_PROBE_PAUSE", "10")))
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            return False
        except Exception as e:
            last = e
            print(f"WARNING: accelerator backend probe "
                  f"{attempt + 1}/{tries} failed ({e!r})", file=sys.stderr)
    print(f"WARNING: all {tries} backend probes failed (last: {last!r}); "
          f"falling back to JAX_PLATFORMS=cpu", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    apply_platform_env()
    return True


def drop_relay_backend_factory() -> None:
    """Remove the axon relay plugin's backend factory so a cpu-intended
    process has NO path that can dial the (possibly half-open) relay.
    Only the relay: popping built-in names (tpu, cuda) breaks later MLIR
    lowering-rule registration, which validates platforms against this
    registry. Shared by apply_platform_env and tests/conftest.py."""
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        # jax internals moved — the config-level platform selection
        # still applies, but it alone has NOT been sufficient against
        # the plugin's get_backend hook (round-5 observation), so say so
        import warnings
        warnings.warn(
            "could not remove the axon backend factory (jax internals "
            "changed?) — cpu-intended runs may hang if the relay plugin "
            "dials a wedged tunnel", RuntimeWarning, stacklevel=2)


def add_model_train_flags(p: argparse.ArgumentParser) -> None:
    # parity flags (reference defaults, pert_gnn.py:15-33)
    p.add_argument("--num_layers", type=int, default=1)
    p.add_argument("--hidden_channels", type=int, default=32)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--tau", type=float, default=0.5,
                   help="pinball-loss quantile level in (0, 1)")
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--batch_size", type=int, default=170)
    p.add_argument("--graph_type", choices=("span", "pert"), default="span")
    p.add_argument("--max_traces", type=int, default=100_000)
    # capability flags
    p.add_argument("--num_heads", type=int, default=1)
    p.add_argument("--label_scale", type=float, default=1.0)
    p.add_argument("--use_node_depth", action="store_true")
    p.add_argument("--use_edge_durations", action="store_true")
    p.add_argument("--nonnegative_pred", action="store_true")
    p.add_argument("--local_loss_weight", type=float, default=0.0)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--attn_dropout", type=float, default=0.0,
                   help="dropout on attention weights inside the conv")
    p.add_argument("--init_scheme", choices=("torch", "torch_full", "flax"),
                   default="torch",
                   help="Linear-kernel init: torch kaiming-uniform "
                        "(reference-faithful, default) or flax defaults")
    p.add_argument("--use_pallas_attention", action="store_true",
                   help="fused Pallas edge-attention kernel (TPU only)")
    p.add_argument("--missing_indicator_is_zero", action="store_true",
                   help="preprocess-time indicator convention (1=present) "
                        "instead of the live get_x convention (1=missing)")
    p.add_argument("--max_nodes_per_batch", type=int, default=0,
                   help="packed-batch node budget; 0 = derived from data")
    p.add_argument("--max_edges_per_batch", type=int, default=0,
                   help="packed-batch edge budget; 0 = derived from data")
    p.add_argument("--budget_headroom", type=float, default=1.1,
                   help="derived-budget head-room over mean-mixture * "
                        "batch_size (batching/pack.py derive_budget)")
    p.add_argument("--no_device_materialize", action="store_true",
                   help="disable chip-resident arenas + device-side batch "
                        "materialization (host-packed streaming instead)")
    p.add_argument("--arena_hbm_budget_gb", type=float, default=4.0,
                   help="HBM budget for chip-resident arenas; exceeding it "
                        "falls back to host packing; <=0 = unlimited")
    p.add_argument("--feature_all_stage_copies", action="store_true",
                   help="feature every PERT stage-copy of a microservice "
                        "(the reference's live get_x features only the "
                        "last copy — PARITY.md)")
    p.add_argument("--no_stage_epoch_recipes", action="store_true",
                   help="disable epoch-level recipe staging (one H2D per "
                        "epoch); fall back to per-chunk recipe transfer")
    p.add_argument("--shard_edges", action="store_true",
                   help="giant-graph mode: shard each batch's edge set "
                        "over the mesh data axis (nodes replicated)")
    p.add_argument("--data_parallel", type=int, default=1,
                   help="mesh data axis size (1 = single device)")
    p.add_argument("--model_parallel", type=int, default=1)
    # multi-host (SURVEY.md §5.8): every process runs the same command with
    # its own --process_id; the mesh then spans all processes' devices
    p.add_argument("--coordinator_address", default="",
                   help="host:port of process 0 (multi-host runs)")
    p.add_argument("--num_processes", type=int, default=0,
                   help="total process count (0/1 = single-process)")
    p.add_argument("--process_id", type=int, default=-1,
                   help="this process's rank in a multi-host run")
    p.add_argument("--checkpoint_dir", default="")
    p.add_argument("--checkpoint_keep", type=int, default=3)
    p.add_argument("--allow_config_mismatch", action="store_true",
                   help="downgrade the checkpoint config-sidecar "
                        "cross-check (label_scale/graph_type/model "
                        "fields at resume and inference) from an error "
                        "to a warning")
    p.add_argument("--profile_dir", default="",
                   help="write a jax.profiler trace of epoch 2 here")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--scan_chunk", type=int, default=16,
                   help="train/eval steps fused into one dispatched "
                        "lax.scan program; 1 disables scan fusion")


def add_serve_flags(p: argparse.ArgumentParser) -> None:
    """Serving-engine knobs (ServeConfig) — serve_main and predict_main's
    bucketed path."""
    p.add_argument("--bucket_growth", type=float,
                   default=ServeConfig.bucket_growth,
                   help="geometric growth of the serving bucket ladder "
                        "(serve/buckets.py); 2.0 = powers-of-two rungs")
    p.add_argument("--max_graphs_per_batch", type=int,
                   default=ServeConfig.max_graphs_per_batch,
                   help="graph slots per serving microbatch")
    p.add_argument("--flush_deadline_ms", type=float,
                   default=ServeConfig.flush_deadline_ms,
                   help="microbatch queue: max wait for co-arriving "
                        "requests before a batch is flushed; 0 = dispatch "
                        "per request")
    p.add_argument("--no_serve_warmup", action="store_true",
                   help="skip AOT-compiling the bucket ladder at engine "
                        "construction (first request per bucket then pays "
                        "the compile)")
    # fault-tolerance knobs (serve/queue.py, docs/RELIABILITY.md)
    p.add_argument("--max_pending", type=int,
                   default=ServeConfig.max_pending,
                   help="admission control: max queued requests; submit "
                        "past it fast-fails with QueueFull (serve.shed)")
    p.add_argument("--request_deadline_ms", type=float,
                   default=ServeConfig.request_deadline_ms,
                   help="per-request deadline: undispatched past it, the "
                        "future resolves with DeadlineExceeded; 0 = none")
    p.add_argument("--dispatch_timeout_s", type=float,
                   default=ServeConfig.dispatch_timeout_s,
                   help="dispatch watchdog: abandon an engine call wedged "
                        "past this, mark the engine unhealthy, attempt "
                        "one rebuild-from-AOT-store recovery; 0 = no "
                        "watchdog (engine calls run inline)")
    p.add_argument("--quarantine_threshold", type=int,
                   default=ServeConfig.quarantine_threshold,
                   help="reject an entry at submit after it poisoned this "
                        "many microbatches (bisect-isolated)")


def add_aot_flags(p: argparse.ArgumentParser) -> None:
    """Cold-start / compile-cache knobs (CompileCacheConfig,
    pertgnn_tpu/aot/) — shared by ALL CLIs and bench.py: any entry point
    that compiles can persist and replay its executables."""
    p.add_argument("--compile_cache_dir", default="",
                   help="persist compiled executables here (xla/ = JAX's "
                        "persistent compilation cache; exe/ = serialized "
                        "serve-rung executables) so later processes skip "
                        "cold-start compilation; empty = off "
                        "(docs/GUIDE.md 'Precompile workflow')")
    p.add_argument("--aot_min_compile_time_s", type=float, default=0.0,
                   help="only persist XLA cache entries whose compile "
                        "took at least this long; 0 caches everything")
    p.add_argument("--no_serialize_executables", action="store_true",
                   help="skip the serialized serve-executable store "
                        "(persistent XLA cache only)")


def aot_config_from_args(args: argparse.Namespace) -> CompileCacheConfig:
    """The ONE flags -> CompileCacheConfig mapping (same pattern as
    telemetry_config_from_args): config_from_args embeds it and
    setup_compile_cache enables the live cache from it."""
    return CompileCacheConfig(
        cache_dir=getattr(args, "compile_cache_dir", ""),
        min_compile_time_s=getattr(args, "aot_min_compile_time_s", 0.0),
        serialize_executables=not getattr(args, "no_serialize_executables",
                                          False))


def setup_compile_cache(args: argparse.Namespace) -> CompileCacheConfig:
    """Enable the persistent compilation cache from parsed flags (no-op
    when --compile_cache_dir is empty). Call AFTER apply_platform_env
    and BEFORE anything compiles — cache entries are keyed per backend,
    so the platform decision must already be final."""
    from pertgnn_tpu.aot import enable_compile_cache

    cfg = aot_config_from_args(args)
    enable_compile_cache(cfg)
    return cfg


def add_telemetry_flags(p: argparse.ArgumentParser) -> None:
    """Telemetry-bus + logging knobs — shared by ALL CLIs (the bus is
    process-wide; any entry point can produce a JSONL stream)."""
    p.add_argument("--telemetry_dir", default="",
                   help="write schema-versioned telemetry JSONL here "
                        "(docs/OBSERVABILITY.md); empty = telemetry off")
    p.add_argument("--telemetry_level", default="basic",
                   choices=("off", "basic", "trace"),
                   help="bus verbosity: basic = run/epoch granularity, "
                        "trace adds per-chunk / per-request events")
    p.add_argument("--tensorboard", action="store_true",
                   help="mirror scalar telemetry to a TensorBoard sink "
                        "under <telemetry_dir>/tb (needs tensorboardX)")
    p.add_argument("--log_level", default="",
                   help="logging level name (DEBUG/INFO/...); default: "
                        "$PERTGNN_LOG_LEVEL or INFO")


def telemetry_config_from_args(args: argparse.Namespace) -> TelemetryConfig:
    """The ONE flags -> TelemetryConfig mapping: config_from_args embeds
    it in the Config (checkpoint-sidecar provenance) and setup_telemetry
    configures the live bus from it, so the two cannot drift."""
    return TelemetryConfig(
        telemetry_dir=getattr(args, "telemetry_dir", ""),
        telemetry_level=getattr(args, "telemetry_level", "basic"),
        tensorboard=getattr(args, "tensorboard", False))


def setup_telemetry(args: argparse.Namespace, cli: str):
    """Install the process-wide bus from parsed flags (and apply
    --log_level). Returns the bus. Call AFTER apply_platform_env so the
    writer's process-index stamp can see an initialized backend."""
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.utils.logging import set_level

    if getattr(args, "log_level", ""):
        set_level(args.log_level)
    return telemetry.configure_from_config(
        telemetry_config_from_args(args), run_meta={"cli": cli})


def add_ingest_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--min_traces_per_entry", type=int, default=100)
    p.add_argument("--min_resource_coverage", type=float, default=0.6)
    p.add_argument("--stream_factorize", action="store_true",
                   help="200GB-scale loader: factorize strings per shard "
                        "against incremental vocabularies so RAM holds "
                        "only numeric columns; ids are isomorphic (not "
                        "equal) to the exact path's (ingest/io.py)")
    p.add_argument("--ingest_workers", type=int, default=1,
                   help="worker processes for the --stream_factorize "
                        "shard parse+factorize fan-out; the parent's "
                        "shard-order vocab merge keeps results identical "
                        "to workers=1")
    p.add_argument("--synthetic", action="store_true",
                   help="use the synthetic generator instead of raw CSVs")
    p.add_argument("--synthetic_entries", type=int, default=8)
    p.add_argument("--synthetic_traces_per_entry", type=int, default=300)
    p.add_argument("--data_dir", default="data",
                   help="raw dataset root (MSCallGraph/ + MSResource/)")
    p.add_argument("--artifact_dir", default="processed",
                   help="idempotent L0-L2 artifact cache directory")


def config_from_args(args: argparse.Namespace) -> Config:
    return Config(
        ingest=IngestConfig(
            min_traces_per_entry=args.min_traces_per_entry,
            min_resource_coverage=args.min_resource_coverage),
        data=DataConfig(max_traces=args.max_traces,
                        batch_size=args.batch_size,
                        max_nodes_per_batch=args.max_nodes_per_batch or None,
                        max_edges_per_batch=args.max_edges_per_batch or None,
                        budget_headroom=args.budget_headroom),
        model=ModelConfig(
            hidden_channels=args.hidden_channels,
            num_layers=args.num_layers,
            num_heads=args.num_heads,
            dropout=args.dropout,
            attn_dropout=args.attn_dropout,
            init_scheme=args.init_scheme,
            use_node_depth=args.use_node_depth,
            use_edge_durations=args.use_edge_durations,
            nonnegative_pred=args.nonnegative_pred,
            local_loss_weight=args.local_loss_weight,
            missing_indicator_is_one=not args.missing_indicator_is_zero,
            feature_all_stage_copies=args.feature_all_stage_copies,
            use_pallas_attention=args.use_pallas_attention,
            bf16_activations=args.bf16),
        train=TrainConfig(
            lr=args.lr, tau=args.tau, epochs=args.epochs,
            label_scale=args.label_scale, seed=args.seed,
            scan_chunk=args.scan_chunk,
            device_materialize=not args.no_device_materialize,
            arena_hbm_budget_gb=(args.arena_hbm_budget_gb
                                 if args.arena_hbm_budget_gb > 0 else None),
            stage_epoch_recipes=not args.no_stage_epoch_recipes,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_keep=args.checkpoint_keep),
        parallel=ParallelConfig(data_parallel=args.data_parallel,
                                model_parallel=args.model_parallel,
                                shard_edges=args.shard_edges),
        # getattr falls back to the DATACLASS defaults: only parsers that
        # call add_serve_flags carry these (train_main does not serve)
        serve=ServeConfig(
            bucket_growth=getattr(args, "bucket_growth",
                                  ServeConfig.bucket_growth),
            max_graphs_per_batch=getattr(args, "max_graphs_per_batch",
                                         ServeConfig.max_graphs_per_batch),
            flush_deadline_ms=getattr(args, "flush_deadline_ms",
                                      ServeConfig.flush_deadline_ms),
            warmup=not getattr(args, "no_serve_warmup", False),
            max_pending=getattr(args, "max_pending",
                                ServeConfig.max_pending),
            request_deadline_ms=getattr(args, "request_deadline_ms",
                                        ServeConfig.request_deadline_ms),
            dispatch_timeout_s=getattr(args, "dispatch_timeout_s",
                                       ServeConfig.dispatch_timeout_s),
            quarantine_threshold=getattr(
                args, "quarantine_threshold",
                ServeConfig.quarantine_threshold)),
        telemetry=telemetry_config_from_args(args),
        aot=aot_config_from_args(args),
        graph_type=args.graph_type,
    )


def get_frames(args: argparse.Namespace):
    """(spans, resources) raw frames per the flags."""
    if args.synthetic:
        from pertgnn_tpu.ingest import synthetic
        data = synthetic.generate(synthetic.SyntheticSpec(
            num_entries=args.synthetic_entries,
            traces_per_entry=args.synthetic_traces_per_entry,
            seed=getattr(args, "seed", 0)))
        return data.spans, data.resources
    from pertgnn_tpu.ingest.io import load_raw_csvs
    return load_raw_csvs(args.data_dir)


def load_or_ingest_artifacts(args: argparse.Namespace, ingest_cfg):
    """(pre, table) from the artifact cache if complete, else ingest +
    persist (including stream vocabs when --stream_factorize produced
    them). Shared by train_main and predict_main so the two CLIs cannot
    drift — notably the vocab persistence, which a predict-first
    workflow would otherwise silently drop."""
    from pertgnn_tpu.ingest.io import (artifacts_present, load_artifacts,
                                       preprocess_cached,
                                       save_stream_vocabs)

    if artifacts_present(args.artifact_dir):
        return load_artifacts(args.artifact_dir)
    spans, resources, ingest_cfg, vocabs = get_frames_with_ingest_cfg(
        args, ingest_cfg)
    if vocabs is not None:
        save_stream_vocabs(args.artifact_dir, vocabs)
    return preprocess_cached(args.artifact_dir, spans, resources,
                             cfg=ingest_cfg)


def get_frames_with_ingest_cfg(args: argparse.Namespace, ingest_cfg):
    """(spans, resources, ingest_cfg, stream_vocabs|None) honoring
    --stream_factorize — shared by BOTH CLIs so the flag cannot be
    silently ignored. Streaming translates the config's special tokens
    to codes; the returned vocabs must be persisted next to any artifact
    cache (io.save_stream_vocabs) or the ids are unrecoverable."""
    if getattr(args, "stream_factorize", False):
        if args.synthetic:
            raise SystemExit(
                "--stream_factorize reads on-disk shards; it cannot "
                "combine with --synthetic (write the synthetic corpus to "
                "CSVs and pass --data_dir instead)")
        from pertgnn_tpu.ingest.io import load_raw_csvs_streaming
        return load_raw_csvs_streaming(
            args.data_dir, ingest_cfg,
            workers=getattr(args, "ingest_workers", 1))
    spans, resources = get_frames(args)
    return spans, resources, ingest_cfg, None
