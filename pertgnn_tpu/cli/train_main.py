"""Training CLI (the reference's `python pert_gnn.py`).

    python -m pertgnn_tpu.cli.train_main --artifact_dir processed --graph_type pert
    python -m pertgnn_tpu.cli.train_main --synthetic --min_traces_per_entry 10 \
        --epochs 5 --label_scale 1000
    python -m pertgnn_tpu.cli.train_main ... --data_parallel 8   # mesh run

Prints the reference's per-epoch line (train/valid/test MAE/MAPE/q-loss,
pert_gnn.py:348-350) plus throughput; checkpoints via orbax when
--checkpoint_dir is set.
"""

from __future__ import annotations

import argparse
import os
import sys

from pertgnn_tpu.cli.common import (add_aot_flags, add_ingest_flags,
                                    add_model_train_flags,
                                    add_scale_flags, add_stream_flags,
                                    add_telemetry_flags, apply_platform_env,
                                    build_dataset_cached, config_from_args,
                                    setup_compile_cache, setup_telemetry)
from pertgnn_tpu.train import supervisor
from pertgnn_tpu.train.loop import fit
from pertgnn_tpu.utils.logging import setup_logging


def _strip_flags(argv: list[str], flags: tuple[str, ...]) -> list[str]:
    """Remove value-taking flags (both `--f V` and `--f=V` forms) from an
    argv list — the supervised child must not re-enter the supervisor."""
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in flags:
            skip = True
            continue
        if any(tok.startswith(f + "=") for f in flags):
            continue
        out.append(tok)
    return out


def main(argv=None) -> None:
    setup_logging()
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    add_model_train_flags(p)
    add_stream_flags(p)
    add_scale_flags(p)
    add_telemetry_flags(p)
    add_aot_flags(p)
    p.add_argument("--supervise", type=int, default=0, metavar="N",
                   help="run training under a crash/hang supervisor with "
                        "up to N automatic restart-and-resumes (requires "
                        "--checkpoint_dir; see train/supervisor.py)")
    p.add_argument("--hang_timeout", type=float, default=900.0,
                   help="supervisor: kill the run if the checkpoint dir "
                        "shows no progress for this many seconds (must "
                        "exceed startup + one checkpoint interval)")
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="supervisor: base seconds of the exponential "
                        "restart backoff (doubles per consecutive fast "
                        "failure); 0 = immediate respawn")
    p.add_argument("--restart_backoff_cap", type=float, default=60.0,
                   help="supervisor: backoff ceiling in seconds")
    p.add_argument("--min_uptime", type=float, default=5.0,
                   help="supervisor: a child dying within this many "
                        "seconds of spawn counts as a crash loop "
                        "(supervisor.crash_loop) and escalates the "
                        "backoff")
    args = p.parse_args(argv)
    if args.supervise > 0 and supervisor.CHILD_ENV_MARKER not in os.environ:
        if not args.checkpoint_dir:
            p.error("--supervise requires --checkpoint_dir (progress "
                    "detection and resume both live there)")
        child_argv = _strip_flags(list(argv if argv is not None
                                       else sys.argv[1:]),
                                  ("--supervise", "--hang_timeout",
                                   "--restart_backoff",
                                   "--restart_backoff_cap",
                                   "--min_uptime"))
        # the parent gets its own (pid-unique) telemetry file so the
        # restart/hang counters land somewhere even though the child owns
        # the training stream
        setup_telemetry(args, "train_main_supervisor")
        raise SystemExit(supervisor.supervise(
            [sys.executable, "-m", "pertgnn_tpu.cli.train_main",
             *child_argv],
            args.checkpoint_dir, max_restarts=args.supervise,
            hang_timeout=args.hang_timeout,
            backoff_base=args.restart_backoff,
            backoff_cap=args.restart_backoff_cap,
            min_uptime_s=args.min_uptime))
    if args.num_processes > 1:
        from pertgnn_tpu.parallel.multihost import initialize
        initialize(args.coordinator_address or None, args.num_processes,
                   args.process_id)
    # after multihost init so the JSONL process-index stamp is real
    bus = setup_telemetry(args, "train_main")
    # before anything compiles: first-step chunk programs should land in
    # (or replay from) the persistent cache
    setup_compile_cache(args)
    print(args)
    cfg = config_from_args(args)

    # --arena_cache_dir: a warm process reconstructs the dataset from
    # the mmap'd arena store and skips ingest entirely
    dataset = build_dataset_cached(args, cfg)

    mesh = None
    if args.data_parallel > 1 or args.model_parallel > 1:
        from pertgnn_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(data=args.data_parallel, model=args.model_parallel)

    ckpt = None
    if args.checkpoint_dir:
        from pertgnn_tpu.train.checkpoint import (CheckpointManager,
                                                  config_mismatches)
        ckpt = CheckpointManager(args.checkpoint_dir,
                                 keep=args.checkpoint_keep)
        # Resume must cross-check the sidecar BEFORE overwriting it:
        # resuming with (say) the label_scale flag forgotten restores
        # cleanly, silently continues training in the wrong label space,
        # AND would launder the sidecar so inference checks pass too.
        saved = ckpt.load_config_dict()
        if ckpt.latest_step() is not None and saved is None:
            # mirror predict_main's no-sidecar warning: the sidecar about
            # to be written is seeded from the CURRENT flags, which
            # nothing can verify against the original training run
            import logging
            logging.getLogger(__name__).warning(
                "resuming a checkpoint that has no train_config.json "
                "sidecar (pre-sidecar run?) — seeding the sidecar from "
                "the current flags, which CANNOT be verified against the "
                "run that produced the checkpoint; later inference "
                "cross-checks will trust them")
        if ckpt.latest_step() is not None and saved is not None:
            mism, _unknown = config_mismatches(saved, cfg)
            if mism:
                detail = "; ".join(f"{k}: trained={a!r} vs now={b!r}"
                                   for k, a, b in mism)
                if not args.allow_config_mismatch:
                    p.error("resuming with different semantics than the "
                            f"checkpoint was trained with: {detail} "
                            "(pass the original flags, or "
                            "--allow_config_mismatch to adopt the new "
                            "ones)")
                # leave a trace BEFORE save_config overwrites the
                # sidecar — otherwise the override launders the change
                import logging
                logging.getLogger(__name__).warning(
                    "config mismatch overridden "
                    "(--allow_config_mismatch); sidecar will now record "
                    "the NEW semantics: %s", detail)
        # sidecar for inference-time cross-checking (predict_main):
        # restore is blind to semantics like label_scale / graph_type
        ckpt.save_config(cfg)
    hook = None
    if args.profile_dir:
        from pertgnn_tpu.utils.profiling import profile_epochs
        hook = profile_epochs(args.profile_dir)

    state, history = fit(dataset, cfg, checkpoint_manager=ckpt,
                         profile_hook=hook, mesh=mesh, bus=bus)
    bus.flush()
    for row in history:
        print(f"Epoch: {row['epoch']}, Train: {row['train_qloss']:.4f}, "
              f"Test mae: {row['test_mae']:.4f}, "
              f"Train mape: {row['train_mape']:.4f}, "
              f"Test mape: {row['test_mape']:.4f}, "
              f"Test q loss: {row['test_qloss']:.4f}, "
              f"{row['graphs_per_s']:.0f} graphs/s")


if __name__ == "__main__":
    main()
