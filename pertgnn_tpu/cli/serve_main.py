"""Serving CLI: answer per-trace latency requests from a trained checkpoint
through the bucketed online inference engine.

    python -m pertgnn_tpu.cli.serve_main --artifact_dir processed \
        --graph_type pert --checkpoint_dir ckpts --from_split test \
        --concurrency 8 --out served.csv
    python -m pertgnn_tpu.cli.serve_main --synthetic ... \
        --requests requests.csv

Requests are (entry_id, ts_bucket) rows — from a CSV (--requests) or
sampled from a positional split (--from_split). They are driven through
the full serving stack: `--concurrency` client threads submit to the
microbatch queue (serve/queue.py), which coalesces co-arriving requests
under the flush deadline and dispatches bucket-shaped batches to the AOT
executable cache (serve/engine.py). Output: one CSV row per request
(entry_id, ts_bucket, y_pred) in request order, plus ONE JSON line of
serving metrics (engine counters + client-observed latency percentiles —
the same schema family as benchmarks/serve_bench.py).

This is the long-lived process the ROADMAP's request-serving north star
needs; an RPC front-end would wrap `MicrobatchQueue.submit` — the queue,
not the transport, is the engineered part.

Operationally hardened (docs/RELIABILITY.md): SIGTERM drains gracefully
(admissions stop, in-flight batches flush, exit 0 with "drained": true
in the stats JSON), `--health_port` serves a 200/503 readiness probe
from `engine.health()`, and typed request failures (shed, deadline,
quarantine — serve/errors.py) are counted per class in the stats JSON
instead of killing the run; their CSV rows hold NaN.

Cold start: with `--compile_cache_dir` the warmed ladder executables
persist across process starts (warmup deserializes instead of
compiling), and `--precompile_only` populates that cache ahead of time
— without needing a checkpoint (docs/GUIDE.md §8).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from pertgnn_tpu.cli.common import (add_aot_flags, add_ingest_flags,
                                    add_lens_flags, add_model_train_flags,
                                    add_serve_flags,
                                    add_telemetry_flags, apply_platform_env,
                                    build_dataset_cached, config_from_args,
                                    setup_compile_cache, setup_telemetry)
from pertgnn_tpu.train.loop import restore_target_state
from pertgnn_tpu.utils.logging import setup_logging
from pertgnn_tpu.utils.profiling import LatencyRecorder


def _load_requests(args, dataset) -> tuple[np.ndarray, np.ndarray]:
    if args.requests:
        import pandas as pd

        df = pd.read_csv(args.requests)
        missing = {"entry_id", "ts_bucket"} - set(df.columns)
        if missing:
            raise SystemExit(
                f"--requests CSV lacks columns {sorted(missing)}")
        entries = df["entry_id"].to_numpy(np.int64)
        buckets = df["ts_bucket"].to_numpy(np.int64)
    else:
        s = dataset.splits[args.from_split]
        entries = np.asarray(s.entry_ids, np.int64)
        buckets = np.asarray(s.ts_buckets, np.int64)
    if args.num_requests:
        entries = entries[:args.num_requests]
        buckets = buckets[:args.num_requests]
    unknown = [int(e) for e in np.unique(entries)
               if int(e) not in dataset.mixtures]
    if unknown:
        raise SystemExit(
            f"requests name entry ids absent from the dataset's mixtures: "
            f"{unknown[:10]}{'...' if len(unknown) > 10 else ''}")
    return entries, buckets


def main(argv=None) -> None:
    setup_logging()
    apply_platform_env()
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    add_model_train_flags(p)
    add_serve_flags(p)
    add_lens_flags(p)
    add_telemetry_flags(p)
    add_aot_flags(p)
    p.add_argument("--requests", default="",
                   help="CSV of requests (entry_id, ts_bucket columns); "
                        "default: replay --from_split")
    p.add_argument("--from_split", default="test",
                   choices=("train", "valid", "test"),
                   help="split to replay as the request stream when no "
                        "--requests CSV is given")
    p.add_argument("--num_requests", type=int, default=0,
                   help="cap the request stream (0 = all)")
    p.add_argument("--concurrency", type=int, default=8,
                   help="client threads submitting to the microbatch "
                        "queue")
    p.add_argument("--out", default="served.csv",
                   help="per-request prediction CSV path")
    p.add_argument("--health_port", type=int, default=0,
                   help="serve a readiness probe on 127.0.0.1:<port> "
                        "(GET /healthz: 200 while the engine is healthy "
                        "and admissions are open, 503 while unhealthy or "
                        "draining; body = engine health + live load: "
                        "queue depth, in-flight count, per-class error "
                        "counts — serve/health.py); 0 = off")
    p.add_argument("--precompile_only", action="store_true",
                   help="populate the compile cache (--compile_cache_dir) "
                        "with every ladder-rung executable and exit "
                        "WITHOUT serving — the host-side stage that makes "
                        "the next serve process's warmup execute-only. "
                        "Works without a checkpoint (executables depend "
                        "on shapes, not weights); docs/GUIDE.md "
                        "'Precompile workflow'")
    args = p.parse_args(argv)
    if not args.checkpoint_dir and not args.precompile_only:
        p.error("--checkpoint_dir is required: serving answers from a "
                "trained checkpoint (run train_main with --checkpoint_dir "
                "first)")
    if args.precompile_only and not args.compile_cache_dir:
        p.error("--precompile_only without --compile_cache_dir would "
                "compile into this process and throw the result away")
    bus = setup_telemetry(args, "serve_main")
    setup_compile_cache(args)
    cfg = config_from_args(args)

    ckpt = None
    if args.checkpoint_dir:
        from pertgnn_tpu.cli.predict_main import _check_train_config
        from pertgnn_tpu.train.checkpoint import CheckpointManager
        ckpt = CheckpointManager(args.checkpoint_dir,
                                 keep=args.checkpoint_keep)
        if ckpt.latest_step() is None:
            p.error(f"no checkpoint steps in {args.checkpoint_dir!r}")
        _check_train_config(p, ckpt, cfg, args.allow_config_mismatch)

    # --arena_cache_dir: a warm serve process reconstructs mixtures,
    # lookup, budget and splits from the mmap'd arena store — zero
    # ingest before the first request
    dataset = build_dataset_cached(args, cfg)
    _model, state = restore_target_state(dataset, cfg)
    start_epoch = 0
    if ckpt is not None:
        state, start_epoch = ckpt.maybe_restore(state)
        if start_epoch == 0:
            p.error(f"no checkpoint found in {args.checkpoint_dir}")

    from pertgnn_tpu.serve.engine import InferenceEngine

    if args.precompile_only:
        from pertgnn_tpu import telemetry
        with telemetry.watch_xla_cache() as cache:
            engine = InferenceEngine.from_dataset(dataset, cfg,
                                                  state).warmup()
        print(json.dumps({
            "precompile_only": True,
            "buckets": len(engine.ladder),
            "compiles": engine.compiles,
            "deserialized": engine.deserialized,
            "warmup_s": engine.warmup_s,
            "xla_cache_hits": cache["hits"],
            "xla_cache_misses": cache["misses"],
            "compile_cache_dir": args.compile_cache_dir,
        }))
        bus.flush()
        return

    entries, buckets = _load_requests(args, dataset)
    if len(entries) == 0:
        raise SystemExit("no requests to serve")

    from pertgnn_tpu.serve.errors import QueueClosed, ServeError
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    engine = InferenceEngine.from_dataset(dataset, cfg, state)
    if cfg.serve.warmup:
        engine.warmup()

    import collections
    import signal
    import threading

    client_latency = LatencyRecorder()
    # multi-quantile heads (ModelConfig.quantile_taus, lens/) serve one
    # column per level; single-tau stays a flat vector
    from pertgnn_tpu.config import resolve_quantile_taus
    taus = resolve_quantile_taus(cfg.model, cfg.train.tau)
    preds = np.full((len(entries), len(taus)) if len(taus) > 1
                    else len(entries), np.nan, np.float32)
    served = np.zeros(len(entries), np.bool_)
    request_errors: collections.Counter = collections.Counter()
    errors_lock = threading.Lock()
    failures: list[tuple[int, BaseException]] = []
    draining = threading.Event()

    def client(indices) -> None:
        for i in indices:
            if draining.is_set():
                return
            t0 = time.perf_counter()
            try:
                # submit + result (not .predict): a multi-quantile
                # future resolves to a (T,) vector float() would reject
                preds[i] = queue.submit(int(entries[i]),
                                        int(buckets[i])).result()
            except QueueClosed:
                return  # admission stopped: drain raced this submit
            except ServeError as exc:
                # typed request failure (shed / deadline / quarantine /
                # unhealthy — serve/errors.py): the request stream goes
                # on; the failure is counted, its CSV row stays NaN
                with errors_lock:
                    request_errors[type(exc).__name__] += 1
                continue
            except BaseException as exc:  # lint: allow-silent-except
                # surface on the MAIN thread (SystemExit below): a
                # traceback printed by a dying client thread exits 0 and
                # leaves silent zero predictions in the CSV
                failures.append((i, exc))
                return
            served[i] = True
            client_latency.record_s(time.perf_counter() - t0)

    t_serve0 = time.perf_counter()
    health_server = None
    prev_term = None
    handler_installed = False
    try:
        with MicrobatchQueue(engine) as queue:
            # graceful drain: SIGTERM stops admissions immediately
            # (submit raises QueueClosed, clients wind down), in-flight
            # batches flush on close(), and the process EXITS 0 —
            # preemption of a serving replica must not read as a crash.
            # The handler stays installed until AFTER close() so a
            # repeated SIGTERM during the drain flush is idempotent
            # instead of killing the process mid-flush.
            def _on_term(signum, frame):
                draining.set()
                queue.begin_drain()

            try:
                prev_term = signal.signal(signal.SIGTERM, _on_term)
                handler_installed = True
            except ValueError:  # not the main thread (embedded use)
                pass
            if args.health_port:
                from pertgnn_tpu.serve.health import start_health_server
                health_server = start_health_server(args.health_port,
                                                    engine, queue)
            # round-robin so concurrent clients interleave distinct
            # requests (each index is served exactly once; preds/latency
            # cells are disjoint per thread, so no locking beyond the
            # queue's own)
            threads = [threading.Thread(
                target=client,
                args=(range(t, len(entries), args.concurrency),),
                name=f"serve-client-{t}")
                for t in range(max(1, args.concurrency))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        # prev_term is None when the prior handler was installed by
        # non-Python code — None is not restorable (TypeError); leave
        # ours in place (begin_drain on a closed queue is a no-op)
        if handler_installed and prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        if health_server is not None:
            health_server.shutdown()
    serve_wall_s = time.perf_counter() - t_serve0
    if failures:
        i, exc = failures[0]
        raise SystemExit(
            f"{len(failures)} client thread(s) failed; first: request {i} "
            f"(entry_id={int(entries[i])}) -> "
            f"{type(exc).__name__}: {exc}")

    import pandas as pd

    rows = {"entry_id": entries, "ts_bucket": buckets}
    if preds.ndim == 2:
        # one labeled column per quantile level + the primary under the
        # legacy y_pred name (same convention as predict_main)
        from pertgnn_tpu.config import primary_tau_index
        for i, t in enumerate(taus):
            rows[f"y_pred_q{t:g}"] = preds[:, i]
        rows["y_pred"] = preds[:, primary_tau_index(taus, cfg.train.tau)]
    else:
        rows["y_pred"] = preds
    pd.DataFrame(rows).to_csv(args.out, index=False)
    stats = {
        "metric": "pert_serve_request_latency_ms",
        "unit": "ms",
        "requests": len(entries),
        "served": int(served.sum()),
        "request_errors": dict(request_errors),
        "drained": draining.is_set(),
        "concurrency": args.concurrency,
        "epochs_trained": start_epoch,
        "throughput_rps": int(served.sum()) / max(serve_wall_s, 1e-9),
        "client_latency": client_latency.summary_dict(),
        # publish_stats also lands the aggregate counters + per-bucket
        # pad waste in the telemetry JSONL at basic level
        "engine": engine.publish_stats(),
        "queue": queue.stats_dict(),
        "health": engine.health(),
        "captured_unix_time": time.time(),
    }
    bus.flush()
    if draining.is_set():
        print(f"drained on SIGTERM: {int(served.sum())}/{len(entries)} "
              f"requests served before shutdown; all in-flight futures "
              f"resolved")
    print(f"wrote {len(entries)} predictions ({int(served.sum())} "
          f"served) to {args.out}")
    print(json.dumps(stats))
    # a run in which NOTHING was served (outside a drain) is a failure,
    # not a quietly all-NaN CSV — automation must see a nonzero exit
    if not draining.is_set() and not served.any():
        raise SystemExit(
            f"no request was served: all {len(entries)} failed "
            f"({dict(request_errors) or 'no typed errors recorded'})")


if __name__ == "__main__":
    main()
