"""Fleet CLI: spawn N warm serve workers and route traffic to them.

    # one command: launcher spawns the workers, routes the stream,
    # writes the CSV + one stats JSON line (serve_main's schema family)
    python -m pertgnn_tpu.cli.fleet_main --artifact_dir processed \
        --checkpoint_dir ckpts --compile_cache_dir cache/aot \
        --arena_cache_dir cache/arena --num_workers 4 \
        --from_split test --out served.csv

The launcher builds the dataset once (warm from --arena_cache_dir),
spawns ``--num_workers`` worker processes — each a full serve stack
(engine + PR-4-hardened microbatch queue) behind an HTTP transport
(fleet/transport.py) — waits for every /healthz readiness probe, then
drives the request stream through the front-door router
(fleet/router.py): deadline-aware least-loaded dispatch, requeue on
worker loss, probe-driven membership.

Warm start is the point: with shared ``--compile_cache_dir`` and
``--arena_cache_dir`` a worker goes cold-to-ready in seconds — zero
compiles (rung executables deserialize from the AOT store, PR 3) and
zero ingest (the dataset reconstructs from the mmap'd arena store,
PR 5). Each worker's probe body carries the evidence (``compiles``,
``deserialized``, ``arena_warm``), which benchmarks/fleet_bench.py
exit-code-asserts. TRUST: workers deserialize executables from the
compile cache and load training data from the arena cache — every
fleet member must trust whoever can write those directories exactly
as it trusts its checkpoints (docs/GUIDE.md).

Worker role (spawned internally; also usable standalone for one
worker per host): ``--role worker --worker_port P`` serves POST
/predict + GET /healthz until SIGTERM, then drains FAST — admissions
stop, the undispatched backlog is handed back via
``MicrobatchQueue.requeue()`` and answered with retryable QueueClosed
rows (the router re-dispatches them to surviving workers), in-flight
batches flush, exit 0.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from pertgnn_tpu.cli.common import (add_aot_flags, add_fleet_flags,
                                    add_ingest_flags,
                                    add_lens_flags, add_model_train_flags,
                                    add_serve_flags,
                                    add_telemetry_flags,
                                    apply_platform_env,
                                    build_dataset_cached, config_from_args,
                                    setup_compile_cache, setup_telemetry)
from pertgnn_tpu.utils.logging import setup_logging
from pertgnn_tpu.utils.profiling import LatencyRecorder

# launcher-only flags (value-taking unless noted) stripped from the
# argv forwarded to workers; everything else — ingest, model, serve,
# telemetry, aot, fleet tuning — forwards VERBATIM so a worker can
# never serve under a different config than the router believes
_LAUNCHER_ONLY = {"--role": 1, "--worker_port": 1, "--worker_id": 1,
                  "--worker_cpu": 1}


def _worker_argv(argv: list[str], worker_id: str, port: int) -> list[str]:
    out = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        key = tok.split("=", 1)[0]
        if key in _LAUNCHER_ONLY:
            i += 1 + (_LAUNCHER_ONLY[key] if "=" not in tok else 0)
            continue
        out.append(tok)
        i += 1
    return [*out, "--role", "worker", "--worker_id", worker_id,
            "--worker_port", str(port)]


def _free_port() -> int:
    """An ephemeral port that was free a moment ago (bind-and-release;
    the classic small race, acceptable for a single-host fleet — a
    collision fails the worker's bind loudly and the launcher reports
    it)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    add_ingest_flags(p)
    add_model_train_flags(p)
    add_serve_flags(p)
    add_lens_flags(p)
    add_fleet_flags(p)
    add_telemetry_flags(p)
    add_aot_flags(p)
    p.add_argument("--role", choices=("launch", "worker"),
                   default="launch",
                   help="launch (default): spawn workers + route a "
                        "request stream; worker: one serve worker "
                        "(spawned by the launcher, or standalone for "
                        "one-worker-per-host fleets)")
    p.add_argument("--worker_port", type=int, default=0,
                   help="worker role: HTTP port to bind (0 = ephemeral, "
                        "printed in the ready line)")
    p.add_argument("--worker_id", default="",
                   help="worker role: identity stamped into the probe "
                        "body and telemetry")
    p.add_argument("--worker_cpu", type=int, default=-1,
                   help="worker role: pin this worker (and its XLA "
                        "threadpool) to one CPU core via "
                        "sched_setaffinity; -1 = unpinned")
    p.add_argument("--pin_worker_cpus", action="store_true",
                   help="launcher: pin worker i to core i %% cpu_count "
                        "— the CPU emulation of the fleet's real "
                        "topology (one DEVICE per worker), and what "
                        "makes N-worker-vs-1 scaling measurements "
                        "honest on a shared-core host (fleet_bench)")
    p.add_argument("--fresh_init", action="store_true",
                   help="serve from a deterministic fresh-init state "
                        "instead of a checkpoint (seeded — every worker "
                        "inits bit-identically). For benches/tests "
                        "where fleet mechanics, not weights, are under "
                        "test; production fleets serve checkpoints")
    p.add_argument("--requests", default="",
                   help="CSV of requests (entry_id, ts_bucket columns); "
                        "default: replay --from_split")
    # open-loop trace-replay load generation (fleet/loadgen.py): these
    # are bench/scenario inputs, not pipeline semantics, so they live
    # here rather than in Config (like --requests/--concurrency)
    p.add_argument("--loadgen", action="store_true",
                   help="drive the fleet OPEN-LOOP from a generated "
                        "arrival schedule (bursts, diurnal envelope, "
                        "Zipf popularity, SLO mix — fleet/loadgen.py) "
                        "instead of closed-loop client threads; "
                        "deterministic per --seed")
    p.add_argument("--loadgen_duration_s", type=float, default=10.0)
    p.add_argument("--loadgen_base_rps", type=float, default=50.0)
    p.add_argument("--loadgen_burst_factor", type=float, default=1.0,
                   help="rate multiplier during burst windows "
                        "(<= 1 = no bursts)")
    p.add_argument("--loadgen_burst_every_s", type=float, default=0.0)
    p.add_argument("--loadgen_burst_len_s", type=float, default=1.0)
    p.add_argument("--loadgen_diurnal_amp", type=float, default=0.0,
                   help="diurnal rate envelope amplitude in [0, 1)")
    p.add_argument("--loadgen_diurnal_period_s", type=float,
                   default=10.0)
    p.add_argument("--loadgen_zipf_s", type=float, default=1.1,
                   help="Zipf popularity exponent over the request "
                        "population (0 = uniform)")
    p.add_argument("--loadgen_slo_mix",
                   default="critical:0.1,standard:0.3,best_effort:0.6",
                   help="SLO class mix as class:weight[,class:weight...]"
                        " (fleet/shield.py class names)")
    # batch counterfactual search (fleet/search.py): rides the served
    # request stream's hottest (entry, ts_bucket) after traffic ends,
    # through the same submit()/hedge/shed/memo machinery
    p.add_argument("--search", action="store_true",
                   help="after the request stream, beam-search the "
                        "hottest entry's drop/sub edit neighborhood "
                        "for the edit minimizing the predicted tail "
                        "quantile (fleet/search.py; stats JSON gains a "
                        "'search' record; zero fresh compiles by "
                        "construction)")
    p.add_argument("--search_beam", type=int, default=4,
                   help="beam width (states kept per depth)")
    p.add_argument("--search_depth", type=int, default=2,
                   help="max edits per candidate script")
    p.add_argument("--search_budget", type=int, default=96,
                   help="total submission budget, baseline included; "
                        "exhaustion truncates loudly "
                        "(search.budget_exhausted, "
                        "docs/RELIABILITY.md)")
    p.add_argument("--search_subs", type=int, default=4,
                   help="max distinct ms_ids offered as sub_node "
                        "candidates (drawn from the hot entry's own "
                        "mixture; 0 = drop_edge only)")
    p.add_argument("--from_split", default="test",
                   choices=("train", "valid", "test"))
    p.add_argument("--num_requests", type=int, default=0,
                   help="cap the request stream (0 = all)")
    p.add_argument("--concurrency", type=int, default=16,
                   help="client threads submitting to the router")
    p.add_argument("--out", default="served.csv",
                   help="per-request prediction CSV path")
    p.add_argument("--ready_timeout_s", type=float, default=600.0,
                   help="max seconds to wait for every worker's "
                        "readiness probe before aborting the launch")
    return p


# -- worker role ---------------------------------------------------------

def _run_worker(args, p: argparse.ArgumentParser) -> None:
    if not args.checkpoint_dir and not args.fresh_init:
        p.error("worker role needs --checkpoint_dir (or --fresh_init "
                "for weight-independent bench/test fleets)")
    if args.worker_cpu >= 0:
        # BEFORE the jax backend initializes: the XLA CPU threadpool
        # inherits this affinity mask, so the worker really is bounded
        # by one core — the CPU stand-in for one-device-per-worker
        if hasattr(os, "sched_setaffinity"):
            ncpu = os.cpu_count() or 1
            os.sched_setaffinity(0, {args.worker_cpu % ncpu})
        else:  # non-Linux: run unpinned rather than die
            print("WARNING: --worker_cpu needs sched_setaffinity; "
                  "running unpinned", file=sys.stderr)
    setup_telemetry(args, "fleet_worker")
    setup_compile_cache(args)
    cfg = config_from_args(args)

    # warm-start evidence for the probe body: is the arena entry this
    # exact (cfg, raw input) resolves to already on disk? (The answer
    # the bench asserts — computed with the store's own key so it
    # cannot drift from what load_or_build will actually hit.)
    arena_warm = False
    if cfg.data.arena_cache_dir:
        try:
            from pertgnn_tpu.batching.arena_store import (ArenaStore,
                                                          arena_cache_key)
            from pertgnn_tpu.cli.common import raw_input_fingerprint
            key, _ = arena_cache_key(cfg, raw_input_fingerprint(args))
            arena_warm = ArenaStore(cfg.data.arena_cache_dir).exists(key)
        except Exception as exc:  # evidence, not control flow
            print(f"WARNING: arena_warm probe failed: {exc}",
                  file=sys.stderr)

    dataset = build_dataset_cached(args, cfg)
    from pertgnn_tpu.train.loop import restore_target_state
    _model, state = restore_target_state(dataset, cfg)
    # the checkpoint epoch this worker serves, surfaced in the probe
    # body: the blue/green rollout controller (fleet/rollout.py) reads
    # it to VERIFY a replacement actually serves the refreshed
    # checkpoint before moving to the next worker (-1 = fresh init)
    ckpt_epoch = -1
    if args.checkpoint_dir:
        from pertgnn_tpu.train.checkpoint import CheckpointManager
        ckpt = CheckpointManager(args.checkpoint_dir,
                                 keep=args.checkpoint_keep)
        state, epoch = ckpt.maybe_restore(state)
        if epoch == 0:
            p.error(f"no checkpoint found in {args.checkpoint_dir}")
        ckpt_epoch = epoch - 1  # maybe_restore returns one PAST the save

    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.serve.errors import QueueClosed
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    engine = InferenceEngine.from_dataset(dataset, cfg, state)
    if cfg.serve.warmup:
        engine.warmup()
    worker_id = args.worker_id or f"w{os.getpid()}"

    stop = threading.Event()
    # trace_roots=False: the ROUTER is the fleet's trace front door —
    # a worker head-sampling its own roots would fork the sampling
    # decision per process; propagated contexts still trace here
    queue = MicrobatchQueue(engine, trace_roots=False)

    def extra():
        return {"worker_id": worker_id, "pid": os.getpid(),
                "compiles": engine.compiles,
                "deserialized": engine.deserialized,
                "arena_warm": arena_warm,
                "warmup_s": engine.warmup_s,
                "serve_dtype": engine.serve_dtype,
                "checkpoint_epoch": ckpt_epoch}

    server = WorkerServer(engine, queue, port=args.worker_port,
                          extra_fn=extra,
                          transport=cfg.fleet.transport,
                          shm_ring_slots=cfg.fleet.shm_ring_slots,
                          shm_slot_bytes=cfg.fleet.shm_slot_bytes)

    def _on_term(signum, frame):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except ValueError:  # not the main thread (embedded use)
        pass
    # ready marker on STDERR: the launcher scrapes the probe, humans
    # scrape logs; launcher stdout stays machine-parseable
    print(json.dumps({"worker_ready": True, "worker_id": worker_id,
                      "port": server.port, **extra()}),
          file=sys.stderr, flush=True)
    stop.wait()
    # FAST drain: stop admissions, hand the undispatched backlog back
    # (queue.requeue) and answer it with retryable QueueClosed rows so
    # the router moves it to surviving workers NOW instead of waiting
    # for this worker to serve a deep backlog; in-flight work flushes
    queue.begin_drain()
    handed_back = queue.requeue()
    for _eid, _ts, fut in handed_back:
        if not fut.done():
            fut.set_exception(QueueClosed(
                "worker draining (SIGTERM); requeue elsewhere"))
    queue.close()
    server.close()
    print(json.dumps({"worker_drained": True, "worker_id": worker_id,
                      "requeued_back": len(handed_back),
                      "queue": queue.stats_dict(),
                      "engine": engine.stats_dict()}),
          file=sys.stderr, flush=True)


# -- launcher role -------------------------------------------------------

def _spawn_workers(args, argv: list[str]):
    """[(worker_id, url, Popen)]; workers inherit stderr (their logs
    and ready lines interleave there) and this process's environment."""
    workers = []
    ncpu = os.cpu_count() or 1
    for i in range(args.num_workers):
        port = (args.worker_base_port + i if args.worker_base_port
                else _free_port())
        wid = f"w{i}"
        wargv = _worker_argv(argv, wid, port)
        if args.pin_worker_cpus:
            wargv += ["--worker_cpu", str(i % ncpu)]
        cmd = [sys.executable, "-m", "pertgnn_tpu.cli.fleet_main", *wargv]
        proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL)
        workers.append((wid, f"http://127.0.0.1:{port}", proc))
    return workers


def _await_ready(workers, timeout_s: float):
    """Poll every worker's /healthz until 200; returns the probe bodies
    (warm-start evidence). Aborts loudly if a worker process dies or
    the timeout lapses."""
    from pertgnn_tpu.fleet.transport import WorkerTransportError, get_probe

    deadline = time.monotonic() + timeout_s
    ready: dict[str, dict] = {}
    while len(ready) < len(workers):
        for wid, url, proc in workers:
            if wid in ready:
                continue
            if proc.poll() is not None:
                raise SystemExit(
                    f"worker {wid} exited rc={proc.returncode} before "
                    f"becoming ready (its logs are on stderr above)")
            try:
                status, body = get_probe(url, timeout_s=2.0)
            except WorkerTransportError:
                continue
            if status == 200:
                ready[wid] = body
        if len(ready) < len(workers):
            if time.monotonic() > deadline:
                missing = [w for w, _u, _p in workers if w not in ready]
                raise SystemExit(
                    f"workers {missing} not ready after "
                    f"{timeout_s:.0f}s")
            time.sleep(0.25)
    return ready


def _stop_workers(workers) -> None:
    for _wid, _url, proc in workers:
        if proc.poll() is None:
            proc.terminate()
    deadline = time.monotonic() + 60
    for wid, _url, proc in workers:
        try:
            proc.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            print(f"WARNING: worker {wid} ignored SIGTERM; killing",
                  file=sys.stderr)
            proc.kill()
            proc.wait()


def _parse_slo_mix(text: str):
    mix = []
    for part in text.split(","):
        name, _, w = part.strip().partition(":")
        mix.append((name.strip(), float(w or 1.0)))
    return tuple(mix)


def _make_autoscaler(args, argv, fcfg, router, bus, spare_procs,
                     spare_bodies):
    """The launcher's elastic-warm-spares wiring: spawn_spare starts a
    REAL worker subprocess (same argv the base workers got, so it
    starts warm from the shared AOT/arena stores), waits for its
    readiness probe, and records its warm-start evidence for the stats
    JSON; stop_spare is the SIGTERM drain. The controller itself is
    fleet/autoscale.py."""
    from pertgnn_tpu.fleet.autoscale import AutoscaleController
    from pertgnn_tpu.fleet.transport import (WorkerTransportError,
                                             get_probe)

    def spawn_spare(index: int):
        port = _free_port()
        wid = f"spare{index}"
        wargv = _worker_argv(argv, wid, port)
        cmd = [sys.executable, "-m", "pertgnn_tpu.cli.fleet_main",
               *wargv]
        proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL)
        spare_procs.append(proc)
        url = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + args.ready_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"spare {wid} exited rc={proc.returncode} before "
                    f"becoming ready")
            try:
                status, body = get_probe(url, timeout_s=2.0)
            except WorkerTransportError:
                time.sleep(0.1)
                continue
            if status == 200:
                spare_bodies[wid] = body
                return wid, url, proc, body
            time.sleep(0.1)
        proc.terminate()
        raise RuntimeError(f"spare {wid} not ready after "
                           f"{args.ready_timeout_s:.0f}s")

    def stop_spare(wid: str, proc):
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                print(f"WARNING: spare {wid} ignored SIGTERM; killing",
                      file=sys.stderr)
                proc.kill()
                proc.wait()

    return AutoscaleController(
        router, spawn_spare=spawn_spare, stop_spare=stop_spare,
        max_spares=fcfg.autoscale_max_spares,
        up_ms=fcfg.autoscale_up_ms, down_ms=fcfg.autoscale_down_ms,
        hold_s=fcfg.autoscale_hold_s,
        cooldown_s=fcfg.autoscale_cooldown_s, bus=bus).start()


def _await_spare_retire(scaler, fcfg, extra_s: float = 30.0) -> None:
    """Give the cooldown path a chance to retire live spares NATURALLY
    (traffic ended, the signal is calm) before close() force-retires
    them — tail_bench asserts a cooldown retire was OBSERVED, not just
    a teardown. Also waits out a spawn still mid-flight (a spare
    triggered during the storm may only become ready after it)."""
    deadline = time.monotonic() + fcfg.autoscale_cooldown_s + extra_s
    prev = None
    while time.monotonic() < deadline:
        st = scaler.stats_dict()
        key = (tuple(st["spares"]), st["spawning"])
        if key != prev:
            # state moved (spare became ready / one retired): re-arm
            # the window so a spare that readied late still gets its
            # full cooldown before the forced close
            prev = key
            deadline = (time.monotonic() + fcfg.autoscale_cooldown_s
                        + extra_s)
        if not st["spares"] and not st["spawning"]:
            return
        time.sleep(0.1)


def _run_launcher(args, p: argparse.ArgumentParser,
                  argv: list[str]) -> None:
    if not args.checkpoint_dir and not args.fresh_init:
        p.error("--checkpoint_dir is required (or --fresh_init for "
                "weight-independent bench/test fleets)")
    if args.num_workers < 1:
        p.error("--num_workers must be >= 1")
    bus = setup_telemetry(args, "fleet_main")
    cfg = config_from_args(args)
    dataset = build_dataset_cached(args, cfg)
    from pertgnn_tpu.cli.serve_main import _load_requests
    entries, buckets = _load_requests(args, dataset)
    if len(entries) == 0:
        raise SystemExit("no requests to serve")

    workers = _spawn_workers(args, argv)
    # machine-readable membership on stdout BEFORE traffic: the chaos
    # bench SIGKILLs a pid from this line mid-stream
    print(json.dumps({"fleet_workers": [
        {"worker_id": wid, "url": url, "pid": proc.pid}
        for wid, url, proc in workers]}), flush=True)
    try:
        t_spawn0 = time.perf_counter()
        ready = _await_ready(workers, args.ready_timeout_s)
        ready_s = time.perf_counter() - t_spawn0

        from pertgnn_tpu.fleet.router import FleetRouter
        from pertgnn_tpu.serve.buckets import make_bucket_ladder
        from pertgnn_tpu.serve.errors import ServeError

        top = make_bucket_ladder(dataset.budget, cfg.serve)[-1]

        def request_size(eid: int):
            m = dataset.mixtures[int(eid)]
            return m.num_nodes, m.num_edges

        client_latency = LatencyRecorder()
        import collections
        request_errors: collections.Counter = collections.Counter()
        errors_lock = threading.Lock()
        failures: list[tuple[int, BaseException]] = []
        schedule = None
        if args.loadgen:
            # open-loop: the request stream is the POPULATION the
            # arrival schedule draws from (Zipf popularity, SLO mix),
            # deterministic per --seed (fleet/loadgen.py)
            from pertgnn_tpu.fleet import loadgen
            spec = loadgen.LoadSpec(
                duration_s=args.loadgen_duration_s,
                base_rps=args.loadgen_base_rps,
                burst_factor=args.loadgen_burst_factor,
                burst_every_s=args.loadgen_burst_every_s,
                burst_len_s=args.loadgen_burst_len_s,
                diurnal_amp=args.loadgen_diurnal_amp,
                diurnal_period_s=args.loadgen_diurnal_period_s,
                zipf_s=args.loadgen_zipf_s,
                slo_mix=_parse_slo_mix(args.loadgen_slo_mix),
                seed=args.seed)
            schedule = loadgen.generate_schedule(spec, entries, buckets)
            out_entries = schedule.entry_ids
            out_buckets = schedule.ts_buckets
        else:
            out_entries, out_buckets = entries, buckets
        # multi-quantile heads (ModelConfig.quantile_taus, lens/) serve
        # one column per level; single-tau stays a flat vector
        from pertgnn_tpu.config import resolve_quantile_taus
        taus = resolve_quantile_taus(cfg.model, cfg.train.tau)
        preds = np.full((len(out_entries), len(taus)) if len(taus) > 1
                        else len(out_entries), np.nan, np.float32)
        served = np.zeros(len(out_entries), np.bool_)
        out_errors: list = [None] * len(out_entries)

        def client(router, indices):
            for i in indices:
                t0 = time.perf_counter()
                try:
                    # submit + result (not .predict): a multi-quantile
                    # future resolves to a (T,) vector float() rejects
                    preds[i] = router.submit(int(entries[i]),
                                             int(buckets[i])).result()
                except ServeError as exc:
                    with errors_lock:
                        request_errors[type(exc).__name__] += 1
                        out_errors[i] = type(exc).__name__
                    continue
                except BaseException as exc:  # lint: allow-silent-except — surfaced via SystemExit below
                    with errors_lock:
                        request_errors[type(exc).__name__] += 1
                        failures.append((i, exc))
                    continue
                served[i] = True
                client_latency.record_s(time.perf_counter() - t0)

        scaler = None
        spare_procs: list = []
        spare_bodies: dict = {}
        loadgen_stats = None
        search_stats = None
        t_serve0 = time.perf_counter()
        try:
            with FleetRouter(
                    {wid: url for wid, url, _p in workers},
                    request_size,
                    (top.max_graphs, top.max_nodes, top.max_edges),
                    cfg=cfg.fleet, bus=bus) as router:
                if router.memo is not None:
                    # arm the memo's generation with exactly what the
                    # predictions depend on: the fleet's checkpoint
                    # epoch (uniform across ready probes — _await_ready
                    # gates on all workers), the arena input
                    # fingerprint, and the quantile head layout.  A
                    # rollout (fleet/rollout.py) retires this at drain
                    # start and installs the successor only after full
                    # fleet verification.
                    import hashlib
                    from pertgnn_tpu.cli.common import (
                        raw_input_fingerprint)
                    epoch = max(
                        int(body.get("checkpoint_epoch", -1))
                        for body in ready.values())
                    fp = hashlib.sha256(json.dumps(
                        raw_input_fingerprint(args), sort_keys=True,
                        default=str).encode()).hexdigest()[:16]
                    router.memo.set_generation(
                        checkpoint_epoch=epoch,
                        arena_fingerprint=fp,
                        taus=tuple(float(t) for t in taus))
                if cfg.fleet.autoscale_max_spares > 0:
                    scaler = _make_autoscaler(args, argv, cfg.fleet,
                                              router, bus, spare_procs,
                                              spare_bodies)
                try:
                    if args.loadgen:
                        from pertgnn_tpu.fleet import loadgen
                        # vector result slots under a multi-quantile
                        # head (one column per tau — the PR-15 scalar
                        # refusal is lifted; loadgen.replay sizes the
                        # slots off the checkpoint's head width)
                        result = loadgen.replay(router.submit, schedule,
                                                bus=bus,
                                                vector_width=len(taus))
                        preds = result.preds
                        served = result.served_mask()
                        out_errors = result.errors
                        request_errors.update(result.error_counts())
                        loadgen_stats = {
                            "offered": result.offered,
                            "submitted": result.submitted,
                            "unresolved": result.unresolved,
                            "lost_futures": result.lost_futures(),
                            "lag_ms_max": float(result.lag_ms.max())
                            if len(result.lag_ms) else 0.0,
                            "latency_by_class":
                                result.latency_summary_by_class(
                                    schedule),
                            "taus": [float(t) for t in taus],
                        }
                        if preds.ndim == 2:
                            # per-tau columns in the stats JSON: the
                            # served mean per quantile level (NaN-free
                            # by the served mask)
                            loadgen_stats["served_mean_by_tau"] = {
                                f"q{t:g}": (float(
                                    preds[served, i].mean())
                                    if served.any() else None)
                                for i, t in enumerate(taus)}
                    else:
                        threads = [threading.Thread(
                            target=client,
                            args=(router,
                                  range(t, len(entries),
                                        max(1, args.concurrency))),
                            name=f"fleet-client-{t}")
                            for t in range(max(1, args.concurrency))]
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                    if scaler is not None:
                        _await_spare_retire(scaler, cfg.fleet)
                finally:
                    if scaler is not None:
                        scaler.close()
                if args.search:
                    # counterfactual search around the hottest served
                    # request (fleet/search.py): every candidate rides
                    # router.submit unchanged, so hedging, shedding,
                    # tracing, and the memo all apply
                    from pertgnn_tpu.fleet.search import (
                        CounterfactualSearch, SearchSpec)
                    hot = collections.Counter(
                        (int(e), int(b))
                        for e, b, ok in zip(out_entries, out_buckets,
                                            served) if ok)
                    if not hot:
                        raise SystemExit(
                            "--search: no request was served, nothing "
                            "to search around")
                    (hot_entry, hot_bucket), _n = hot.most_common(1)[0]
                    mix = dataset.mixtures[hot_entry]
                    subs = tuple(
                        int(m) for m in
                        np.unique(np.asarray(mix.ms_id))
                        [:max(0, args.search_subs)])
                    sresult = CounterfactualSearch(
                        router.submit,
                        SearchSpec(
                            entry_id=hot_entry, ts_bucket=hot_bucket,
                            num_nodes=int(mix.num_nodes),
                            num_edges=int(mix.num_edges),
                            beam_width=args.search_beam,
                            max_depth=args.search_depth,
                            budget=args.search_budget,
                            sub_ms_ids=subs),
                        bus=bus).run()
                    search_stats = sresult.to_dict()
                    search_stats["entry_id"] = hot_entry
                    search_stats["ts_bucket"] = hot_bucket
                router_stats = router.stats_dict()
                memo_stats = (router.memo.stats_dict()
                              if router.memo is not None else None)
                autoscale_stats = (scaler.stats_dict()
                                   if scaler is not None else None)
            serve_wall_s = time.perf_counter() - t_serve0
        finally:
            for proc in spare_procs:
                if proc.poll() is None:
                    proc.terminate()
    finally:
        _stop_workers(workers)

    import pandas as pd

    frame = {"entry_id": out_entries, "ts_bucket": out_buckets}
    if preds.ndim == 2:
        # one labeled column per quantile level + the primary under the
        # legacy y_pred name (same convention as serve_main/predict_main)
        from pertgnn_tpu.config import primary_tau_index
        for i, t in enumerate(taus):
            frame[f"y_pred_q{t:g}"] = preds[:, i]
        frame["y_pred"] = preds[:, primary_tau_index(taus,
                                                     cfg.train.tau)]
    else:
        frame["y_pred"] = preds
    if schedule is not None:
        frame["slo"] = [schedule.slo_name(i)
                        for i in range(len(schedule))]
        frame["error"] = out_errors
    pd.DataFrame(frame).to_csv(args.out, index=False)
    stats = {
        "metric": "fleet_request_latency_ms",
        "unit": "ms",
        "num_workers": args.num_workers,
        "requests": len(out_entries),
        "served": int(served.sum()),
        "request_errors": dict(request_errors),
        "concurrency": args.concurrency,
        "ready_s": round(ready_s, 3),
        "throughput_rps": int(served.sum()) / max(serve_wall_s, 1e-9),
        "serve_wall_s": round(serve_wall_s, 3),
        "client_latency": client_latency.summary_dict(),
        "router": router_stats,
        "workers_ready": ready,
        "captured_unix_time": time.time(),
    }
    if loadgen_stats is not None:
        stats["loadgen"] = loadgen_stats
    if autoscale_stats is not None:
        stats["autoscale"] = autoscale_stats
        stats["autoscale_workers"] = spare_bodies
    if memo_stats is not None:
        stats["memo"] = memo_stats
    if search_stats is not None:
        stats["search"] = search_stats
    bus.flush()
    print(f"wrote {len(out_entries)} predictions ({int(served.sum())} "
          f"served by {args.num_workers} worker(s)) to {args.out}",
          file=sys.stderr)
    print(json.dumps(stats), flush=True)
    if failures:
        i, exc = failures[0]
        raise SystemExit(
            f"{len(failures)} request(s) failed with non-serve errors; "
            f"first: request {i} (entry_id={int(entries[i])}) -> "
            f"{type(exc).__name__}: {exc}")
    if args.loadgen and loadgen_stats is not None:
        if loadgen_stats["lost_futures"] or loadgen_stats["unresolved"]:
            raise SystemExit(
                f"loadgen: {loadgen_stats['lost_futures']} lost "
                f"future(s), {loadgen_stats['unresolved']} unresolved "
                f"at tail-wait timeout — the ALWAYS-resolves contract "
                f"broke")
    if not served.any():
        raise SystemExit(
            f"no request was served: all {len(out_entries)} failed "
            f"({dict(request_errors) or 'no typed errors recorded'})")


def main(argv=None) -> None:
    setup_logging()
    apply_platform_env()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = _parser()
    args = p.parse_args(argv)
    if args.role == "worker":
        _run_worker(args, p)
    else:
        _run_launcher(args, p, argv)


if __name__ == "__main__":
    main()
