"""Canonical schemas for raw trace data.

Span rows mirror the Alibaba-2021 MSCallGraph CSV columns the reference
consumes (/root/reference/preprocess.py:296-298 shows the post-processed
frame; raw columns before factorization are the same names in string domain):

- traceid   : request id shared by all spans of one distributed request
- timestamp : call start time (ms)
- rpcid     : hierarchical call id, unique per span within a trace
- um        : upstream (calling) microservice name
- rpctype   : rpc kind ("http", "rpc", "mc", "db", "mq", ...)
- dm        : downstream (called) microservice name
- interface : called interface/endpoint id
- rt        : response time (ms); may be negative in the raw trace — the
              reference takes abs() everywhere (preprocess.py:114, 263, 291)

Resource rows mirror MSResource (/root/reference/preprocess.py:228-233):

- timestamp, msname, instance_cpu_usage, instance_memory_usage
"""

SPAN_COLUMNS = (
    "traceid",
    "timestamp",
    "rpcid",
    "um",
    "rpctype",
    "dm",
    "interface",
    "rt",
)

RESOURCE_COLUMNS = (
    "timestamp",
    "msname",
    "instance_cpu_usage",
    "instance_memory_usage",
)

# Number of numeric node features: 2 usage columns x 4 aggregations
# (reference: preprocess.py:237-240), plus one missing-indicator column
# appended at featurization time (pert_gnn.py:44-52).
NUM_RESOURCE_FEATURES = 8
NUM_NODE_FEATURES = NUM_RESOURCE_FEATURES + 1
