"""Synthetic microservice trace generator.

Produces raw-domain span and resource DataFrames with the same statistical
shape as the Alibaba-2021 MSCallGraph/MSResource CSVs the reference consumes
(/root/reference/preprocess.py:203-236), so the FULL ingest path — entry
detection, filters, factorization, runtime-pattern dedup, graph construction —
is exercised without the 200 GB download (BASELINE configs 1 and 5).

Generated structure:

- A pool of named microservices.
- E entry endpoints; each entry owns K call-graph topologies ("runtime
  patterns") sampled as random trees, with a fixed categorical probability
  over patterns.
- Each trace instantiates one pattern: an entry span (um="(?)",
  rpctype="http", maximal |rt|, minimal timestamp — matching the detection
  heuristic at /root/reference/preprocess.py:111-123) plus one span per edge.
  Per-pattern timestamp offsets are fixed so every trace of a pattern yields
  the same `um_dm_interface` corpus string and therefore the same runtime id
  after factorization (/root/reference/preprocess.py:280-293).
- A resource table sampled for every (30 s bucket, microservice) pair that
  traces touch, minus a configurable fraction of microservices left without
  resources to exercise the missing-feature path and the coverage filter.
- Trace latency y = entry |rt| is generated as
  entry_base * pattern_multiplier * (1 + 0.8 * cpu(entry_ms, bucket)) + noise,
  where cpu() is the same sinusoidal-drift signal written into the resource
  table — so the resource features carry real, learnable signal (the
  loss-decreases e2e test depends on this). The per-pattern multiplier
  (±15%) is deliberately small: the model observes only the entry's mixture,
  never the trace's actual pattern, so within-entry pattern variance is an
  irreducible noise floor.

Everything is deterministic given `seed`.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from pertgnn_tpu.ingest.schema import SPAN_COLUMNS, RESOURCE_COLUMNS


@dataclasses.dataclass(frozen=True)
class SyntheticSpec:
    num_microservices: int = 40
    num_entries: int = 4
    patterns_per_entry: int = 3
    # Nodes per pattern tree drawn uniformly from this range (inclusive).
    pattern_size_range: tuple[int, int] = (3, 8)
    traces_per_entry: int = 60
    num_interfaces: int = 12
    # Fraction of microservices with NO resource rows at all.
    missing_resource_frac: float = 0.15
    # Probability a non-entry span's raw rt is negated (the raw trace contains
    # negative rt; the reference abs()es everywhere).
    negative_rt_prob: float = 0.1
    # Wall-clock span of trace start times (ms).
    time_span_ms: int = 10 * 60 * 1000
    ts_bucket_ms: int = 30_000
    # Streaming-scenario knob (pertgnn_tpu/stream/): when set, the FIRST
    # trace of every (entry, pattern) pair starts before this instant,
    # so a base corpus sliced at/after it covers the full ms/interface/
    # rpctype vocabulary and later time-window shards ingest vocab-
    # stably (stream/delta.py).  None (default) leaves start times
    # untouched — byte-identical output to previous versions.
    ensure_pattern_coverage_before_ms: int | None = None
    seed: int = 0


_RPC_TYPES = ("rpc", "db", "mc", "mq")


def _random_tree(rng: np.random.Generator, n_nodes: int, ms_pool: np.ndarray,
                 root_ms: str, num_interfaces: int):
    """A random call tree: list of (um, dm, interface, rpctype, depth).

    Node microservices are sampled without replacement so a pattern has no
    self-loops and no duplicate (um, dm) pairs by construction (the messy
    cases — self-loops, duplicate rpcids, cycles — are covered by the
    hand-built golden tests, not the generator).
    """
    others = rng.choice(ms_pool[ms_pool != root_ms], size=n_nodes - 1,
                        replace=False)
    nodes = [root_ms] + list(others)
    edges = []
    for i in range(1, n_nodes):
        parent = rng.integers(0, i)  # guarantees a DAG (tree)
        depth = 1
        p = parent
        while p != 0:
            # recompute depth by walking up
            p = edges[p - 1][5]
            depth += 1
        iface = f"if_{rng.integers(0, num_interfaces)}"
        rpctype = _RPC_TYPES[rng.integers(0, len(_RPC_TYPES))]
        edges.append((nodes[parent], nodes[i], iface, rpctype, depth, parent))
    return [(um, dm, iface, t, d) for um, dm, iface, t, d, _ in edges]


@dataclasses.dataclass
class SyntheticData:
    spans: pd.DataFrame
    resources: pd.DataFrame
    spec: SyntheticSpec
    # ground-truth pattern index per trace, for debugging/tests
    trace_pattern: dict[str, tuple[int, int]]


def generate(spec: SyntheticSpec = SyntheticSpec()) -> SyntheticData:
    rng = np.random.default_rng(spec.seed)
    ms_pool = np.array([f"ms_{i}" for i in range(spec.num_microservices)])

    # --- entries and their patterns -------------------------------------
    entry_ms = rng.choice(ms_pool, size=spec.num_entries, replace=False)
    entries = []
    for e in range(spec.num_entries):
        patterns = []
        for _ in range(spec.patterns_per_entry):
            n = int(rng.integers(spec.pattern_size_range[0],
                                 spec.pattern_size_range[1] + 1))
            tree = _random_tree(rng, n, ms_pool, entry_ms[e],
                                spec.num_interfaces)
            # Fixed per-pattern start offsets (ms) for each span; defines a
            # stable within-trace ordering => stable corpus string.
            offsets = np.sort(rng.integers(1, 500, size=len(tree)))
            # small fixed per-pattern multiplier: within-entry variance the
            # model cannot resolve (it sees only the mixture) stays bounded
            patterns.append({"tree": tree, "offsets": offsets,
                             "latency_mult": float(rng.uniform(0.85, 1.15))})
        probs = rng.dirichlet(np.ones(spec.patterns_per_entry) * 2.0)
        entries.append({"ms": entry_ms[e], "interface": f"if_entry_{e}",
                        "patterns": patterns, "probs": probs,
                        "base_latency": float(rng.uniform(300, 2000))})

    # --- resource table -------------------------------------------------
    # entry microservices always keep resources: the label's cpu term must
    # stay observable or the e2e signal tests degrade to noise
    n_missing = int(spec.missing_resource_frac * spec.num_microservices)
    non_entry = ms_pool[~np.isin(ms_pool, entry_ms)]
    ms_without_resources = set(
        rng.choice(non_entry, size=min(n_missing, len(non_entry)),
                   replace=False).tolist())
    buckets = np.arange(0, spec.time_span_ms + spec.ts_bucket_ms,
                        spec.ts_bucket_ms)
    res_rows = []
    # Per-ms base load + per-bucket sinusoidal drift; 3 samples per
    # (bucket, ms) so the max/min/mean/median aggregations differ. The SAME
    # cpu_at() drives the labels below, so resource features carry real,
    # learnable signal (the loss-decreases e2e test depends on this).
    ms_base_cpu = {ms: rng.uniform(0.1, 0.8) for ms in ms_pool}
    ms_phase = {ms: rng.uniform(0, 2 * np.pi) for ms in ms_pool}

    def cpu_at(ms: str, b: int) -> float:
        return float(ms_base_cpu[ms] + 0.15 * np.sin(
            2 * np.pi * b / spec.time_span_ms + ms_phase[ms]))

    for ms in ms_pool:
        if ms in ms_without_resources:
            continue
        for b in buckets:
            cpu = np.clip(cpu_at(ms, int(b))
                          + rng.normal(0, 0.02, size=3), 0, 1)
            mem = np.clip(0.3 + 0.5 * cpu + rng.normal(0, 0.02, size=3), 0, 1)
            for c, m in zip(cpu, mem):
                res_rows.append((int(b), ms, float(c), float(m)))
    resources = pd.DataFrame(res_rows, columns=list(RESOURCE_COLUMNS))

    # --- traces ---------------------------------------------------------
    span_rows = []
    trace_pattern: dict[str, tuple[int, int]] = {}
    trace_counter = 0
    for e_idx, entry in enumerate(entries):
        choices = rng.choice(len(entry["patterns"]),
                             size=spec.traces_per_entry, p=entry["probs"])
        if spec.ensure_pattern_coverage_before_ms is not None:
            # every pattern must OCCUR in the stream of choices or the
            # coverage promise is vacuous. Each missing pattern
            # replaces the LAST occurrence of the currently most
            # frequent one — never truncation, which could silently
            # drop a pattern whose only occurrence sat in the tail
            choices = choices.copy()
            for p in range(len(entry["patterns"])):
                if p in choices:
                    continue
                counts = np.bincount(choices,
                                     minlength=len(entry["patterns"]))
                donor = int(np.argmax(counts))
                if counts[donor] <= 1:
                    break  # traces_per_entry < patterns: cover what fits
                choices[np.where(choices == donor)[0][-1]] = p
        seen_patterns: set[int] = set()
        for p_idx in choices:
            pat = entry["patterns"][p_idx]
            traceid = f"tr_{trace_counter:06d}"
            trace_counter += 1
            trace_pattern[traceid] = (e_idx, int(p_idx))
            t0 = int(rng.integers(0, spec.time_span_ms))
            if (spec.ensure_pattern_coverage_before_ms is not None
                    and int(p_idx) not in seen_patterns):
                # fold the first sight of each pattern into the early
                # window WITHOUT extra rng draws (determinism of the
                # remaining stream is preserved). The WHOLE trace must
                # land before the boundary — span offsets reach 499 ms
                # past t0, and stream slicers drop boundary-crossing
                # traces (shard_frames_by_window), which would silently
                # un-cover the pattern — so fold t0 with a margin
                margin = 600
                bound = max(spec.ensure_pattern_coverage_before_ms
                            - margin, 1)
                t0 = t0 % bound
                seen_patterns.add(int(p_idx))
            bucket = t0 // spec.ts_bucket_ms * spec.ts_bucket_ms
            # latency signal: entry base * pattern multiplier, scaled by the
            # OBSERVABLE time-varying cpu load of the entry microservice
            cpu = cpu_at(entry["ms"], bucket)
            y = (entry["base_latency"] * pat["latency_mult"]
                 * (1.0 + 0.8 * cpu) + float(rng.normal(0, 5.0)))
            y = max(y, 10.0)
            # entry span: um="(?)", dm=entry ms, http, min timestamp, max |rt|
            span_rows.append((traceid, t0, "0", "(?)", "http", entry["ms"],
                              entry["interface"], y))
            for k, ((um, dm, iface, rtype, depth), off) in enumerate(
                    zip(pat["tree"], pat["offsets"])):
                # child rt strictly below the entry's so the entry keeps
                # max |rt|; deeper calls are shorter
                rt = y * float(rng.uniform(0.2, 0.8)) / (depth + 1)
                if rng.random() < spec.negative_rt_prob:
                    rt = -rt
                span_rows.append((traceid, t0 + int(off), f"0.{k + 1}",
                                  um, rtype, dm, iface, rt))
    spans = pd.DataFrame(span_rows, columns=list(SPAN_COLUMNS))
    # Raw feeds arrive time-sorted (the reference sorts by timestamp,
    # preprocess.py:213); do the same here.
    spans = spans.sort_values(by=["timestamp"], kind="stable")
    spans = spans.reset_index(drop=True)
    return SyntheticData(spans=spans, resources=resources, spec=spec,
                         trace_pattern=trace_pattern)


def write_csvs(data: SyntheticData, out_dir: str, shards: int = 2) -> None:
    """Write spans/resources as sharded CSVs shaped like the raw dataset
    layout (data/MSCallGraph/*.csv, data/MSResource/*.csv)."""
    import os

    cg_dir = os.path.join(out_dir, "MSCallGraph")
    rs_dir = os.path.join(out_dir, "MSResource")
    os.makedirs(cg_dir, exist_ok=True)
    os.makedirs(rs_dir, exist_ok=True)
    for i, part in enumerate(np.array_split(np.arange(len(data.spans)),
                                            shards)):
        data.spans.iloc[part].to_csv(
            os.path.join(cg_dir, f"MSCallGraph_{i}.csv"))
    for i, part in enumerate(np.array_split(np.arange(len(data.resources)),
                                            shards)):
        data.resources.iloc[part].to_csv(
            os.path.join(rs_dir, f"MSResource_{i}.csv"), index=False)
