"""Raw dataset loading + artifact cache.

Raw layout mirrors the reference's expectation (preprocess.py:205, 228):

    <data_dir>/MSCallGraph/*.csv   — span rows
    <data_dir>/MSResource/*.csv    — resource rows

The artifact cache keeps the reference's idempotent skip-if-present idiom
(preprocess.py:23-29, 192-199; SURVEY.md §5.4) with npz/parquet instead of
pickles: `save_artifacts` / `load_artifacts` round-trip the PreprocessResult
and TraceTable, so the expensive L0-L2 pass runs once per dataset.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

import numpy as np
import pandas as pd

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.assemble import TraceTable, assemble
from pertgnn_tpu.ingest.preprocess import PreprocessResult, preprocess
from pertgnn_tpu.ingest.schema import RESOURCE_COLUMNS, SPAN_COLUMNS

log = logging.getLogger(__name__)

try:
    import pyarrow  # noqa: F401 — pandas engine="pyarrow" availability probe
    _CSV_ENGINE = "pyarrow"
except ImportError:  # pragma: no cover — pyarrow ships with the env
    _CSV_ENGINE = "c"


def _read_shard(path: str, columns) -> pd.DataFrame:
    """One raw CSV shard, schema-hardened:

    - pyarrow engine when available (the reference's choice against the
      200 GB+ raw dataset, /root/reference/preprocess.py:197, 205, 228);
    - only the schema columns are kept (raw shards carry an unnamed index
      column and occasionally extras — dropping them per shard bounds
      memory at ~1/NumShards of the naive full-tree read);
    - NaN cells in string columns become the literal "nan" (the raw trace
      uses both; the reference normalizes the same way via its na handling).
    """
    try:
        df = pd.read_csv(path, engine=_CSV_ENGINE)
    except Exception as e:
        # truncated / garbled shards happen on 200 GB-scale copies; fail
        # loudly with the shard path instead of a bare parser traceback
        raise ValueError(f"failed to parse raw shard {path}: "
                         f"{type(e).__name__}: {e}") from e
    missing = [c for c in columns if c not in df.columns]
    if missing:
        raise ValueError(f"{path} lacks expected columns {missing}; "
                         f"found {list(df.columns)}")
    df = df.loc[:, list(columns)]
    for c in df.columns:
        # pandas 3 infers the dedicated `str` dtype for string columns
        # (object under pandas 2) — cover both
        if (pd.api.types.is_object_dtype(df[c])
                or pd.api.types.is_string_dtype(df[c])):
            df[c] = df[c].fillna("nan")
    return df


def _raw_dirs(data_dir: str) -> tuple[str, str]:
    cg_dir = os.path.join(data_dir, "MSCallGraph")
    rs_dir = os.path.join(data_dir, "MSResource")
    for d in (cg_dir, rs_dir):
        if not os.path.isdir(d):
            raise FileNotFoundError(
                f"expected raw layout <data_dir>/MSCallGraph and "
                f"<data_dir>/MSResource; missing {d}")
    return cg_dir, rs_dir


def list_shards(root: str) -> list[str]:
    """Shard discovery both loaders share: sorted .csv filenames, loud
    error on an empty tree."""
    files = [f for f in sorted(os.listdir(root)) if f.endswith(".csv")]
    if not files:
        raise FileNotFoundError(f"no .csv shards under {root}")
    return files


def iter_shards(root: str, columns, dedupe: bool):
    """Yield (filename, pruned shard frame) for every CSV shard — the ONE
    shard-walk both loaders share (discovery via `list_shards`, schema
    hardening via `_read_shard`, per-shard dedupe; the streaming loader
    composes the same pieces in `_factorize_shard`)."""
    for f in list_shards(root):
        shard = _read_shard(os.path.join(root, f), columns)
        if dedupe:
            shard = shard.drop_duplicates()
        yield f, shard


def load_raw_csvs(data_dir: str) -> tuple[pd.DataFrame, pd.DataFrame]:
    """Concatenate the sharded raw CSVs (reference: preprocess.py:203-236).

    Shards are read, pruned, and de-duplicated ONE AT A TIME so peak memory
    tracks the pruned concatenation, never the raw tree."""
    cg_dir, rs_dir = _raw_dirs(data_dir)

    def read_tree(root, columns, dedupe):
        parts = []
        for f, shard in iter_shards(root, columns, dedupe):
            log.info("read %s: %d rows kept, engine=%s",
                     f, len(shard), _CSV_ENGINE)
            parts.append(shard)
        return pd.concat(parts, ignore_index=True)

    # Spans: shard-level dedupe is safe (preprocess() dedupes the whole
    # span frame again anyway — it only bounds memory early). Resources:
    # NO dedupe anywhere — repeated identical (ts, ms, cpu, mem) readings
    # are real samples; dropping them would shift the mean/median
    # aggregates (reference dedupes only the call-graph rows,
    # /root/reference/preprocess.py:209 vs :227-242).
    spans = read_tree(cg_dir, SPAN_COLUMNS, dedupe=True)
    resources = read_tree(rs_dir, RESOURCE_COLUMNS, dedupe=False)
    log.info("raw load: %d span rows, %d resource rows",
             len(spans), len(resources))
    return spans, resources


class StreamVocab:
    """Incremental string->dense-int vocabulary for streaming
    factorization: per-shard `pd.factorize` produces shard-local codes;
    only the shard's UNIQUES walk the python dict, so the per-shard cost
    is O(rows) vectorized + O(uniques) python."""

    def __init__(self):
        self.map: dict = {}
        self.items: list = []

    def encode(self, col: pd.Series) -> np.ndarray:
        # normalize NaN to the literal "nan" BEFORE factorizing — the
        # exact path's _read_shard does this for string columns, and a
        # -1 NaN sentinel here would otherwise alias glob[-1] (the last
        # unique) or crash on an all-NaN shard
        if col.isna().any():
            col = col.astype(object).fillna("nan")
        codes, uniques = pd.factorize(col)
        return self.merge(uniques)[codes]

    def merge(self, uniques) -> np.ndarray:
        """Fold one shard's factorize uniques into the global vocabulary;
        returns local-code -> global-code remap. This O(uniques) walk is
        the only serial part of the shard encode — the parallel loader
        runs it in the parent, in shard order, so worker count never
        changes the code assignment."""
        glob = np.empty(len(uniques), dtype=np.int64)
        for i, u in enumerate(uniques):
            g = self.map.get(u)
            if g is None:
                g = len(self.items)
                self.map[u] = g
                self.items.append(u)
            glob[i] = g
        if len(self.items) >= np.iinfo(np.int32).max:
            raise RuntimeError(
                f"stream vocabulary exceeded int32 range "
                f"({len(self.items)} entries) — the downstream int32 "
                f"code columns would wrap; shard the dataset or widen "
                f"the code dtype")
        return glob

    def code_of(self, value, default=-1) -> int:
        return self.map.get(value, default)


def _factorize_shard(path: str, columns, str_cols: tuple, dedupe: bool):
    """Worker half of the streaming encode: parse + prune + dedupe ONE
    shard and factorize its string columns to SHARD-LOCAL codes.

    Runs in a worker process under `ingest_workers > 1` — everything
    heavy (CSV parse, dedupe, vectorized factorize) is here; only the
    O(uniques) vocab merge stays in the parent (StreamVocab.merge), so
    results are independent of worker count and identical to the serial
    path. Returns ({col: codes-or-raw}, {col: uniques}, nrows)."""
    shard = _read_shard(path, columns)
    if dedupe:
        shard = shard.drop_duplicates()
    codes_d, uniq_d = {}, {}
    for c in columns:
        if c in str_cols:
            col = shard[c]
            if col.isna().any():  # mirror StreamVocab.encode's NaN rule
                col = col.astype(object).fillna("nan")
            codes, uniques = pd.factorize(col)
            codes_d[c] = codes.astype(np.int32)
            uniq_d[c] = np.asarray(uniques, dtype=object)
        else:
            codes_d[c] = shard[c].to_numpy()
    return codes_d, uniq_d, len(shard)


def load_raw_csvs_streaming(data_dir: str, cfg: IngestConfig,
                            workers: int = 1,
                            ) -> tuple[pd.DataFrame, pd.DataFrame,
                                       IngestConfig, dict]:
    """200GB-scale loader: factorize every string column PER SHARD
    against incremental vocabularies, so RAM holds only NUMERIC columns
    (int64/float64) — never the string pool of the whole tree.

    um/dm/msname share ONE vocabulary (the resource-coverage filter and
    the shared ms2int map need them comparable, preprocess.py:248-254 in
    the reference). The special tokens the pipeline compares against
    ("http" entry rpctype, "(?)" tie-break um) are translated to their
    codes in the RETURNED IngestConfig — `preprocess()` then runs
    UNCHANGED on the numeric frame.

    Trade-off vs `load_raw_csvs` (the default, exact path): codes are
    assigned in shard-read order rather than the reference's
    concat-sort-factorize order, so downstream ids are ISOMORPHIC to the
    exact path's (bijective relabeling), not equal — pinned by
    tests/test_ingest_scale.py::test_streaming_isomorphic. Peak RSS on
    the 2.66 GB measurement tree drops accordingly (RESULTS.md).

    Returns (spans, resources, translated_cfg, vocabs) where `vocabs`
    maps column -> StreamVocab (code -> raw string recovery).
    """
    cg_dir, rs_dir = _raw_dirs(data_dir)
    ms_vocab = StreamVocab()  # shared: um, dm, msname
    vocabs = {"traceid": StreamVocab(), "rpcid": StreamVocab(),
              "rpctype": StreamVocab(), "interface": StreamVocab(),
              "ms": ms_vocab}
    str_cols = {"traceid": vocabs["traceid"], "rpcid": vocabs["rpcid"],
                "um": ms_vocab, "dm": ms_vocab,
                "rpctype": vocabs["rpctype"],
                "interface": vocabs["interface"]}

    # Codes are downcast to int32 (vocab sizes are bounded by unique
    # strings, far under 2^31) and shards accumulate as per-COLUMN numpy
    # lists concatenated one column at a time — peak during load is then
    # ~one numeric frame + one column, not (412 shard frames + a pandas
    # concat double buffer), which dominated the measured peak before.
    #
    # workers > 1 (VERDICT r4 #4): shard parse+factorize fan out to a
    # process pool; the parent folds each shard's uniques into the
    # global vocabularies IN SHARD ORDER (StreamVocab.merge), so the
    # output frame, codes, and vocabs are byte-identical to workers=1 —
    # pinned by tests/test_ingest_scale.py::test_parallel_streaming_equal.
    def encode_tree(root, columns, colmap, dedupe):
        files = list_shards(root)
        str_cols = tuple(colmap)
        jobs = [(os.path.join(root, f), columns, str_cols, dedupe)
                for f in files]
        if workers > 1:
            import multiprocessing
            from collections import deque
            from concurrent.futures import ProcessPoolExecutor

            # spawn, not fork: the caller may be a process that already
            # imported jax (train_main does), and forking a multithreaded
            # parent risks deadlock in the child (Python 3.12 warns on
            # exactly this). Cost: spawn re-imports the caller's __main__
            # in every worker — from train_main that includes the jax
            # stack, seconds per worker — but one-time per pool and noise
            # against a multi-GB tree; a silent fork deadlock is not.
            pool = ProcessPoolExecutor(
                max_workers=workers,
                mp_context=multiprocessing.get_context("spawn"))

            def windowed():
                # Bounded in-flight window: at most 2*workers shards are
                # submitted-or-buffered at once, so a straggler shard
                # cannot make the parent hold every later shard's
                # completed result (executor.map would — breaking the
                # bounded-peak-RSS contract this loader exists for).
                pending: deque = deque()
                it = iter(jobs)
                while len(pending) < 2 * workers:
                    j = next(it, None)
                    if j is None:
                        break
                    pending.append(pool.submit(_factorize_shard, *j))
                while pending:
                    yield pending.popleft().result()  # shard order
                    j = next(it, None)
                    if j is not None:
                        pending.append(pool.submit(_factorize_shard, *j))

            results = windowed()
        else:
            pool = None
            results = (_factorize_shard(*j) for j in jobs)
        cols: dict[str, list] = {c: [] for c in columns}
        try:
            for f, (codes_d, uniq_d, nrows) in zip(files, results):
                for c in columns:
                    if c in colmap:
                        remap = colmap[c].merge(uniq_d[c])
                        cols[c].append(remap[codes_d[c]].astype(np.int32))
                    else:
                        cols[c].append(codes_d[c])
                log.info("stream-read %s: %d rows, vocab sizes ms=%d "
                         "trace=%d", f, nrows, len(ms_vocab.items),
                         len(vocabs["traceid"].items))
        except BaseException:
            if pool is not None:  # don't parse 2*workers more shards
                pool.shutdown(cancel_futures=True)  # before surfacing the
                pool = None                         # corrupt-shard error
            raise
        finally:
            if pool is not None:
                pool.shutdown()
        out = {}
        for c in columns:
            out[c] = np.concatenate(cols[c])
            cols[c].clear()  # free shard pieces before the next column
        return pd.DataFrame(out)

    spans = encode_tree(cg_dir, SPAN_COLUMNS, str_cols, dedupe=True)
    resources = encode_tree(rs_dir, RESOURCE_COLUMNS,
                            {"msname": ms_vocab}, dedupe=False)

    translated = dataclasses.replace(
        cfg,
        entry_rpctype=vocabs["rpctype"].code_of(cfg.entry_rpctype),
        entry_tiebreak_um=ms_vocab.code_of(cfg.entry_tiebreak_um))
    log.info("stream load: %d span rows, %d resource rows, "
             "%d microservices", len(spans), len(resources),
             len(ms_vocab.items))
    return spans, resources, translated, vocabs


def save_stream_vocabs(out_dir: str, vocabs: dict) -> None:
    """Persist streaming code -> raw-string recovery next to the artifact
    cache (np.load(..., allow_pickle=True) to read back)."""
    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, "stream_vocabs.npz"),
             **{name: np.asarray(v.items, dtype=object)
                for name, v in vocabs.items()})


def save_artifacts(out_dir: str, pre: PreprocessResult,
                   table: TraceTable) -> None:
    os.makedirs(out_dir, exist_ok=True)
    pre.spans.to_parquet(os.path.join(out_dir, "spans.parquet"))
    pre.resources.to_parquet(os.path.join(out_dir, "resources.parquet"))
    np.savez(os.path.join(out_dir, "vocabs.npz"),
             traceid=pre.traceid_vocab, interface=pre.interface_vocab,
             entryid=pre.entryid_vocab, rpctype=pre.rpctype_vocab,
             ms=pre.ms_vocab)
    with open(os.path.join(out_dir, "stats.json"), "w") as f:
        json.dump(pre.stats, f)
    table.meta.to_parquet(os.path.join(out_dir, "trace_meta.parquet"))
    entries = {str(k): {"runtimes": v[0].tolist(), "probs": v[1].tolist()}
               for k, v in table.entry2runtimes.items()}
    with open(os.path.join(out_dir, "entry2runtimes.json"), "w") as f:
        json.dump(entries, f)
    with open(os.path.join(out_dir, "runtime2trace.json"), "w") as f:
        json.dump({str(k): v for k, v in table.runtime2trace.items()}, f)
    log.info("artifacts written to %s", out_dir)


def artifacts_present(out_dir: str) -> bool:
    needed = ("spans.parquet", "resources.parquet", "vocabs.npz",
              "trace_meta.parquet", "entry2runtimes.json",
              "runtime2trace.json")
    return all(os.path.isfile(os.path.join(out_dir, f)) for f in needed)


def load_artifacts(out_dir: str) -> tuple[PreprocessResult, TraceTable]:
    vocabs = np.load(os.path.join(out_dir, "vocabs.npz"), allow_pickle=True)
    with open(os.path.join(out_dir, "stats.json")) as f:
        stats = json.load(f)
    pre = PreprocessResult(
        spans=pd.read_parquet(os.path.join(out_dir, "spans.parquet")),
        resources=pd.read_parquet(os.path.join(out_dir, "resources.parquet")),
        traceid_vocab=vocabs["traceid"], interface_vocab=vocabs["interface"],
        entryid_vocab=vocabs["entryid"], rpctype_vocab=vocabs["rpctype"],
        ms_vocab=vocabs["ms"], stats=stats)
    with open(os.path.join(out_dir, "entry2runtimes.json")) as f:
        entries = json.load(f)
    entry2runtimes = {
        int(k): (np.asarray(v["runtimes"], dtype=np.int64),
                 np.asarray(v["probs"], dtype=np.float64))
        for k, v in entries.items()}
    with open(os.path.join(out_dir, "runtime2trace.json")) as f:
        runtime2trace = {int(k): int(v) for k, v in json.load(f).items()}
    table = TraceTable(
        meta=pd.read_parquet(os.path.join(out_dir, "trace_meta.parquet")),
        entry2runtimes=entry2runtimes, runtime2trace=runtime2trace)
    return pre, table


def preprocess_cached(out_dir: str, spans: pd.DataFrame | None = None,
                      resources: pd.DataFrame | None = None,
                      data_dir: str | None = None,
                      cfg: IngestConfig = IngestConfig(),
                      ) -> tuple[PreprocessResult, TraceTable]:
    """Idempotent L0-L2: load the cache if complete, else compute + save."""
    if artifacts_present(out_dir):
        log.info("artifact cache hit at %s", out_dir)
        return load_artifacts(out_dir)
    if spans is None or resources is None:
        if data_dir is None or spans is not None or resources is not None:
            raise ValueError(
                "need BOTH spans and resources frames, or a data_dir")
        spans, resources = load_raw_csvs(data_dir)
    pre = preprocess(spans, resources, cfg)
    table = assemble(pre, cfg)
    save_artifacts(out_dir, pre, table)
    return pre, table
