"""L2: dataset assembly — runtime-pattern identity, labels, mixture weights.

Re-implements the first half of the reference's `main()`
(/root/reference/preprocess.py:269-316): each trace is represented as the
string of its `um_dm_interface` tokens in row (timestamp) order; identical
strings share a `runtime_id` (preprocess.py:280-293); the label is the
trace-maximal |rt| (preprocess.py:290-292); `entry2runtimes` holds, per entry,
the empirical probability of each runtime pattern (preprocess.py:310-316,
371-375).

The reference materializes these inside a per-(entry, trace) Python loop; here
everything is a vectorized pandas pass, and only ONE representative trace per
runtime pattern is handed to graph construction (matching the reference's
"first sight of runtime_id" behavior, preprocess.py:317-318: groupby iterates
entries and traces in sorted order, so first sight = minimal traceid).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.preprocess import PreprocessResult


@dataclasses.dataclass
class TraceTable:
    """Per-trace metadata + mixture weights, host-side."""

    # columns: traceid, entry_id, runtime_id, ts_bucket, y — row order is the
    # reference's tr2data insertion order (sorted by entry, then trace;
    # preprocess.py:295-309), which the 60/20/20 positional split depends on
    # (pert_gnn.py:198-200).
    meta: pd.DataFrame
    # entry_id -> (runtime_ids ordered by first appearance, probs)
    entry2runtimes: dict[int, tuple[np.ndarray, np.ndarray]]
    # runtime_id -> representative traceid (builds the pattern's graph)
    runtime2trace: dict[int, int]


def assemble(pre: PreprocessResult,
             cfg: IngestConfig = IngestConfig()) -> TraceTable:
    df = pre.spans

    token = (df["um"].astype(str) + "_" + df["dm"].astype(str)
             + "_" + df["interface"].astype(str))
    corpus = token.groupby(df["traceid"]).agg(" ".join)  # sorted by traceid
    runtime_id, _ = pd.factorize(corpus)
    tr2runtime = pd.Series(runtime_id, index=corpus.index)

    abs_rt = df["rt"].abs()
    tr2delay = abs_rt.groupby(df["traceid"]).max()
    tr2bucket = (df.groupby("traceid")["timestamp"].min()
                 // cfg.ts_bucket_ms * cfg.ts_bucket_ms)
    tr2entry = df.groupby("traceid")["entryid"].first()

    meta = pd.DataFrame({
        "traceid": corpus.index,
        "entry_id": tr2entry.loc[corpus.index].values,
        "runtime_id": tr2runtime.values,
        "ts_bucket": tr2bucket.loc[corpus.index].values,
        "y": tr2delay.loc[corpus.index].values.astype(np.float64),
    })
    # reference iteration order: sorted by entry, then by trace within entry
    meta = meta.sort_values(["entry_id", "traceid"],
                            kind="stable").reset_index(drop=True)

    # mixture weights per entry, runtime order = first appearance in the
    # sorted-trace iteration (matches dict-insertion order in the reference,
    # preprocess.py:310-316)
    entry2runtimes: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    for entry_id, grp in meta.groupby("entry_id", sort=True):
        rts = grp["runtime_id"]
        first_order = rts.drop_duplicates().values
        counts = rts.value_counts()
        probs = np.array([counts[rt] for rt in first_order], dtype=np.float64)
        probs /= probs.sum()
        entry2runtimes[int(entry_id)] = (first_order.astype(np.int64), probs)

    runtime2trace = meta.groupby("runtime_id")["traceid"].min().to_dict()
    runtime2trace = {int(k): int(v) for k, v in runtime2trace.items()}

    return TraceTable(meta=meta, entry2runtimes=entry2runtimes,
                      runtime2trace=runtime2trace)
