"""L2: dataset assembly — runtime-pattern identity, labels, mixture weights.

Re-implements the first half of the reference's `main()`
(/root/reference/preprocess.py:269-316): each trace is represented as the
string of its `um_dm_interface` tokens in row (timestamp) order; identical
strings share a `runtime_id` (preprocess.py:280-293); the label is the
trace-maximal |rt| (preprocess.py:290-292); `entry2runtimes` holds, per entry,
the empirical probability of each runtime pattern (preprocess.py:310-316,
371-375).

The reference materializes these inside a per-(entry, trace) Python loop; here
everything is a vectorized pandas pass, and only ONE representative trace per
runtime pattern is handed to graph construction (matching the reference's
"first sight of runtime_id" behavior, preprocess.py:317-318: groupby iterates
entries and traces in sorted order, so first sight = minimal traceid).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.preprocess import PreprocessResult


@dataclasses.dataclass
class TraceTable:
    """Per-trace metadata + mixture weights, host-side."""

    # columns: traceid, entry_id, runtime_id, ts_bucket, y — row order is the
    # reference's tr2data insertion order (sorted by entry, then trace;
    # preprocess.py:295-309), which the 60/20/20 positional split depends on
    # (pert_gnn.py:198-200).
    meta: pd.DataFrame
    # entry_id -> (runtime_ids ordered by first appearance, probs)
    entry2runtimes: dict[int, tuple[np.ndarray, np.ndarray]]
    # runtime_id -> representative traceid (builds the pattern's graph)
    runtime2trace: dict[int, int]


def _runtime_ids_numeric(df: pd.DataFrame) -> pd.Series | None:
    """Vectorized runtime-pattern identity WITHOUT corpus strings.

    The reference's corpus string (space-joined "um_dm_interface" tokens
    in row order, preprocess.py:280-293) is injective in the sequence of
    (um, dm, interface) triples once those columns are ints (fixed
    underscore arity), so string equality == triple-sequence equality.
    This computes the SAME runtime ids — first-appearance order over
    ascending traceid, pinned exactly by the reference cross-check — via
    packed token codes and a padded-matrix np.unique: at the 6.6M-trace
    scale measurement this replaced 22M string concatenations + a
    per-trace join (the single slowest pipeline phase, ~236 s) with
    ~3 s of numpy. Returns None when the inputs don't fit the fast
    path's bounds (non-integer columns, packing overflow, or a padded
    matrix that would exceed the memory guard) — caller falls back to
    the literal string corpus.
    """
    for c in ("traceid", "um", "dm", "interface"):
        if not pd.api.types.is_integer_dtype(df[c]):
            return None
    um = df["um"].to_numpy(np.int64)
    dm = df["dm"].to_numpy(np.int64)
    ifc = df["interface"].to_numpy(np.int64)
    tid = df["traceid"].to_numpy(np.int64)
    if min(um.min(initial=0), dm.min(initial=0), ifc.min(initial=0),
           tid.min(initial=0)) < 0:
        return None
    bits = [int(a.max(initial=0)).bit_length() + 1 for a in (um, dm, ifc)]
    if sum(bits) > 62:
        return None
    token = (um << (bits[1] + bits[2])) | (dm << bits[2]) | ifc

    order = np.argsort(tid, kind="stable")  # traces ascending, row order
    tid_s, token_s = tid[order], token[order]
    uniq_tid, start = np.unique(tid_s, return_index=True)
    counts = np.diff(np.concatenate([start, [len(tid_s)]]))
    max_len = int(counts.max(initial=0))
    n_traces = len(uniq_tid)
    # np.unique(axis=0) makes a contiguous copy + a sorted copy of the
    # matrix, so transient RSS is ~3x the matrix itself — budget the
    # MATRIX at 1.5 GiB (~4.5 GiB transient ceiling)
    if n_traces * max_len * 8 > int(1.5 * 2**30):
        return None
    total = int(counts.sum())
    pos = np.arange(total) - np.repeat(start, counts)
    mat = np.full((n_traces, max_len), -1, dtype=np.int64)
    mat[np.repeat(np.arange(n_traces), counts), pos] = token_s
    _, inverse = np.unique(mat, axis=0, return_inverse=True)
    inverse = inverse.ravel()
    # np.unique codes are sorted-order; the reference's are
    # first-appearance over ascending traceid — remap
    n_uniq = int(inverse.max(initial=-1)) + 1
    first = np.full(n_uniq, n_traces, dtype=np.int64)
    np.minimum.at(first, inverse, np.arange(n_traces))
    rank = np.empty(n_uniq, dtype=np.int64)
    rank[np.argsort(first)] = np.arange(n_uniq)
    return pd.Series(rank[inverse], index=uniq_tid)


def assemble(pre: PreprocessResult,
             cfg: IngestConfig = IngestConfig()) -> TraceTable:
    from pertgnn_tpu import telemetry
    with telemetry.span("ingest.assemble", rows=len(pre.spans)):
        return _assemble(pre, cfg)


def _assemble(pre: PreprocessResult, cfg: IngestConfig) -> TraceTable:
    df = pre.spans

    tr2runtime = _runtime_ids_numeric(df)
    if tr2runtime is None:
        token = (df["um"].astype(str) + "_" + df["dm"].astype(str)
                 + "_" + df["interface"].astype(str))
        corpus = token.groupby(df["traceid"]).agg(" ".join)  # by traceid
        runtime_id, _ = pd.factorize(corpus)
        tr2runtime = pd.Series(runtime_id, index=corpus.index)
    corpus = tr2runtime  # sorted-by-traceid index used below

    abs_rt = df["rt"].abs()
    tr2delay = abs_rt.groupby(df["traceid"]).max()
    tr2bucket = (df.groupby("traceid")["timestamp"].min()
                 // cfg.ts_bucket_ms * cfg.ts_bucket_ms)
    tr2entry = df.groupby("traceid")["entryid"].first()

    meta = pd.DataFrame({
        "traceid": corpus.index,
        "entry_id": tr2entry.loc[corpus.index].values,
        "runtime_id": tr2runtime.values,
        "ts_bucket": tr2bucket.loc[corpus.index].values,
        "y": tr2delay.loc[corpus.index].values.astype(np.float64),
    })
    return table_from_meta(meta)


def table_from_meta(meta: pd.DataFrame) -> TraceTable:
    """The meta -> TraceTable tail of assemble, shared with the stream
    subsystem (pertgnn_tpu/stream/merge.py builds a merged meta from
    base + delta shard entries and must derive mixture weights and
    representatives through the SAME code the batch path uses, so the
    two cannot drift)."""
    # reference iteration order: sorted by entry, then by trace within entry
    meta = meta.sort_values(["entry_id", "traceid"],
                            kind="stable").reset_index(drop=True)

    # mixture weights per entry, runtime order = first appearance in the
    # sorted-trace iteration (matches dict-insertion order in the reference,
    # preprocess.py:310-316)
    entry2runtimes: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    for entry_id, grp in meta.groupby("entry_id", sort=True):
        rts = grp["runtime_id"]
        first_order = rts.drop_duplicates().values
        counts = rts.value_counts()
        probs = np.array([counts[rt] for rt in first_order], dtype=np.float64)
        probs /= probs.sum()
        entry2runtimes[int(entry_id)] = (first_order.astype(np.int64), probs)

    runtime2trace = meta.groupby("runtime_id")["traceid"].min().to_dict()
    runtime2trace = {int(k): int(v) for k, v in runtime2trace.items()}

    return TraceTable(meta=meta, entry2runtimes=entry2runtimes,
                      runtime2trace=runtime2trace)
