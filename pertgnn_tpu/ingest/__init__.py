from pertgnn_tpu.ingest.schema import SPAN_COLUMNS, RESOURCE_COLUMNS
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.preprocess import (
    preprocess,
    PreprocessResult,
    detect_entries,
    filter_by_resource_coverage,
    filter_by_entry_occurrence,
    build_resource_table,
    factorize_columns,
)
