"""L0-L2: raw span cleaning, entry detection, filters, factorization.

Re-implements the behavior of the reference's `get_df`
(/root/reference/preprocess.py:191-266) with the same pipeline order and
filter semantics, but vectorized end-to-end: the reference's per-trace Python
`for` loop over `df.groupby("traceid")` (preprocess.py:110-137) — its single
largest preprocessing hot spot — becomes groupby-transform masks.

Pipeline order (must match the reference exactly, because factorization codes
depend on it):

1. concat shards, drop duplicates, sort by timestamp   (preprocess.py:203-213)
2. factorize traceid, then interface                   (preprocess.py:216-217)
3. entry detection + entryid assignment + trace filter (preprocess.py:218)
4. factorize entryid, rpcid, rpctype                   (preprocess.py:219-221)
5. resource table: concat, groupby (ts, ms), 4 aggs    (preprocess.py:227-242)
6. resource-coverage filter (>= 0.6)                   (preprocess.py:245)
7. entry-occurrence filter (> 100)                     (preprocess.py:246)
8. shared ms2int over um ∪ dm ∪ msname                 (preprocess.py:248-254)
9. endTimestamp = timestamp + |rt|                     (preprocess.py:263)
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Sequence

import numpy as np
import pandas as pd

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.schema import RESOURCE_COLUMNS

log = logging.getLogger(__name__)


def factorize_columns(df: pd.DataFrame, cols: Sequence[str]):
    """Jointly map the values of `cols` to dense ints starting at 0.

    Equivalent of the reference's `map_consecutive_ids`
    (/root/reference/preprocess.py:80-96): values are stacked across the
    columns and factorized together, so the same value in different columns
    gets the same code. Returns (df, uniques) with codes ordered by first
    appearance (pandas factorize semantics).

    Memory: every live call site passes ONE column, where stacking is a
    pointless row-count-sized double copy — the fast path factorizes the
    column directly (identical first-appearance codes; pinned against the
    reference's own preprocess by tests/test_reference_crosscheck.py).
    Frames are shallow-copied: pandas-3 copy-on-write makes the column
    assignment safe without materializing the other columns (measured on
    the 2.66 GB tree: benchmarks/ingest_scale_r4.py, RESULTS.md).
    """
    cols = list(cols)
    out = df.copy(deep=False)
    if len(cols) == 1:
        codes, uniques = pd.factorize(df[cols[0]])
        out[cols[0]] = codes
        return out, uniques
    stacked = df[cols].stack()
    codes, uniques = stacked.factorize()
    recoded = pd.Series(codes, index=stacked.index).unstack()
    for c in cols:
        out[c] = recoded[c]
    return out, uniques


def detect_entries(df: pd.DataFrame, cfg: IngestConfig = IngestConfig()):
    """Find each trace's entry row and drop traces without exactly one.

    Semantics of /root/reference/preprocess.py:99-149, vectorized:
    a candidate row has rpctype == "http", the trace-minimal timestamp and
    the trace-maximal |rt| (preprocess.py:111-115). Traces with multiple
    candidates fall back to candidates with um == "(?)" (preprocess.py:121);
    anything other than exactly one surviving candidate drops the trace.
    The entry id is the string `dm + "_" + interface` (preprocess.py:135).

    Returns (filtered df with an `entryid` column, stats dict).
    """
    g = df.groupby("traceid")
    is_cand = (
        (df["rpctype"] == cfg.entry_rpctype)
        & (df["timestamp"] == g["timestamp"].transform("min"))
        & (df["rt"].abs() == df["rt"].abs().groupby(df["traceid"]).transform("max"))
    )
    cand = df[is_cand]
    n_cand = cand.groupby("traceid").size()
    all_traces = df["traceid"].unique()

    # exactly one candidate -> take it
    unique_traces = n_cand[n_cand == 1].index
    # multiple candidates -> keep only um == "(?)" rows, need exactly one
    multi_traces = n_cand[n_cand > 1].index
    tiebreak = cand[cand["traceid"].isin(multi_traces)
                    & (cand["um"] == cfg.entry_tiebreak_um)]
    n_tie = tiebreak.groupby("traceid").size()
    tie_ok = n_tie[n_tie == 1].index

    keep_first = cand[cand["traceid"].isin(unique_traces)]
    keep_tie = tiebreak[tiebreak["traceid"].isin(tie_ok)]
    entries = pd.concat([keep_first, keep_tie])

    entry_str = entries["dm"].astype(str) + "_" + entries["interface"].astype(str)
    tr2entry = pd.Series(entry_str.values, index=entries["traceid"].values)

    # row filtering already yields a fresh frame under pandas-3 CoW; an
    # explicit deep copy here would double the surviving rows' footprint
    out = df[df["traceid"].isin(tr2entry.index)]
    out["entryid"] = out["traceid"].map(tr2entry)
    stats = {
        "num_traces": len(all_traces),
        "num_without_entry": int(len(all_traces) - len(n_cand)),
        "num_ambiguous_entry": int(len(multi_traces) - len(tie_ok)),
        "num_kept": int(tr2entry.size),
    }
    log.info("entry detection: %s", stats)
    return out, stats


def build_resource_table(resources: pd.DataFrame,
                         cfg: IngestConfig = IngestConfig()) -> pd.DataFrame:
    """(timestamp, msname) -> 8 aggregate usage features.

    Reference: /root/reference/preprocess.py:227-242 — groupby
    (timestamp, msname) over [cpu, mem] with aggs [max, min, mean, median],
    columns flattened to `<col>_<agg>`.
    """
    r = resources.loc[:, list(RESOURCE_COLUMNS)]
    agg = r.groupby(["timestamp", "msname"]).agg(list(cfg.resource_aggs))
    agg.columns = ["_".join(c) for c in agg.columns]
    return agg.reset_index()


def filter_by_resource_coverage(df: pd.DataFrame, resource_df: pd.DataFrame,
                                cfg: IngestConfig = IngestConfig()):
    """Keep traces where >= `min_resource_coverage` of the distinct
    microservices (union of um and dm) appear in the resource table.

    Reference: /root/reference/preprocess.py:155-177 (threshold 0.6,
    comparison is `>=`, preprocess.py:170).
    """
    def _packable(col, bound):
        # the packed-key fast path needs ms codes in [0, 2^32) and trace
        # codes in [0, 2^31) (the >> 32 unpack is an arithmetic shift):
        # true for StreamVocab/factorize codes, NOT for arbitrary native
        # int ids (64-bit hashes, negatives) — those take the general path
        a = df[col].to_numpy()
        return (pd.api.types.is_integer_dtype(df[col]) and len(a) > 0
                and int(a.min()) >= 0 and int(a.max()) < bound)

    if (_packable("um", 2**32) and _packable("dm", 2**32)
            and _packable("traceid", 2**31)
            # the fast path also reads msname as int64; a mixed-domain
            # input (int span codes, string resource names) must take the
            # general path instead of raising (ADVICE r4)
            and pd.api.types.is_integer_dtype(resource_df["msname"])):
        # Numeric fast path (the --stream_factorize loader): distinct
        # (trace, ms) pairs via ONE packed-int64 np.unique instead of a
        # 2x-row pandas concat + drop_duplicates — the concat was the
        # measured peak-RSS phase of the whole pipeline (RESULTS.md
        # round-4 scale proof; ms codes < 2^32 by construction).
        t = df["traceid"].to_numpy(np.int64)
        key = np.concatenate([
            (t << 32) | df["um"].to_numpy(np.int64),
            (t << 32) | df["dm"].to_numpy(np.int64)])
        pairs = np.unique(key)
        tr = pairs >> 32
        ms = pairs & np.int64(0xFFFFFFFF)
        covered = np.isin(
            ms, np.unique(resource_df["msname"].to_numpy(np.int64)))
        uniq_tr, start = np.unique(tr, return_index=True)
        n_pairs = np.diff(np.concatenate([start, [len(tr)]]))
        n_cov = np.add.reduceat(covered.astype(np.int64), start)
        keep_tr = uniq_tr[n_cov / n_pairs >= cfg.min_resource_coverage]
        return df[np.isin(t, keep_tr)]
    ms_with_res = set(resource_df["msname"].values)
    long = pd.concat([
        df[["traceid", "um"]].rename(columns={"um": "ms"}),
        df[["traceid", "dm"]].rename(columns={"dm": "ms"}),
    ]).drop_duplicates()
    long["covered"] = long["ms"].isin(ms_with_res)
    coverage = long.groupby("traceid")["covered"].mean()
    keep = coverage[coverage >= cfg.min_resource_coverage].index
    return df[df["traceid"].isin(keep)]


def filter_by_entry_occurrence(df: pd.DataFrame,
                               cfg: IngestConfig = IngestConfig()):
    """Keep traces whose entry occurs in strictly more than
    `min_traces_per_entry` traces (/root/reference/preprocess.py:180-188)."""
    occ = df.groupby("entryid")["traceid"].nunique()
    keep = occ[occ > cfg.min_traces_per_entry].index
    return df[df["entryid"].isin(keep)]


@dataclasses.dataclass
class PreprocessResult:
    spans: pd.DataFrame        # factorized columns + endTimestamp
    resources: pd.DataFrame    # msname (int), timestamp, 8 feature columns
    # factorization vocabularies (code -> original value)
    traceid_vocab: np.ndarray
    interface_vocab: np.ndarray
    entryid_vocab: np.ndarray
    rpctype_vocab: np.ndarray
    ms_vocab: np.ndarray
    stats: dict


def preprocess(spans: pd.DataFrame, resources: pd.DataFrame,
               cfg: IngestConfig = IngestConfig()) -> PreprocessResult:
    """Full L0→L2 pipeline on in-memory raw-domain frames."""
    from pertgnn_tpu import telemetry
    with telemetry.span("ingest.preprocess", rows=len(spans)):
        return _preprocess(spans, resources, cfg)


def _preprocess(spans: pd.DataFrame, resources: pd.DataFrame,
                cfg: IngestConfig) -> PreprocessResult:
    df = spans.drop_duplicates()
    df = df.sort_values(by=["timestamp"], kind="stable")
    log.info("raw: %d rows (%d after dedupe), %d traces",
             len(spans), len(df), df["traceid"].nunique())

    df, traceid_vocab = factorize_columns(df, ["traceid"])
    df, interface_vocab = factorize_columns(df, ["interface"])
    df, entry_stats = detect_entries(df, cfg)
    df, entryid_vocab = factorize_columns(df, ["entryid"])
    df, _ = factorize_columns(df, ["rpcid"])
    df, rpctype_vocab = factorize_columns(df, ["rpctype"])

    resource_df = build_resource_table(resources, cfg)
    # Per-filter trace accounting, as the reference prints at every stage
    # (/root/reference/preprocess.py:141-148, 160-176, 183-187) — silent
    # drops on the real trace are undebuggable.
    n0 = df["traceid"].nunique()
    df = filter_by_resource_coverage(df, resource_df, cfg)
    n1 = df["traceid"].nunique()
    num_coverage_dropped = n0 - n1
    log.info("resource-coverage filter (>= %.2f): %d -> %d traces (-%d)",
             cfg.min_resource_coverage, n0, n1, n0 - n1)
    # per-entry occurrence among coverage survivors, BEFORE the
    # occurrence filter — stream/merge.py's filter-drift guard compares
    # these against cumulative delta counts to detect (loudly) when a
    # batch rebuild of the grown corpus would resurrect traces this
    # build dropped
    occ_pre = df.groupby("entryid")["traceid"].nunique()
    entry_occ_prefilter = {str(entryid_vocab[int(code)]): int(c)
                           for code, c in occ_pre.items()}
    df = filter_by_entry_occurrence(df, cfg)
    n2, e2 = df["traceid"].nunique(), df["entryid"].nunique()
    log.info("entry-occurrence filter (> %d): %d -> %d traces (-%d), "
             "%d entries remain",
             cfg.min_traces_per_entry, n1, n2, n1 - n2, e2)

    # shared microservice vocabulary over um ∪ dm ∪ msname
    # (/root/reference/preprocess.py:248-254). The reference builds it from a
    # Python set — i.e. unordered; we sort for determinism, which only
    # permutes opaque ids.
    ms_vocab = np.sort(np.array(list(
        set(df["um"].values) | set(df["dm"].values)
        | set(resource_df["msname"].values))))
    ms2int = {ms: i for i, ms in enumerate(ms_vocab)}
    df["um"] = df["um"].map(ms2int)
    df["dm"] = df["dm"].map(ms2int)
    resource_df["msname"] = resource_df["msname"].map(ms2int).astype(np.int64)

    df["endTimestamp"] = df["timestamp"] + df["rt"].abs()

    stats = dict(entry_stats)
    stats["entry_occ_prefilter"] = entry_occ_prefilter
    # stream/merge.py's coverage-drift guard: when this is 0, no later
    # resource rows can resurrect a base trace (nothing was dropped)
    stats["num_coverage_dropped"] = int(num_coverage_dropped)
    stats["num_traces_final"] = int(df["traceid"].nunique())
    stats["num_entries_final"] = int(df["entryid"].nunique())
    # RAW span time range (pre-filter: dropped traces still occupied
    # sort positions, so stream/merge.py's shard-ordering guard must see
    # the full range, not the survivors')
    if len(spans):
        stats["span_ts_min"] = int(spans["timestamp"].min())
        stats["span_ts_max"] = int(spans["timestamp"].max())
    return PreprocessResult(
        spans=df.reset_index(drop=True),
        resources=resource_df,
        traceid_vocab=np.asarray(traceid_vocab),
        interface_vocab=np.asarray(interface_vocab),
        entryid_vocab=np.asarray(entryid_vocab),
        rpctype_vocab=np.asarray(rpctype_vocab),
        ms_vocab=ms_vocab,
        stats=stats,
    )
