"""Graph-transformer building blocks (flax.linen, segment ops, MXU GEMMs).

`GraphTransformerLayer` reimplements the semantics of PyG 2.4.0
`TransformerConv` as exercised by the reference (/root/reference/model.py:
25-52, 99-104; SURVEY.md §2.3):

    q = W_q x_dst + b_q
    k = W_k x_src + b_k           e  = W_e edge_feat        (no bias)
    v = W_v x_src + b_v
    alpha_ij = softmax_j->i ( <q_i, k_j + e_ij> / sqrt(C) )   per head
    out_i    = sum_j alpha_ij (v_j + e_ij)  ++ heads concat
    out_i   += W_skip x_i + b_skip          (root_weight=True default)

with the per-destination softmax computed as a masked segment softmax so
padding edges/nodes are unobservable. heads=1 matches the reference exactly
(model.py:29); heads>1 generalizes it for the deep/wide stress config with
out-channels split per head (hidden = heads * per-head-C).

`MaskedBatchNorm` replaces torch.nn.BatchNorm1d (model.py:34, 44, 101):
batch statistics are computed over VALID node rows only — flax's BatchNorm
is not padding-aware, and unmasked statistics would silently shift real
outputs with the amount of padding (SURVEY.md §7 "hard parts").
Defaults match torch BatchNorm1d: eps 1e-5, momentum 0.1, affine, running
stats used at eval.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from pertgnn_tpu.ops.segment import segment_edge_attention

log = logging.getLogger(__name__)

# In-process mirror of the model.kernel_fallback telemetry counter, keyed
# by the REQUESTED impl. Same-process harnesses (bench.py) read it to
# stamp whether the claimed attention_impl actually ran, so a trace-time
# fallback can never attribute segment-path numbers to a kernel variant.
FALLBACK_COUNTS: dict[str, int] = {}


def _count_kernel_fallback(impl: str, reason: str, **tags) -> None:
    """A requested kernel impl fell back to the segment path. NEVER
    silent (tools/check_excepts.py discipline): logged + counted on the
    telemetry bus. Fires at TRACE time — once per compiled program, not
    per step."""
    from pertgnn_tpu import telemetry

    FALLBACK_COUNTS[impl] = FALLBACK_COUNTS.get(impl, 0) + 1
    log.warning("attention_impl=%s fell back to the segment path (%s %s)",
                impl, reason, tags or "")
    telemetry.get_bus().counter("model.kernel_fallback", impl=impl,
                                reason=reason, **tags)


def kernel_initializer(scheme: str, role: str = "attn"):
    """Dense-kernel initializer per (scheme, role) — the single mapping.

    "torch": kaiming-uniform(a=sqrt5), i.e. U(+-1/sqrt(fan_in)) for every
    Linear — torch.nn.Linear's default, hence what the reference's PyG
    stack trains with (variance_scaling(1/3, fan_in, uniform) gives
    exactly bound sqrt(3*(1/3)/fan_in) = 1/sqrt(fan_in)).
    "torch_full": same kernels as "torch", plus torch's BIAS init
    U(+-1/sqrt(fan_in)) (see bias_initializer) — flax's zero biases are
    the one remaining init difference vs the reference stack.
    "flax": the framework's conventional defaults — glorot-uniform for
    attention projections ("attn"), flax's lecun-normal Dense default for
    output heads ("head")."""
    if scheme in ("torch", "torch_full"):
        return nn.initializers.variance_scaling(1.0 / 3.0, "fan_in",
                                                "uniform")
    if scheme == "flax":
        return (nn.initializers.glorot_uniform() if role == "attn"
                else nn.linear.default_kernel_init)
    raise ValueError(f"unknown init_scheme {scheme!r}")


def bias_initializer(scheme: str, fan_in: int):
    """Dense-bias initializer. torch.nn.Linear draws biases from
    U(+-1/sqrt(fan_in)); flax uses zeros. Only "torch_full" adopts the
    torch behavior (fan_in must be supplied by the caller — flax bias
    initializers only see the bias shape)."""
    if scheme == "torch_full":
        bound = 1.0 / (max(fan_in, 1) ** 0.5)
        return lambda key, shape, dtype=jnp.float32: jax.random.uniform(
            key, shape, dtype, -bound, bound)
    return nn.initializers.zeros


class _SkipParams(nn.Module):
    """Declares the skip projection's (kernel, bias) with EXACTLY the
    names/shapes nn.Dense(name="skip") would create, without applying the
    GEMM — the fused-epilogue path runs that matmul inside the Pallas
    kernel (ops/pallas_attention.fused_epilogue) but must stay
    checkpoint-compatible with every other attention_impl."""

    features: int
    kernel_init: Any
    bias_init: Any

    @nn.compact
    def __call__(self, in_features: int):
        kernel = self.param("kernel", self.kernel_init,
                            (in_features, self.features), jnp.float32)
        bias = self.param("bias", self.bias_init, (self.features,),
                          jnp.float32)
        return kernel, bias


class GraphTransformerLayer(nn.Module):
    out_channels: int          # total output width (= heads * per-head dim)
    heads: int = 1
    attn_dropout: float = 0.0  # PyG TransformerConv drops attention weights
    init_scheme: str = "torch"  # keep aligned with ModelConfig.init_scheme
    use_pallas: bool = False   # DEPRECATED alias for attention_impl="pallas"
    # Conv hot-op implementation (config.ATTENTION_IMPLS; the model passes
    # the RESOLVED impl via config.resolve_attention_impl). "segment"
    # honors the legacy use_pallas bool for direct constructors.
    attention_impl: str = "segment"
    # pallas_fused: also return the masked (Σy, Σy²) per-feature partials
    # the following MaskedBatchNorm needs (call gains a second return
    # value) — set only by PertGNN for non-final convs.
    emit_bn_stats: bool = False
    # Pallas tile sizes / blocked-dense admissibility (ModelConfig twins).
    kernel_block_n: int = 128
    kernel_block_e: int = 128
    blocked_dense_max_cells: int = 1 << 22
    # jax.sharding.Mesh: shard the EDGE set over the mesh's `data` axis
    # inside the layer (parallel/graph_shard.py) — the giant-graph /
    # "sequence parallel" path for DAGs whose edge set exceeds one chip
    # (ParallelConfig.shard_edges; BASELINE config 5). Static module attr;
    # nodes stay replicated.
    edge_shard_mesh: Any = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, edge_embeds, senders, receivers, edge_mask,
                 *, training: bool = False, node_mask=None):
        if self.out_channels % self.heads:
            raise ValueError(
                f"out_channels {self.out_channels} not divisible by heads "
                f"{self.heads}")
        H, C = self.heads, self.out_channels // self.heads
        dense = lambda name, bias: nn.Dense(
            H * C, use_bias=bias, name=name, dtype=self.dtype,
            kernel_init=kernel_initializer(self.init_scheme),
            bias_init=bias_initializer(self.init_scheme, x.shape[-1]))
        q = dense("query", True)(x)
        k = dense("key", True)(x)
        v = dense("value", True)(x)
        e = dense("edge", False)(edge_embeds)

        num_nodes = x.shape[0]
        attn_drop = self.attn_dropout > 0.0 and training
        impl = self.attention_impl
        if impl == "segment" and self.use_pallas:
            impl = "pallas"  # deprecated-bool alias

        mask_or_ones = (node_mask if node_mask is not None
                        else jnp.ones(num_nodes, bool))
        # The fused-epilogue path runs the skip GEMM inside the Pallas
        # kernel, so it declares the params WITHOUT applying nn.Dense —
        # created up front so a kernel fallback below reuses the same
        # params (flax forbids two modules named "skip" in one trace).
        skip_params = None
        if (impl == "pallas_fused" and not attn_drop
                and self.edge_shard_mesh is None):
            skip_params = _SkipParams(
                features=H * C, name="skip",
                kernel_init=kernel_initializer(self.init_scheme),
                bias_init=bias_initializer(self.init_scheme,
                                           x.shape[-1]))(x.shape[-1])

        def finish(out):
            """Unfused epilogue: skip projection + residual, plus the
            masked BN stat partials when the caller asked for them."""
            if skip_params is not None:
                w_s, b_s = skip_params
                y = out + (x.astype(self.dtype) @ w_s.astype(self.dtype)
                           + b_s.astype(self.dtype))
            else:
                y = out + dense("skip", True)(x)
            if not self.emit_bn_stats:
                return y
            m = mask_or_ones.astype(jnp.float32)[:, None]
            ym = y.astype(jnp.float32) * m
            stats = jnp.stack([ym.sum(0),
                               (ym * y.astype(jnp.float32)).sum(0)])
            return y, stats

        if self.edge_shard_mesh is not None and not attn_drop:
            if impl != "segment":
                # the edge-sharded formulation only exists for the
                # segment math — a mesh run of another impl is a
                # fallback and must say so
                _count_kernel_fallback(impl, "edge_shard_mesh")
            # k[senders] + e happens inside the shard_map, on each device's
            # edge shard; attn_dropout falls through to the segment path
            # (dropout on a sharded alpha would need per-shard rng plumbing)
            from pertgnn_tpu.parallel.graph_shard import (
                sharded_edge_attention)
            out = sharded_edge_attention(
                q.reshape(-1, H, C), k.reshape(-1, H, C),
                v.reshape(-1, H, C), e.reshape(-1, H, C),
                senders, receivers, edge_mask,
                self.edge_shard_mesh).astype(self.dtype)
            return finish(out)

        k_e = k[senders].reshape(-1, H, C) + e.reshape(-1, H, C)
        v_e = v[senders].reshape(-1, H, C) + e.reshape(-1, H, C)

        if impl in ("pallas", "pallas_fused") and not attn_drop:
            try:
                from pertgnn_tpu.ops.pallas_attention import (
                    edge_attention, fused_epilogue)
                attn = edge_attention(q.reshape(-1, H, C), k_e, v_e,
                                      receivers, edge_mask, num_nodes,
                                      block_n=self.kernel_block_n,
                                      block_e=self.kernel_block_e,
                                      assume_sorted=True)
                if impl == "pallas_fused" and self.emit_bn_stats:
                    w_s, b_s = skip_params
                    y, stats = fused_epilogue(attn, x, w_s, b_s,
                                              mask_or_ones,
                                              block_n=self.kernel_block_n)
                    return y.astype(self.dtype), stats
                # pallas_fused with no stats consumer (final conv, eval /
                # serve): the epilogue is just attn + skip GEMM + bias —
                # XLA fuses that on its own, and skipping the Pallas
                # stats kernel avoids paying for a (2, HD) masked
                # accumulation nobody reads (a pallas_call output can
                # never be DCE'd)
                return finish(attn.astype(self.dtype))
            except Exception as err:  # Pallas unavailable on this stack
                _count_kernel_fallback(impl, "pallas_unavailable",
                                       error=type(err).__name__)
        elif impl == "blocked_dense" and not attn_drop:
            from pertgnn_tpu.ops import blocked_dense as bd
            num_edges = int(k_e.shape[0])
            if bd.fits(num_nodes, num_edges, self.blocked_dense_max_cells,
                       self.kernel_block_n, self.kernel_block_e):
                out = bd.blocked_dense_edge_attention(
                    q.reshape(-1, H, C), k_e, v_e, receivers, edge_mask,
                    num_nodes, block_n=self.kernel_block_n,
                    block_e=self.kernel_block_e)
                return finish(out.astype(self.dtype))
            _count_kernel_fallback(
                "blocked_dense", "max_cells", nodes=num_nodes,
                edges=num_edges,
                cells=bd.dense_cells(num_nodes, num_edges,
                                     self.kernel_block_n,
                                     self.kernel_block_e),
                max_cells=self.blocked_dense_max_cells)
        elif impl != "segment" and attn_drop:
            # attention-weight dropout needs the segment formulation's
            # alpha hook — fall back, visibly
            _count_kernel_fallback(impl, "attn_dropout")

        alpha_fn = None
        if attn_drop:
            drop = nn.Dropout(rate=self.attn_dropout,
                              deterministic=False)
            alpha_fn = lambda a: drop(a)
        out = segment_edge_attention(
            q.reshape(-1, H, C), k_e, v_e, receivers, edge_mask,
            num_nodes, alpha_fn=alpha_fn)
        return finish(out)


class MaskedBatchNorm(nn.Module):
    momentum: float = 0.1      # torch convention: new = (1-m)*old + m*batch
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask, *, training: bool = False,
                 precomputed_sums=None):
        """`precomputed_sums` — a (2, features) array of masked (Σx, Σx²)
        per-feature partials, e.g. from the fused Pallas epilogue
        (ops/pallas_attention.fused_epilogue) — replaces the training
        statistics reduction (mean = Σx/n, biased var = Σx²/n − mean²,
        clamped ≥ 0) so this module never re-reads x from HBM for stats;
        the normalize + affine remain here and fuse with the following
        relu. Ignored at eval (running stats)."""
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)

        if training:
            w = mask.astype(jnp.float32)[:, None]
            n = jnp.maximum(w.sum(), 1.0)
            if precomputed_sums is not None:
                s, ss = precomputed_sums[0], precomputed_sums[1]
                mean = s / n
                # E[x²] − E[x]² == the masked biased variance below,
                # up to rounding; clamp the cancellation residue
                var = jnp.maximum(ss / n - mean * mean, 0.0)
            else:
                mean = (x * w).sum(0) / n
                # biased variance for normalization (torch semantics) ...
                var = ((x - mean) ** 2 * w).sum(0) / n
            if not self.is_initializing():
                # ... but unbiased variance tracked in running stats
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        return (y * scale + bias).astype(self.dtype)
