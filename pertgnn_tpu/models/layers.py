"""Graph-transformer building blocks (flax.linen, segment ops, MXU GEMMs).

`GraphTransformerLayer` reimplements the semantics of PyG 2.4.0
`TransformerConv` as exercised by the reference (/root/reference/model.py:
25-52, 99-104; SURVEY.md §2.3):

    q = W_q x_dst + b_q
    k = W_k x_src + b_k           e  = W_e edge_feat        (no bias)
    v = W_v x_src + b_v
    alpha_ij = softmax_j->i ( <q_i, k_j + e_ij> / sqrt(C) )   per head
    out_i    = sum_j alpha_ij (v_j + e_ij)  ++ heads concat
    out_i   += W_skip x_i + b_skip          (root_weight=True default)

with the per-destination softmax computed as a masked segment softmax so
padding edges/nodes are unobservable. heads=1 matches the reference exactly
(model.py:29); heads>1 generalizes it for the deep/wide stress config with
out-channels split per head (hidden = heads * per-head-C).

`MaskedBatchNorm` replaces torch.nn.BatchNorm1d (model.py:34, 44, 101):
batch statistics are computed over VALID node rows only — flax's BatchNorm
is not padding-aware, and unmasked statistics would silently shift real
outputs with the amount of padding (SURVEY.md §7 "hard parts").
Defaults match torch BatchNorm1d: eps 1e-5, momentum 0.1, affine, running
stats used at eval.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from pertgnn_tpu.ops.segment import segment_edge_attention


def kernel_initializer(scheme: str, role: str = "attn"):
    """Dense-kernel initializer per (scheme, role) — the single mapping.

    "torch": kaiming-uniform(a=sqrt5), i.e. U(+-1/sqrt(fan_in)) for every
    Linear — torch.nn.Linear's default, hence what the reference's PyG
    stack trains with (variance_scaling(1/3, fan_in, uniform) gives
    exactly bound sqrt(3*(1/3)/fan_in) = 1/sqrt(fan_in)).
    "torch_full": same kernels as "torch", plus torch's BIAS init
    U(+-1/sqrt(fan_in)) (see bias_initializer) — flax's zero biases are
    the one remaining init difference vs the reference stack.
    "flax": the framework's conventional defaults — glorot-uniform for
    attention projections ("attn"), flax's lecun-normal Dense default for
    output heads ("head")."""
    if scheme in ("torch", "torch_full"):
        return nn.initializers.variance_scaling(1.0 / 3.0, "fan_in",
                                                "uniform")
    if scheme == "flax":
        return (nn.initializers.glorot_uniform() if role == "attn"
                else nn.linear.default_kernel_init)
    raise ValueError(f"unknown init_scheme {scheme!r}")


def bias_initializer(scheme: str, fan_in: int):
    """Dense-bias initializer. torch.nn.Linear draws biases from
    U(+-1/sqrt(fan_in)); flax uses zeros. Only "torch_full" adopts the
    torch behavior (fan_in must be supplied by the caller — flax bias
    initializers only see the bias shape)."""
    if scheme == "torch_full":
        bound = 1.0 / (max(fan_in, 1) ** 0.5)
        return lambda key, shape, dtype=jnp.float32: jax.random.uniform(
            key, shape, dtype, -bound, bound)
    return nn.initializers.zeros


class GraphTransformerLayer(nn.Module):
    out_channels: int          # total output width (= heads * per-head dim)
    heads: int = 1
    attn_dropout: float = 0.0  # PyG TransformerConv drops attention weights
    init_scheme: str = "torch"  # keep aligned with ModelConfig.init_scheme
    use_pallas: bool = False   # fused edge-attention kernel for the hot op
    # jax.sharding.Mesh: shard the EDGE set over the mesh's `data` axis
    # inside the layer (parallel/graph_shard.py) — the giant-graph /
    # "sequence parallel" path for DAGs whose edge set exceeds one chip
    # (ParallelConfig.shard_edges; BASELINE config 5). Static module attr;
    # nodes stay replicated.
    edge_shard_mesh: Any = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, edge_embeds, senders, receivers, edge_mask,
                 *, training: bool = False):
        if self.out_channels % self.heads:
            raise ValueError(
                f"out_channels {self.out_channels} not divisible by heads "
                f"{self.heads}")
        H, C = self.heads, self.out_channels // self.heads
        dense = lambda name, bias: nn.Dense(
            H * C, use_bias=bias, name=name, dtype=self.dtype,
            kernel_init=kernel_initializer(self.init_scheme),
            bias_init=bias_initializer(self.init_scheme, x.shape[-1]))
        q = dense("query", True)(x)
        k = dense("key", True)(x)
        v = dense("value", True)(x)
        e = dense("edge", False)(edge_embeds)

        num_nodes = x.shape[0]
        attn_drop = self.attn_dropout > 0.0 and training
        if self.edge_shard_mesh is not None and not attn_drop:
            # k[senders] + e happens inside the shard_map, on each device's
            # edge shard; attn_dropout falls through to the segment path
            # (dropout on a sharded alpha would need per-shard rng plumbing)
            from pertgnn_tpu.parallel.graph_shard import (
                sharded_edge_attention)
            out = sharded_edge_attention(
                q.reshape(-1, H, C), k.reshape(-1, H, C),
                v.reshape(-1, H, C), e.reshape(-1, H, C),
                senders, receivers, edge_mask,
                self.edge_shard_mesh).astype(self.dtype)
            return out + dense("skip", True)(x)

        k_e = k[senders].reshape(-1, H, C) + e.reshape(-1, H, C)
        v_e = v[senders].reshape(-1, H, C) + e.reshape(-1, H, C)

        if self.use_pallas and not attn_drop:
            from pertgnn_tpu.ops.pallas_attention import edge_attention
            out = edge_attention(q.reshape(-1, H, C), k_e, v_e, receivers,
                                 edge_mask, num_nodes,
                                 assume_sorted=True).astype(self.dtype)
        else:
            alpha_fn = None
            if self.attn_dropout > 0.0 and training:
                drop = nn.Dropout(rate=self.attn_dropout,
                                  deterministic=False)
                alpha_fn = lambda a: drop(a)
            out = segment_edge_attention(
                q.reshape(-1, H, C), k_e, v_e, receivers, edge_mask,
                num_nodes, alpha_fn=alpha_fn)
        out = out + dense("skip", True)(x)
        return out


class MaskedBatchNorm(nn.Module):
    momentum: float = 0.1      # torch convention: new = (1-m)*old + m*batch
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask, *, training: bool = False):
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)

        if training:
            w = mask.astype(jnp.float32)[:, None]
            n = jnp.maximum(w.sum(), 1.0)
            mean = (x * w).sum(0) / n
            # biased variance for normalization (torch semantics) ...
            var = ((x - mean) ** 2 * w).sum(0) / n
            if not self.is_initializing():
                # ... but unbiased variance tracked in running stats
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) * jax.lax.rsqrt(var + self.eps)
        return (y * scale + bias).astype(self.dtype)
