"""The PERT-GNN latency-regression model (flax.linen).

Architecture parity with the reference's `SAGEDeterministic`
(/root/reference/model.py:10-114 — the name is vestigial; it is a graph
transformer, SURVEY.md §2.3):

- inputs: numeric node features ++ summed categorical (microservice)
  embeddings (model.py:87-90); edge features = interface-embedding ++
  rpctype-embedding (model.py:91-97);
- `max(2, num_layers)` conv layers with `max(1, num_layers-1)` BatchNorms —
  the reference's exact (and quirky) stack arithmetic (model.py:24-52):
  every conv but the last is followed by BN → ReLU → dropout (model.py:99-103),
  the last conv is bare (model.py:104);
- per-node local head (model.py:53, 105) — computed and returned; its loss
  weight is a config option (the reference never trains on it,
  pert_gnn.py:245);
- global head: prob-weighted mixture pooling, concat entry embedding,
  2-layer MLP → scalar (model.py:106-112); optional non-negativity clamp
  (the unimplemented comment at model.py:113). With
  `ModelConfig.quantile_taus` >= 2 levels the head widens to one column
  per tau under a cumulative-softplus non-crossing parameterization
  (distributional serving, pertgnn_tpu/lens/).

TPU-first details: all GEMMs via flax Dense on the MXU (optionally bf16
activations), attention via masked segment ops, BatchNorm masked for
padding, everything shape-static under jit.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from pertgnn_tpu.config import ModelConfig, resolve_attention_impl
from pertgnn_tpu.models.layers import (GraphTransformerLayer,
                                       MaskedBatchNorm, bias_initializer,
                                       kernel_initializer)
from pertgnn_tpu.ops.segment import segment_mean_by_graph


class PertGNN(nn.Module):
    cfg: ModelConfig
    num_ms: int
    num_entries: int
    num_interfaces: int
    num_rpctypes: int
    # Mesh to shard each layer's EDGE set over (ParallelConfig.shard_edges —
    # the giant-graph path, parallel/graph_shard.py); None = unsharded.
    edge_shard_mesh: Any = None

    @nn.compact
    def __call__(self, batch, *, training: bool = False):
        cfg = self.cfg
        hidden = cfg.hidden_channels
        dtype = jnp.bfloat16 if cfg.bf16_activations else jnp.float32
        num_graphs = batch.entry_id.shape[0]

        embed = lambda n, num: nn.Embed(
            num, hidden, name=n, dtype=dtype,
            embedding_init=nn.initializers.normal(1.0))
        ms_emb = embed("ms_embed", self.num_ms)(batch.ms_id)
        x = jnp.concatenate([batch.x.astype(dtype), ms_emb], axis=1)
        edge_parts = [
            embed("interface_embed", self.num_interfaces)(batch.edge_iface),
            embed("rpctype_embed", self.num_rpctypes)(batch.edge_rpctype),
        ]
        if cfg.use_edge_durations:
            edge_parts.append(
                jnp.log1p(batch.edge_duration).astype(dtype)[:, None])
        edge_embeds = jnp.concatenate(edge_parts, axis=1)

        impl = resolve_attention_impl(cfg)
        conv_kwargs = dict(out_channels=hidden, heads=cfg.num_heads,
                           dtype=dtype, attn_dropout=cfg.attn_dropout,
                           init_scheme=cfg.init_scheme,
                           attention_impl=impl,
                           kernel_block_n=cfg.kernel_block_n,
                           kernel_block_e=cfg.kernel_block_e,
                           blocked_dense_max_cells=cfg.blocked_dense_max_cells,
                           edge_shard_mesh=self.edge_shard_mesh)
        # pallas_fused: the conv's Pallas epilogue also emits the masked
        # (Σy, Σy²) partials the following MaskedBatchNorm consumes, so
        # the BN statistics pass never re-reads the conv output from HBM.
        # Training only — at eval/serve MaskedBatchNorm normalizes with
        # running stats and would discard the partials, so the conv
        # skips the stats kernel entirely there.
        fused_bn = (impl == "pallas_fused"
                    and self.edge_shard_mesh is None and training)
        num_convs = max(2, cfg.num_layers)
        for i in range(num_convs - 1):
            x = GraphTransformerLayer(name=f"conv_{i}",
                                      emit_bn_stats=fused_bn,
                                      **conv_kwargs)(
                x, edge_embeds, batch.senders, batch.receivers,
                batch.edge_mask, training=training,
                node_mask=batch.node_mask)
            sums = None
            if fused_bn:
                x, sums = x
            x = MaskedBatchNorm(name=f"bn_{i}", dtype=dtype)(
                x, batch.node_mask, training=training,
                precomputed_sums=sums)
            x = nn.relu(x)
            if cfg.dropout > 0.0:
                x = nn.Dropout(rate=cfg.dropout,
                               deterministic=not training)(x)
        x = GraphTransformerLayer(name=f"conv_{num_convs - 1}",
                                  **conv_kwargs)(
            x, edge_embeds, batch.senders, batch.receivers,
            batch.edge_mask, training=training,
            node_mask=batch.node_mask)

        head_init = kernel_initializer(cfg.init_scheme, role="head")
        local_pred = nn.Dense(
            1, name="local_head", dtype=dtype, kernel_init=head_init,
            bias_init=bias_initializer(cfg.init_scheme, x.shape[-1]),
        )(x)[:, 0]

        # mixture pooling: zero pad nodes explicitly so they cannot leak
        weights = jnp.where(batch.node_mask,
                            batch.pattern_prob / batch.pattern_size, 0.0)
        pooled = segment_mean_by_graph(x, batch.node_graph,
                                       weights.astype(dtype), num_graphs)
        entry_emb = embed("entry_embed", self.num_entries)(batch.entry_id)
        g = jnp.concatenate([pooled, entry_emb], axis=1)
        g = nn.relu(nn.Dense(
            hidden, name="global_head1", dtype=dtype,
            kernel_init=head_init,
            bias_init=bias_initializer(cfg.init_scheme, g.shape[-1]))(g))
        # Multi-quantile head (ModelConfig.quantile_taus, lens/): one
        # column per quantile level. Single-tau keeps the exact legacy
        # Dense(1)[:, 0] graph (checkpoints + compiled programs
        # byte-identical); >= 2 taus use the CUMULATIVE-SOFTPLUS
        # parameterization — column 0 is raw, column i adds
        # softplus(raw_i) — so quantile vectors are monotone for ANY
        # parameter values, a structural guarantee rather than a
        # training outcome (non-crossing property, tests/test_lens.py).
        num_taus = len(cfg.quantile_taus)
        raw = nn.Dense(
            num_taus, name="global_head2", dtype=dtype,
            kernel_init=head_init,
            bias_init=bias_initializer(cfg.init_scheme, hidden))(g)
        if num_taus == 1:
            global_pred = raw[:, 0]
        else:
            # explicit accumulation (not jnp.cumsum) so the traced
            # program stays inside graftaudit's modeled primitive set
            cols = [raw[:, 0]]
            for i in range(1, num_taus):
                cols.append(cols[-1] + nn.softplus(raw[:, i]))
            global_pred = jnp.stack(cols, axis=1)
        if cfg.nonnegative_pred:
            # softplus, not relu: a relu clamp kills the gradient whenever
            # the raw prediction is negative (dead at init). Elementwise
            # monotone, so the non-crossing ordering survives the clamp.
            global_pred = nn.softplus(global_pred)
        return global_pred.astype(jnp.float32), local_pred.astype(jnp.float32)


def entry_capacity(num_entries: int, headroom_multiple: int) -> int:
    """The entry-embedding table size for a dataset with `num_entries`
    entries under ModelConfig.vocab_headroom_entries: rounded UP to the
    next multiple so the table size is stable while the live corpus
    grows within the current capacity window (new entries land in
    pre-allocated rows and the checkpoint keeps restoring) — and
    changes LOUDLY (a different model shape) only when growth crosses
    the window. 0 = exact sizing."""
    if headroom_multiple <= 0:
        return num_entries
    return -(-num_entries // headroom_multiple) * headroom_multiple


def make_model(cfg: ModelConfig, num_ms: int, num_entries: int,
               num_interfaces: int, num_rpctypes: int,
               edge_shard_mesh: Any = None) -> PertGNN:
    # THE construction point: fit(), the serve engine, precompile, and
    # graftaudit all come through here, so the entry-capacity headroom
    # cannot apply in one layer and not another
    num_entries = entry_capacity(num_entries, cfg.vocab_headroom_entries)
    return PertGNN(cfg=cfg, num_ms=num_ms, num_entries=num_entries,
                   num_interfaces=num_interfaces, num_rpctypes=num_rpctypes,
                   edge_shard_mesh=edge_shard_mesh)
