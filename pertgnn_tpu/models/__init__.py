from pertgnn_tpu.models.layers import GraphTransformerLayer, MaskedBatchNorm
from pertgnn_tpu.models.pert_model import PertGNN, make_model
