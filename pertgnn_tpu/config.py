"""Configuration for the full pipeline.

Every magic number that lives inline in the reference is surfaced here as a
named field (SURVEY.md §5.6 inventory):

- 30 000 ms trace start-time bucket      (/root/reference/preprocess.py:39)
- 0.6 resource-coverage threshold        (/root/reference/preprocess.py:170)
- 100 min traces per entry               (/root/reference/preprocess.py:180,246)
- 100 000 trace subsample                (/root/reference/pert_gnn.py:299)
- 60/20/20 positional split              (/root/reference/pert_gnn.py:198-200)
- "(?)" entry tie-break token            (/root/reference/preprocess.py:121)
- resource agg set [max,min,mean,median] (/root/reference/preprocess.py:238)
- training defaults (hidden 32, lr 3e-4, tau 0.5, batch 170, 100 epochs,
  num_layers 1, dropout 0)               (/root/reference/pert_gnn.py:15-33)

Deliberate divergences from the reference are opt-in flags documented on each
field and in PARITY.md.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """L0-L2 preprocessing knobs."""

    # Trace start-time bucket (ms) keying resource lookups
    # (reference: preprocess.py:39 `// 30000 * 30000`).
    ts_bucket_ms: int = 30_000
    # Keep traces where >= this fraction of participating microservices have
    # resource features (reference: preprocess.py:170).
    min_resource_coverage: float = 0.6
    # Keep traces whose entry endpoint occurs in MORE than this many traces
    # (strict >, reference: preprocess.py:185 `> min_occurence`).
    min_traces_per_entry: int = 100
    # Entry-row tie-break: among multiple candidates prefer um == this token
    # (reference: preprocess.py:121). Raw-string domain; factorized away later.
    entry_tiebreak_um: str = "(?)"
    # Aggregations applied to per-(timestamp, msname) resource usage columns
    # (reference: preprocess.py:238). 2 columns x 4 aggs = 8 numeric features.
    resource_aggs: Sequence[str] = ("max", "min", "mean", "median")
    # rpctype string that identifies candidate entry rows
    # (reference: preprocess.py:113 `group.rpctype == "http"`).
    entry_rpctype: str = "http"


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """L3 dataset assembly / batching knobs."""

    # Subsample of traces used for training (reference: pert_gnn.py:299).
    max_traces: int = 100_000
    # Positional split fractions (reference: pert_gnn.py:198-200).
    split: Sequence[float] = (0.6, 0.2, 0.2)
    # Graphs per packed batch (reference batch_size: pert_gnn.py:31).
    batch_size: int = 170
    # Packed-batch budgets. `None` -> derived from the dataset (max mixture
    # size * batch_size head-room, rounded up to multiples of 128 for TPU
    # lane alignment). These give every batch ONE static shape -> one compile.
    max_nodes_per_batch: int | None = None
    max_edges_per_batch: int | None = None
    # Head-room factor for derived node/edge budgets over
    # mean-mixture-size * batch_size. 1.1 measured: same batch count as
    # 1.3 at ~0.90 (vs 0.76) padded-slot utilization — see
    # batching/pack.py derive_budget for the sizing law and why quantile
    # bucketing was rejected.
    budget_headroom: float = 1.1
    # Shuffle seed for the train split.
    shuffle_seed: int = 0
    # Persistent arena store (batching/arena_store.py): memory-mapped
    # .npy persistence of the MixtureArena / FeatureArena + pack
    # metadata, keyed by a content hash over the ingest/data/graph
    # config subtree and the raw-input fingerprint. A warm process
    # reconstructs the dataset from mmap and skips ingest + graph
    # construction + featurization entirely — the data-path twin of
    # CompileCacheConfig.cache_dir. Empty = off.
    # TRUST: entries are plain arrays (no pickle), but they ARE the
    # training data — whoever can write this directory controls every
    # later run's features/labels; keep it as private as checkpoints.
    arena_cache_dir: str = ""
    # How cli/common.raw_input_fingerprint keys raw input trees for the
    # arena/delta stores: "stat" (relpath, size, mtime — cheap, but a
    # touch-without-change rebuilds everything) or "content" (relpath,
    # size, sha256 of the bytes — immune to mtime churn from rsync /
    # container image layers / CI checkouts, at the cost of hashing the
    # tree once per process). Switching modes re-keys the store once
    # (the invalidation diagnostics name the fingerprint as the changed
    # ingredient).
    fingerprint_mode: str = "stat"


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model hyper-parameters (reference: pert_gnn.py:15-33, model.py:10-68)."""

    hidden_channels: int = 32
    # NOTE: reference `--num_layers L` builds max(2, L) conv layers
    # (model.py:24-52; default L=1 still builds 2 convs). We keep that exact
    # arithmetic so configs transfer: num_conv_layers = max(2, num_layers).
    num_layers: int = 1
    # Attention heads. Reference hard-codes 1 (model.py:29); >1 generalizes it
    # (BASELINE config 4 uses 8).
    num_heads: int = 1
    dropout: float = 0.0
    # Dropout on attention weights inside the conv (PyG TransformerConv's
    # `dropout` arg; the reference leaves it 0, model.py:26-31).
    attn_dropout: float = 0.0
    # --- capability switches for paths the reference computes but never uses
    # (SURVEY.md §2.3 "declared-but-dead"); all default to reference-live
    # behavior.
    # Feed normalized node depth as an extra input feature (reference stores
    # node_depth in every Data, pert_gnn.py:168, but the model never sees it).
    use_node_depth: bool = False
    # Clamp the global prediction to be non-negative (reference comment
    # model.py:113, unimplemented).
    nonnegative_pred: bool = False
    # Weight of the per-node local head in the loss (reference computes
    # local_pred but never trains on it, pert_gnn.py:245).
    local_loss_weight: float = 0.0
    # Resource features on EVERY stage-copy of a microservice in a PERT
    # graph. The reference's live get_x assigns features only to the LAST
    # stage-copy (pert_gnn.py:56: ms2nid dict comprehension over the
    # duplicated stage list — later copies overwrite earlier ones), leaving
    # the other copies zeros + missing indicator; discovered by executing
    # the reference's own driver (benchmarks/parity/
    # reference_driver_crosscheck.py). False (default) = reference-faithful;
    # True = feature all copies (strictly more information). No-op for span
    # graphs (one node per ms).
    # COMPAT (ADVICE r4): the default flipped True -> False in round 4;
    # pert checkpoints trained before that commit saw all-copies features
    # and should be re-trained, or loaded with
    # --feature_all_stage_copies for input-compatible inference.
    feature_all_stage_copies: bool = False
    # Missing-feature indicator convention. The reference has TWO conventions:
    # train-time get_x uses 1=missing (pert_gnn.py:50,62-66) — that is what
    # the model actually sees; preprocess-time uses 1=present (misc.py:153) —
    # dead output. True = the live get_x convention.
    missing_indicator_is_one: bool = True
    # Use the Pallas fused edge-attention kernel for the conv hot op.
    # DEPRECATED alias: equivalent to attention_impl="pallas"; kept so
    # existing flags/configs keep working (resolve_attention_impl maps
    # it). attention_impl wins when both are set non-default.
    use_pallas_attention: bool = False
    # The conv hot-op implementation (ops/; docs/GUIDE.md "Choosing
    # attention_impl"):
    #   "segment"       — XLA sorted-segment ops (ops/segment.py), the
    #                     reference-parity default; works everywhere.
    #   "pallas"        — flash-style fused fwd/bwd Pallas kernels
    #                     (ops/pallas_attention.py); compiled on TPU,
    #                     interpret mode elsewhere (slow — tests only).
    #   "pallas_fused"  — "pallas" plus the fused per-node EPILOGUE: the
    #                     skip projection + residual (and the masked
    #                     BatchNorm statistics pass in training) run in
    #                     one Pallas pass over node blocks instead of
    #                     round-tripping HBM between the attention
    #                     kernel and the rest of the layer.
    #   "blocked_dense" — the small-graph segment ops recast as MASKED
    #                     DENSE matmuls over (node, edge) blocks
    #                     (ops/blocked_dense.py; arXiv:1906.11786's
    #                     systolic-hardware formulation), gated by
    #                     blocked_dense_max_cells with a logged+counted
    #                     segment fallback above it.
    attention_impl: str = "segment"
    # Pallas kernel tile sizes (node-block x edge-block). 128 matches
    # the MXU lane width; these are BAKED INTO compiled programs, so the
    # AOT store keys cover them (they ride in ModelConfig).
    kernel_block_n: int = 128
    kernel_block_e: int = 128
    # blocked_dense guard: the dense incidence mask is (N_pad x E_pad)
    # CELLS per head — above this the quadratic materialization loses to
    # the segment formulation (and can blow VMEM/HBM), so the layer
    # falls back to "segment" with a logged warning + a
    # model.kernel_fallback counter (never silently).
    blocked_dense_max_cells: int = 1 << 22
    # Feed span edge durations |rt| (log1p-compressed) as an extra edge
    # feature. The reference computes these but never persists or uses them
    # (misc.py:183-186 vs preprocess.py:333-340) — exposed here as the
    # capability option SURVEY.md §2.3 calls for. No-op for pert graphs
    # (durations are zero there; the reference's PERT duration code is
    # commented out, misc.py:259-269).
    use_edge_durations: bool = False
    # Parameter/activation dtype for the MXU. Params stay f32; activations in
    # bf16 when True.
    bf16_activations: bool = False
    # Entry-embedding capacity headroom for the streaming path
    # (pertgnn_tpu/stream/): round the entry-embedding table size UP to
    # the next multiple of this, so a delta shard that introduces a NEW
    # entry (a new dm_interface combination over existing strings) still
    # fits the checkpointed embedding and the continual trainer can
    # warm-restart instead of cold-retraining. 0 (default) = exact
    # sizing, the reference-parity behavior; growth past the rounded
    # capacity is a loud rebuild (stream/merge.py). Changes model
    # shapes, so it rides checkpoints and every AOT key via cfg.model.
    vocab_headroom_entries: int = 0
    # Weight-init scheme. "torch" (default): kaiming-uniform(a=sqrt5) for
    # every Linear kernel — what torch.nn.Linear (and therefore the
    # reference's PyG stack) trains with; measured 98.2+-5.5 train-fit MAE
    # vs 117.0+-13.8 for "flax" (glorot attention / lecun-normal heads) on
    # the 6-seed 20-epoch synthetic A/B — the flax defaults were the source
    # of the round-2/3 quality-parity gap (RESULTS.md). "torch_full" adds
    # torch's U(+-1/sqrt(fan_in)) BIAS init on top (flax biases are zeros)
    # — the remaining init difference, A/B'd for the span 20-epoch gap
    # (benchmarks/span_gap_r4.py).
    init_scheme: str = "torch"
    # Multi-quantile global head (pertgnn_tpu/lens/ — distributional
    # serving): one output column per quantile level, e.g.
    # (0.5, 0.95, 0.99) predicts p50/p95/p99 latency in ONE forward.
    # Non-crossing BY CONSTRUCTION: column 0 is the raw head output,
    # every later column adds a softplus increment (cumulative-softplus
    # parameterization in models/pert_model.py), so served quantile
    # vectors are monotone for ANY parameter values — a property test,
    # not a training outcome. The default (0.5,) is the LEGACY
    # single-tau mode: the head keeps its exact pre-lens shape (Dense(1)
    # — checkpoints and compiled programs byte-identical) and the
    # training quantile stays TrainConfig.tau (the reference's --tau
    # flag); resolve_quantile_taus is the ONE resolution point. With
    # >= 2 taus the loss sums one pinball term per (tau, column) and
    # metrics report the PRIMARY column (tau closest to train.tau).
    # Changes model shapes, so it rides checkpoints and every AOT key
    # via cfg.model.
    quantile_taus: Sequence[float] = (0.5,)


ATTENTION_IMPLS = ("segment", "pallas", "pallas_fused", "blocked_dense")
SERVE_DTYPES = ("f32", "bf16", "int8")


def resolve_attention_impl(model: "ModelConfig") -> str:
    """The effective conv hot-op implementation: a non-default
    `attention_impl` wins; the deprecated `use_pallas_attention` bool
    maps to "pallas" when attention_impl is left at "segment". NOTE: an
    explicit "segment" is indistinguishable from the default, so it
    cannot override the legacy bool — to get the segment path, drop
    `use_pallas_attention` (it is deprecated; that is the migration).
    The ONE resolution point — models, benches, and AOT keys all go
    through it so a legacy flag cannot mean different impls in
    different layers."""
    if model.attention_impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attention_impl {model.attention_impl!r} "
            f"(choose from {ATTENTION_IMPLS})")
    if model.attention_impl != "segment":
        return model.attention_impl
    return "pallas" if model.use_pallas_attention else "segment"


def resolve_quantile_taus(model: "ModelConfig",
                          train_tau: float) -> tuple[float, ...]:
    """The effective quantile levels of the global head — the ONE
    resolution point (models, the train loop, serving, and the lens
    benches all go through it, so legacy and multi-quantile configs
    cannot mean different losses in different layers).

    The default ``quantile_taus=(0.5,)`` is the LEGACY single-tau mode:
    the quantile level is ``TrainConfig.tau`` (the reference's ``--tau``
    flag), exactly as before the lens subsystem existed — byte-identical
    programs for every pre-lens config, including non-default ``--tau``.
    Any OTHER setting wins over train.tau and must be strictly ascending
    in (0, 1)."""
    taus = tuple(float(t) for t in model.quantile_taus)
    if not taus:
        raise ValueError("quantile_taus must name at least one level")
    if taus == (0.5,):
        return (float(train_tau),)
    for t in taus:
        if not 0.0 < t < 1.0:
            raise ValueError(
                f"quantile_taus entries must lie in (0, 1); got {t}")
    if any(b <= a for a, b in zip(taus, taus[1:])):
        raise ValueError(
            f"quantile_taus must be strictly ascending (the non-crossing "
            f"head assigns column i the i-th level); got {taus}")
    return taus


def primary_tau_index(taus: Sequence[float], train_tau: float) -> int:
    """The column whose quantile level is closest to TrainConfig.tau —
    what single-number metrics (mae/mape/qloss history rows, the serve
    quality gates) report in multi-quantile mode, and the level the
    auxiliary local-head loss trains at (attribution ranks the local
    head, so it should be trained at the quantile callers ask about)."""
    return min(range(len(taus)), key=lambda i: abs(taus[i] - train_tau))


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Optimizer / loop knobs (reference: pert_gnn.py:15-33, 343)."""

    lr: float = 3e-4
    # Pinball-loss quantile level (reference: pert_gnn.py:24-28).
    tau: float = 0.5
    # Labels are divided by this inside the loss (the head learns in scaled
    # space); metrics are always reported in raw label units. The reference
    # regresses raw millisecond latencies (pert_gnn.py:245), which is a big
    # part of why it needs 100 epochs — 1.0 keeps that behavior.
    label_scale: float = 1.0
    epochs: int = 100
    # Steps between metric log lines.
    log_every: int = 50
    # Orbax checkpoint cadence (steps); 0 disables.
    checkpoint_every: int = 500
    checkpoint_dir: str = "checkpoints"
    # Keep at most this many checkpoints.
    checkpoint_keep: int = 3
    seed: int = 0
    # Steps fused into one dispatched program via lax.scan (single-chip
    # path). Per-step dispatch latency dominates this workload's step time
    # (~300us dispatch vs ~60us compute measured on one chip — 6x), so the
    # loop stacks `scan_chunk` same-shape batches on host, transfers them
    # in one copy, and scans. The tail chunk is padded with zero-mask
    # batches whose optimizer update is skipped (lax.cond), preserving the
    # reference's step-count semantics. <= 1 disables.
    scan_chunk: int = 16
    # Device-side batch materialization: keep topology/feature arenas
    # chip-resident and feed the step small int32 gather recipes
    # (batching/materialize.py) instead of full packed batches. Removes the
    # host gather/pack from the epoch critical path entirely; the host's
    # only per-epoch work is index arithmetic.
    device_materialize: bool = True
    # HBM budget (GiB) for the chip-resident arenas. The feature arena grows
    # with the number of unique (entry, ts_bucket) pairs and is NOT bounded
    # by the batch shape; if the arenas would exceed this budget, fit()
    # falls back to host-packed streaming with a warning instead of OOMing
    # the chip. None = no limit.
    arena_hbm_budget_gb: float | None = 4.0
    # Stage each epoch's CompactBatch recipes on device in ONE transfer
    # per field (then slice per scan-chunk on device) instead of one H2D
    # per chunk. An epoch of recipes is O(graphs) int32s (~1.6 MB at 98k
    # graphs) but per-chunk puts pay the link's per-transfer latency
    # (~3.5 ms over the axon tunnel) once per field per chunk — measured
    # as the main fit-vs-ceiling gap on chip (VERDICT r3). Applies to the
    # compact paths: single-device, and single-process mesh (sharded
    # staging with the epoch axis replicated); multi-host keeps per-chunk
    # assembly because each host owns only its slab.
    # Tri-state: None = AUTO — staged on accelerator backends, DISABLED
    # on the CPU backend where whole-epoch staging measured SLOWER than
    # streaming (staged_over_unstaged 0.956, BENCH_r05: there is no
    # transfer latency to amortize, only an extra epoch-sized copy).
    # True/False (CLI --staged_epochs on|off) force it either way; the
    # resolved decision is logged and counted (train.staging_decision).
    stage_epoch_recipes: bool | None = None
    # Depth of the bounded double-buffered prefetch
    # (batching/prefetch.py) used where the input path streams per-chunk
    # — today the over-cap staging fallback: the host packs + device_puts
    # chunk i+1 on a background thread while the device computes chunk i.
    # 0 = fully synchronous per-chunk transfers (the A/B control
    # benchmarks/pipeline_bench.py measures against).
    prefetch_depth: int = 2
    # Cap (MiB) on the host bytes staged per epoch by stage_epoch_recipes;
    # past it fit() falls back to per-chunk transfers so staging can never
    # blow HBM outside the arena budget accounting (ADVICE r4). Recipes
    # are O(graphs) int32s, so the default never binds in practice.
    stage_recipes_max_mb: float = 256.0


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online inference engine knobs (pertgnn_tpu/serve/).

    The request path applies the training packer's static-shape discipline
    to latency-sensitive serving: a small geometric ladder of bucket
    shapes, each AOT-compiled once at warmup, with every request padded up
    to the smallest fitting bucket so steady-state serving never
    recompiles (serve/buckets.py, serve/engine.py)."""

    # Geometric growth factor of the bucket ladder's node/edge capacities
    # (2.0 = powers-of-two rungs up to the dataset-derived budget).
    bucket_growth: float = 2.0
    # Smallest rung's node/edge capacity (rounded up to multiples of 128
    # for TPU lane alignment, like the training budget).
    min_bucket_nodes: int = 128
    min_bucket_edges: int = 128
    # Graph slots per serving microbatch (every rung shares this graph
    # capacity — the per-graph arrays are O(G) and cost nothing to pad).
    max_graphs_per_batch: int = 16
    # Microbatch queue: a request waits at most this long for co-arriving
    # requests before its batch is flushed to the engine (serve/queue.py).
    flush_deadline_ms: float = 2.0
    # AOT-compile every ladder rung at engine construction so the first
    # request of each shape pays dispatch, not compilation.
    warmup: bool = True
    # --- fault tolerance (serve/queue.py, docs/RELIABILITY.md) ---
    # Admission control: max requests queued awaiting dispatch; submit
    # past it fast-fails with QueueFull (counter serve.shed) instead of
    # growing the pending set without bound under overload.
    max_pending: int = 1024
    # Per-request deadline: a request not DISPATCHED within this many ms
    # of submission resolves with DeadlineExceeded instead of waiting
    # forever (counter serve.deadline_exceeded). 0 = no deadline.
    request_deadline_ms: float = 0.0
    # Dispatch watchdog: an engine call exceeding this many seconds is
    # abandoned (the wedged-device signature raises nothing, ever), the
    # engine is marked unhealthy, and ONE rebuild-from-AOT-store
    # recovery is attempted before a fail-fast cooldown (counters
    # serve.watchdog_trip / serve.recovered). 0 = no watchdog: engine
    # calls run inline on the queue worker (zero thread-hop overhead,
    # but a wedge hangs the worker and every future behind it).
    dispatch_timeout_s: float = 60.0
    # A request (entry_id) isolated as the poisoner of this many
    # microbatches (bisect-retry, serve/queue.py) is rejected at submit
    # with RequestQuarantined (counter serve.quarantined).
    quarantine_threshold: int = 3
    # Quantized serve tier (docs/GUIDE.md "Choosing serve_dtype"):
    #   "f32"  — serve with the training dtype (default; bit-identical
    #            to offline predict).
    #   "bf16" — bf16 activations through the MXU (params stay f32);
    #            halves activation HBM traffic.
    #   "int8" — bf16 activations + per-output-channel symmetric int8
    #            WEIGHT quantization (ops/quantize.py), dequantized
    #            in-graph: weight HBM traffic drops 4x, matmuls run
    #            bf16 on dequantized operands.
    # Quality is exit-code-gated: benchmarks/serve_bench.py asserts the
    # quantile-loss delta vs the f32 engine stays inside the
    # pre-registered per-dtype threshold. The serve engine's AOT rung
    # keys cover this knob (a dtype change invalidates executables).
    serve_dtype: str = "f32"
    # Overlapped dispatch (serve/queue.py): the queue worker packs the
    # NEXT microbatch on the host while the device computes the current
    # one (one batch in flight; result resolution deferred to a
    # completion step). Every fault-tolerance invariant above holds
    # unchanged — the fault hooks fire at the same sites, a failed
    # completion bisects exactly like a failed synchronous dispatch
    # (benchmarks/pipeline_bench.py re-runs the chaos scenarios under
    # overlap). False = dispatch-and-wait (the pre-overlap behavior,
    # the bench's throughput control).
    overlap_dispatch: bool = True


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Replicated serve fleet knobs (pertgnn_tpu/fleet/).

    One front-door ROUTER process owns the client-facing request queue
    and dispatches microbatches to N serve WORKERS (cli/fleet_main.py
    spawns them; each is a full PR-4-hardened engine+queue stack behind
    an HTTP transport). Dispatch is deadline-aware least-loaded: the
    router tracks per-worker in-flight depth and recent batch latency,
    routes each microbatch to the worker with the earliest predicted
    completion (fleet/policy.py — a pure function, unit-tested without
    subprocesses), sheds at the door when no worker could meet a
    request's deadline, and drives membership from the workers'
    /healthz readiness probes. Every PR-4 invariant holds fleet-wide: a
    submitted Future ALWAYS resolves, and a lost worker's undispatched
    work is requeued to the survivors (surviving predictions stay
    bit-identical to a single-engine reference —
    benchmarks/fleet_bench.py exit-code-asserts it)."""

    # Serve workers the launcher spawns (one engine per worker; on a
    # multi-device host, one worker per device).
    num_workers: int = 2
    # First worker HTTP port; worker i listens on base+i. 0 = the
    # launcher picks free ephemeral ports.
    worker_base_port: int = 0
    # Router-side microbatch coalescing window (the fleet twin of
    # ServeConfig.flush_deadline_ms): a request waits at most this long
    # for co-arriving requests before its microbatch is dispatched.
    router_flush_deadline_ms: float = 2.0
    # Router admission control: max requests queued at the front door;
    # submit past it fast-fails with QueueFull (counter router.shed).
    max_pending: int = 4096
    # Door deadline (ms): a request whose deadline no worker's
    # predicted completion can meet is shed AT SUBMIT with
    # DeadlineExceeded (counter router.shed_infeasible), and a queued
    # request expires if still undispatched past it. 0 = no deadlines.
    request_deadline_ms: float = 0.0
    # Per-dispatch HTTP timeout (seconds): a worker call exceeding it
    # counts as a lost worker — its batch requeues to the survivors.
    dispatch_timeout_s: float = 60.0
    # Outstanding microbatches per worker before the router stops
    # assigning it more (keeps each worker's overlap pipeline full
    # without queue-stacking behind a slow one).
    worker_slots: int = 2
    # Readiness-probe poll cadence (seconds) driving membership.
    health_poll_interval_s: float = 1.0
    # Consecutive failed probes before a member is excluded (a single
    # in-flight transport failure excludes immediately — the probe
    # threshold only governs the polling path, so one dropped probe
    # packet cannot flap an otherwise healthy worker).
    probe_lost_after: int = 2
    # EWMA smoothing for the per-worker batch-latency estimate feeding
    # predicted completion (higher = reacts faster to load shifts).
    latency_ewma_alpha: float = 0.3
    # Times a single request may be requeued (worker loss / drain)
    # before the router gives up and fails it with the last error —
    # bounds the worst case where every dispatch lands on a dying
    # worker; requeue-on-loss is otherwise invisible to the caller.
    max_requeues: int = 3
    # --- hedged dispatch (fleet/router.py; docs/RELIABILITY.md) ---
    # Fixed hedge threshold (ms): a dispatched microbatch still running
    # past it is RE-DISPATCHED to a second worker; first answer wins,
    # the loser is ignored (predictions are deterministic, so hedging
    # is bit-safe). > 0 enables hedging with this explicit threshold;
    # 0 defers to hedge_quantile.
    hedge_quantile_ms: float = 0.0
    # Adaptive hedge threshold: the rolling q-quantile of recent batch
    # round-trip times (policy.hedge_threshold_s; needs a minimum
    # sample count before it arms). In (0, 1) enables adaptive hedging
    # when hedge_quantile_ms is 0; both 0 = hedging off.
    hedge_quantile: float = 0.0
    # --- SLO brownout (fleet/shield.py) ---
    # Pending-occupancy ratio (pending / max_pending) at which the
    # router enters BROWNOUT: best-effort traffic is downgraded to the
    # workers' cheapest ladder rung before anything is shed. <= 0
    # disables the mode (class-aware shedding still applies at a full
    # pending set).
    brownout_enter_ratio: float = 0.0
    # Occupancy below which brownout exits (hysteresis); <= 0 = half
    # the enter ratio.
    brownout_exit_ratio: float = 0.0
    # --- elastic warm spares (fleet/autoscale.py) ---
    # Max spare workers the autoscale controller may spawn (warm from
    # the shared AOT/arena stores) on top of num_workers; 0 = off.
    autoscale_max_spares: int = 0
    # router.queue_wait (ms) above which a spare is spawned once the
    # signal has held for autoscale_hold_s.
    autoscale_up_ms: float = 50.0
    # router.queue_wait (ms) below which the newest spare retires after
    # autoscale_cooldown_s of sustained calm.
    autoscale_down_ms: float = 10.0
    # Seconds the up-signal must hold before spawning (no scale-up off
    # one noisy batch).
    autoscale_hold_s: float = 0.5
    # Seconds the down-signal must hold before a spare retires (spares
    # are cheap to keep and expensive to thrash).
    autoscale_cooldown_s: float = 10.0
    # --- graftwire data plane (fleet/wire.py, fleet/shmring.py) ---
    # Router->worker wire: "json" (the legacy JSON-over-HTTP wire,
    # byte-identical default), "binary" (the versioned graftwire frame
    # codec over pooled HTTP — bit-identity is structural, raw IEEE-754
    # on the wire), or "shm" (binary frames over same-host shared-
    # memory SPSC rings with an eventfd-style doorbell; negotiated at
    # probe time, degrading LOUDLY to HTTP — counter transport.fallback
    # — for version-skewed or cross-host workers). docs/GUIDE.md §14.
    transport: str = "json"
    # Slots per shm ring direction (per worker). The router's serial
    # per-sender call protocol needs only a few; extra slots absorb
    # abandoned-deadline responses without stalling the service thread.
    shm_ring_slots: int = 8
    # Slot payload budget (bytes) per ring slot. A frame larger than
    # one slot falls back to HTTP for that call (transport.fallback
    # reason=oversize); size it to the largest microbatch frame —
    # request frames are ~16B/request, response frames ~8B/request
    # plus lens attribution JSON.
    shm_slot_bytes: int = 65536
    # --- graftmemo read-mostly path (fleet/memo.py) ---
    # Byte budget for the router's content-keyed prediction cache —
    # LRU over wire-encoded rows, generation-tagged so a blue/green
    # rollout retires every cached byte atomically (docs/GUIDE.md §17).
    # 0 (the default) disables the memo entirely: every submit rides
    # the wire, byte-identical to the pre-memo router.
    memo_capacity_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Streaming ingest + continual training knobs (pertgnn_tpu/stream/).

    The live-traffic subsystem: new trace shards ingest, featurize, and
    persist INDEPENDENTLY into an append-only delta arena store keyed on
    each shard's own fingerprint (stream/store.py), a mixture-merge
    reconstitutes the serving/training corpus from base + deltas without
    a full rebuild (stream/merge.py, bit-identical to a from-scratch
    rebuild — benchmarks/stream_bench.py exit-code-asserts it), and a
    sliding window of recent shards drives warm-restart fine-tuning from
    the latest checkpoint (stream/continual.py). Paired with the
    blue/green fleet rollout controller (fleet/rollout.py)."""

    # Root directory of the append-only delta arena store. Empty = the
    # streaming path is off. TRUST: same boundary as arena_cache_dir —
    # entries are plain arrays + JSON, but they ARE the training data.
    delta_store_dir: str = ""
    # Sliding fine-tune window: warm-restart training sees the examples
    # of the LAST this-many shards (the base corpus counts as shard 0);
    # <= 0 = every shard (full-corpus fine-tune).
    window_shards: int = 4
    # Epochs per continual fine-tune round (short on purpose: the point
    # is a fresh checkpoint in seconds-to-minutes, not convergence).
    finetune_epochs: int = 2


@dataclasses.dataclass(frozen=True)
class ScaleConfig:
    """Giant-corpus scale-out knobs (pertgnn_tpu/parallel/scale.py).

    Two independent axes for the regime where one host's arena or one
    device's HBM no longer holds the corpus (ROADMAP item 2):

    - per-host SHARDED delta arenas: delta shards are assigned to hosts
      deterministically in content-key order, each host mmaps only its
      slice of the stream store (stream/store.py ``open_shards``), and
      the mixture/vocab statistics merge via collectives over the
      existing mesh — bit-identical to the single-host
      stream/merge.py oracle (benchmarks/scale_bench.py asserts it);
    - SAR-style REMATERIALIZED training (arXiv:2111.06483): one
      optimizer step sequentially aggregates over topology buckets with
      per-bucket rematerialization, so mixtures larger than one
      device's memory train at bounded peak HBM with gradients
      bit-identical to the aggregation-held (monolithic) step."""

    # Number of logical hosts the delta shard set is partitioned over.
    # 1 (default) = the single-host merge path, byte-for-byte the
    # pre-scale behavior. Must not exceed the mesh's data-axis size
    # when the collective merge runs.
    scale_hosts: int = 1
    # Topology-bucket CAPACITY of the SAR accumulated train step: one
    # compiled program scans over this many bucket slots (short
    # mixtures ride zero-masked slots skipped under lax.cond, so the
    # live bucket count varies with ZERO fresh compiles). <= 1 = the
    # monolithic per-batch step, exactly as before. A mixture needing
    # more buckets than this refuses loudly (scale.accum_overflow)
    # instead of silently truncating.
    accum_buckets: int = 1


@dataclasses.dataclass(frozen=True)
class LensConfig:
    """Distributional / explainable what-if serving knobs
    (pertgnn_tpu/lens/ — docs/GUIDE.md §13).

    Three request variants ride the EXISTING pack/dispatch/hedge/trace
    machinery (serve/queue.py ``submit(lens=...)``, fleet/router.py,
    the transport body — fields omitted when default, like SLO classes):
    multi-quantile predictions (``ModelConfig.quantile_taus``),
    root-cause attribution (top-k per-node local predictions mapped
    back to (ms, interface) calls — lens/attribute.py), and
    counterfactual topology queries (pure call-graph edits re-packed
    through the existing bucket ladder — lens/whatif.py, zero fresh
    compiles by construction since rungs key on shape)."""

    # Warm + serve the local-pred-returning (attribution) rung programs
    # next to the standard ladder. Off (default) = attribution requests
    # are refused at submit with the typed LensDisabled — the engine
    # NEVER compiles a program variant on the request path. The local
    # variant is a distinct compiled program per rung (pad node rows
    # masked to -inf in-graph so top-k can never rank them — verified
    # by graftaudit's padding-taint pass on the traced programs).
    lens_local: bool = False
    # Cap on per-request top-k attribution rows (a request asking for
    # more is clamped, never refused — k is a presentation knob).
    lens_top_k: int = 8


@dataclasses.dataclass(frozen=True)
class CompileCacheConfig:
    """Cold-start elimination knobs (pertgnn_tpu/aot/).

    Every hot executable in this repo is resumable from disk: JAX's
    persistent compilation cache replays train/eval chunk programs, and
    the serve ladder's per-rung executables are serialized with a
    content-hash key (aot/store.py). A process that compiled yesterday
    makes today's first step execute-only — the mechanism that turns a
    sub-minute TPU window from useless (wedged inside first-step
    compilation) into sufficient (docs/GUIDE.md "Precompile workflow")."""

    # Root directory for persisted compilation artifacts: `xla/` holds
    # JAX's persistent compilation cache (every jit compile, keyed by
    # XLA over the HLO + backend), `exe/` the serialized serve-rung
    # executables. Empty = disabled (every process cold-starts).
    # TRUST: store entries are unpickled at load — whoever can write
    # this directory can execute code in every process that reads it;
    # keep it as private as your checkpoints (aot/store.py docstring).
    cache_dir: str = ""
    # Only persist XLA cache entries whose compile took at least this
    # long (seconds). 0 caches everything — right for this workload,
    # whose many small programs are exactly what cold start re-pays.
    min_compile_time_s: float = 0.0
    # Serialize serve-ladder executables into `exe/` at warmup so a
    # later process's warmup deserializes instead of compiling. Off =
    # persistent XLA cache only.
    serialize_executables: bool = True

    @property
    def enabled(self) -> bool:
        return bool(self.cache_dir)


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Unified telemetry bus knobs (pertgnn_tpu/telemetry/).

    The bus is a no-op unless `telemetry_dir` is set AND
    `telemetry_level` != "off"; the no-op costs nanoseconds per call
    site (benchmarks/telemetry_overhead.py), so instrumentation is
    always compiled in. Schema + workflow: docs/OBSERVABILITY.md."""

    # Directory for the append-only JSONL event stream (one
    # pid/process-index-stamped file per process). Empty = disabled.
    telemetry_dir: str = ""
    # Verbosity: "off" | "basic" (run/epoch granularity) | "trace"
    # (adds per-chunk and per-request events).
    telemetry_level: str = "basic"
    # Mirror scalar events to a TensorBoard sink under telemetry_dir/tb
    # (requires tensorboardX; silently JSONL-only without it).
    tensorboard: bool = False
    # Distributed request tracing (telemetry/tracing.py, effective at
    # "trace" level only): head-sampling probability per request. The
    # 0.1 default keeps tracing within the telemetry_overhead.py 1%
    # budget; benches asserting trace completeness run at 1.0.
    trace_sample_rate: float = 0.1
    # Always-keep override: an UNSAMPLED request whose total latency
    # crosses this many ms flushes its buffered spans anyway (tagged
    # sampled="slow") — tail exemplars survive low sample rates.
    # <= 0 disables the override.
    trace_slow_ms: float = 250.0
    # Size-based JSONL rotation: when the current telemetry file
    # exceeds this many MiB the writer switches to a .partN.jsonl
    # sibling (tools/graftscope and every glob-the-dir reader see all
    # parts). 0 (default) = one unbounded file.
    telemetry_rotate_mb: float = 0.0


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Mesh / sharding layout.

    The reference is single-device (pert_gnn.py:36-37); distribution here is
    first-class: a (data, model) mesh, batch sharded over `data` with psum
    gradient all-reduce over ICI, hidden dims optionally sharded over `model`.
    """

    data_axis: str = "data"
    model_axis: str = "model"
    # -1 = all available devices on the data axis.
    data_parallel: int = -1
    model_parallel: int = 1
    # Shard edges of one giant graph across `data` for the 5k-node stress
    # path (BASELINE config 5).
    shard_edges: bool = False


@dataclasses.dataclass(frozen=True)
class Config:
    ingest: IngestConfig = IngestConfig()
    data: DataConfig = DataConfig()
    model: ModelConfig = ModelConfig()
    train: TrainConfig = TrainConfig()
    parallel: ParallelConfig = ParallelConfig()
    serve: ServeConfig = ServeConfig()
    fleet: FleetConfig = FleetConfig()
    stream: StreamConfig = StreamConfig()
    scale: ScaleConfig = ScaleConfig()
    lens: LensConfig = LensConfig()
    telemetry: TelemetryConfig = TelemetryConfig()
    aot: CompileCacheConfig = CompileCacheConfig()
    # span | pert (reference: pert_gnn.py:32).
    graph_type: str = "span"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def default_config() -> Config:
    return Config()
