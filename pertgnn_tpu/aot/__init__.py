"""Cold-start elimination: persistent compile cache + serialized AOT
executables (ISSUE 3).

BENCH_r05 put ``fit()`` at 1.01x of the measured step ceiling —
steady-state throughput is no longer the bottleneck; COLD START is: the
round's only TPU window (<1 min) wedged inside first-step compilation
before a single measurement persisted, and every serve process start
re-paid a full ``lower().compile()`` per ladder rung. This package makes
every hot executable resumable from disk, two mechanisms deep:

1. **Persistent compilation cache** (`enable_compile_cache`): JAX's
   ``jax_compilation_cache_dir`` pointed at ``<cache_dir>/xla``. Every
   jit compile — the scan-fused train/eval chunk programs (including
   donated-buffer programs ``jax.export`` cannot carry), model init, the
   packed ceiling twins — is written to disk keyed by XLA over (HLO,
   compile options, backend) and replayed by any later process.
2. **Serialized serve executables** (`aot/store.py`): the serve ladder's
   per-rung executables persisted under a content-hash key over
   (jax/jaxlib version, device kind, mesh, Config subtree, function
   identity, abstract signature — `aot/keys.py`), with loud invalidation
   on any mismatch and corrupt-entry fallback to fresh compilation.

The host-only **precompile stage** (`aot/precompile.py`, surfaced as
``bench.py --precompile`` and ``serve_main --precompile_only``)
populates both before a TPU window opens, so the in-window first step is
execute-only. Workflow: docs/GUIDE.md "Precompile workflow"; metrics:
docs/OBSERVABILITY.md ``aot.*``.
"""

from __future__ import annotations

import logging
import os

from pertgnn_tpu.aot.keys import (abstract_signature, cache_key,
                                  environment_fingerprint)
from pertgnn_tpu.aot.store import ExecutableStore, diff_components
from pertgnn_tpu.config import CompileCacheConfig
from pertgnn_tpu.telemetry.jaxmon import watch_xla_cache

__all__ = [
    "CompileCacheConfig", "ExecutableStore", "abstract_signature",
    "cache_key", "diff_components", "enable_compile_cache",
    "environment_fingerprint", "store_from_config", "watch_xla_cache",
]

log = logging.getLogger(__name__)


def enable_compile_cache(cfg: CompileCacheConfig) -> str | None:
    """Point JAX's persistent compilation cache at ``<cache_dir>/xla``.

    Returns the cache directory actually enabled, or None when the
    config disables it. Call BEFORE the first compile (the CLIs do,
    right after apply_platform_env); calling again with the same config
    is a no-op, with a different dir redirects future compiles.

    The min-entry-size floor is dropped to \"cache everything\": this
    workload's cold start is the SUM of many small programs (eager init
    ops, chunk programs, per-rung serve executables), so the default
    floor would exempt exactly the entries we need."""
    if not cfg.enabled:
        return None
    xla_dir = os.path.abspath(os.path.join(cfg.cache_dir, "xla"))
    os.makedirs(xla_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", xla_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(cfg.min_compile_time_s))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    log.info("persistent compilation cache enabled at %s "
             "(min_compile_time_s=%g)", xla_dir, cfg.min_compile_time_s)
    return xla_dir


def store_from_config(cfg, bus=None) -> ExecutableStore | None:
    """The serialized-executable store for a Config (or a bare
    CompileCacheConfig), or None when disabled. Also enables the
    persistent compilation cache — the store's stablehlo format replays
    through it, so the two are only ever on together."""
    aot_cfg = getattr(cfg, "aot", cfg)
    enable_compile_cache(aot_cfg)  # cache-only mode still wants XLA on
    if not aot_cfg.enabled or not aot_cfg.serialize_executables:
        return None
    return ExecutableStore(
        os.path.abspath(os.path.join(aot_cfg.cache_dir, "exe")), bus=bus)
