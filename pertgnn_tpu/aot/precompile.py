"""Host-driven precompile: populate the compile caches BEFORE the run
that needs them.

``fit()``'s first step pays model init + the scan-fused chunk compile;
a serve process start pays one compile per ladder rung. This stage
builds the SAME programs those paths run — through
``train.loop.build_single_device_programs``, the construction fit()
itself uses, so the persisted artifacts match by code identity — and
exits. The next process's "compiles" are then disk replays: the fused
init and any non-exportable program through JAX's persistent
compilation cache, the train/eval chunk programs and serve rungs
through the serialized-executable store (aot/store.py).
``tpu_watch.sh`` runs this the moment the tunnel answers, before arming
a capture window, so the in-window first step is execute-only (the
<1 min windows this environment grants no longer die inside XLA).

Entry points: ``bench.py --precompile`` (train + ceiling programs over
the bench workload) and ``serve_main --precompile_only`` (the serve
ladder via the engine's own warmup). Mirrors fit()'s SINGLE-PROCESS
program selection; mesh runs are skipped with a warning (their programs
shard over the live mesh — precompile them by running the same command
shape on the same slice).
"""

from __future__ import annotations

import logging
import time

import jax

from pertgnn_tpu import telemetry
from pertgnn_tpu.telemetry.devmem import sample_device_memory
from pertgnn_tpu.aot import enable_compile_cache
from pertgnn_tpu.config import Config

log = logging.getLogger(__name__)


def precompile_train(dataset, cfg: Config, *, include_packed: bool = False,
                     mesh=None, bus=None) -> dict:
    """Build (= compile + persist) every program fit() will run on this
    dataset/config; returns a JSON-ready stats dict. ``include_packed``
    additionally primes the packed chunk program even when the compact
    path is active (bench.py's replay ceilings run both)."""
    if bus is None:
        bus = telemetry.get_bus()
    if mesh is not None:
        log.warning("precompile_train skips mesh configs: SPMD programs "
                    "compile against the live mesh — run the real "
                    "command on the same slice to prime them")
        return {"programs": [], "skipped": "mesh"}
    if not cfg.aot.enabled:
        raise ValueError(
            "precompile needs CompileCacheConfig.cache_dir set "
            "(--compile_cache_dir) — without it the compiled programs "
            "die with this process")
    enable_compile_cache(cfg.aot)

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_resolve_device_materialize,
                                        _train_eval_abstract, _train_sample,
                                        build_single_device_programs,
                                        make_train_chunk, make_train_step,
                                        make_tx)

    stats: list[dict] = []
    t_all = time.perf_counter()
    with telemetry.watch_xla_cache() as cache:
        model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                           dataset.num_interfaces, dataset.num_rpctypes)
        tx = make_tx(cfg)
        sample = _train_sample(dataset)
        compact = _resolve_device_materialize(dataset, cfg)

        t0 = time.perf_counter()
        with bus.span("aot.compile", program="fit_programs"):
            state, train_step, eval_step = build_single_device_programs(
                dataset, cfg, model=model, tx=tx, sample=sample,
                device_materialize=compact, bus=bus)
        stats.append({"name": "init+fit_programs",
                      "seconds": round(time.perf_counter() - t0, 3)})

        # store-less mode returns lazily-jitted programs — force their
        # compiles into the persistent cache now, that is the job
        abs_args = None
        for name, step in (("train", train_step), ("eval", eval_step)):
            if not hasattr(step, "lower"):
                continue  # already an AOT-compiled executable
            if abs_args is None:
                abs_args = _train_eval_abstract(dataset, cfg, state,
                                                compact)
            t0 = time.perf_counter()
            with bus.span("aot.compile", program=name):
                step.lower(*abs_args).compile()
            dt = time.perf_counter() - t0
            bus.histogram("aot.compile_seconds", dt, program=name)
            stats.append({"name": name, "seconds": round(dt, 3)})

        if include_packed and compact:
            # bench.py's packed replay ceiling compiles the packed chunk
            # program in its ORIGINAL jit form — prime exactly that
            pabs = _train_eval_abstract(dataset, cfg, state, False)
            packed = (make_train_chunk(model, cfg, tx)
                      if cfg.train.scan_chunk > 1
                      else make_train_step(model, cfg, tx))
            t0 = time.perf_counter()
            with bus.span("aot.compile", program="train_packed_ceiling"):
                packed.lower(*pabs).compile()
            dt = time.perf_counter() - t0
            bus.histogram("aot.compile_seconds", dt,
                          program="train_packed_ceiling")
            stats.append({"name": "train_packed_ceiling",
                          "seconds": round(dt, 3)})
        for row in stats:
            log.info("precompiled %s in %.2fs", row["name"],
                     row["seconds"])
    dev = jax.devices()[0]
    return {
        "backend": dev.platform,
        "device_kind": getattr(dev, "device_kind", "") or "",
        "cache_dir": cfg.aot.cache_dir or None,
        "programs": stats,
        "total_seconds": round(time.perf_counter() - t_all, 3),
        # hits mean a previous stage (or run) already paid these
        # compiles; misses are the fresh ones this stage just persisted
        "xla_cache_hits": cache["hits"],
        "xla_cache_misses": cache["misses"],
        # post-compile allocator state (ISSUE 17): what the primed
        # programs cost in device memory before any capture window
        # opens; None on backends without memory_stats (CPU)
        "device_mem": sample_device_memory(bus, where="precompile",
                                           device=dev),
    }
