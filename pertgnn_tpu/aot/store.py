"""On-disk store of serialized AOT executables.

The serve engine compiles one executable per ladder rung at warmup and
``fit()`` compiles its train/eval chunk programs at first step; on every
process start those used to re-pay a full ``lower().compile()`` each.
The store makes them resumable. Two formats, picked by a one-time probe
of what the backend's PJRT client supports:

- ``pjrt`` — the compiled executable itself
  (jax.experimental.serialize_executable): load is a pure
  deserialization, no XLA involved, and every compile-time property
  (donated buffers included) survives byte-for-byte. TPU/GPU backends.
- ``stablehlo`` — XLA:CPU executables do not survive the pjrt
  round-trip (unresolved JIT symbols), so the fallback persists the
  ``jax.export`` StableHLO artifact. ``load_or_build`` then makes the
  REPLAYED form (``jit(deserialize(blob).call)``) the live executable on
  BOTH the cold and the warm path: cold pays exactly one backend
  compile (of the replay form, which lands in the persistent
  compilation cache), warm re-lowers the deserialized artifact and hits
  that cache entry — no model re-trace, no fresh XLA compile, and no
  double-compile on the cold path. Replay output is bit-identical to
  the original program (pinned by tests/test_aot.py).

A miss with OTHER keys present under the same logical name diffs the
persisted key components and logs loudly WHICH ingredient changed (jax
upgrade, device kind, config field, signature) — silent permanent
recompiles are the failure mode this kills. A corrupt or truncated
entry logs a warning and falls back to fresh compilation (never crashes
the caller); the fresh save overwrites it.

Telemetry: ``aot.cache_hit`` / ``aot.cache_miss`` counters (tag
``program``, plus ``reason`` on misses), ``aot.compile_seconds`` /
``aot.deserialize_seconds`` / ``aot.serialize_seconds`` histograms and
``aot.compile`` / ``aot.deserialize`` spans (docs/OBSERVABILITY.md).

TRUST BOUNDARY: entries are unpickled at load (the pjrt format's
in_tree/out_tree are jax treedefs that have no stable non-pickle
serialization), so anyone who can write under ``cache_dir`` gains code
execution in every later train/serve process that reads it — the same
trust level as the persistent XLA cache and the checkpoint directory.
Point ``--compile_cache_dir`` only at directories writable solely by
the user running the jobs; never at world-writable or multi-tenant
shared paths (docs/GUIDE.md "Precompile workflow").
"""

from __future__ import annotations

import logging
import os
import pickle
import time

import jax

from pertgnn_tpu import telemetry
from pertgnn_tpu.store import durable
from pertgnn_tpu.store.durable import StoreCorruption, StoreLock
from pertgnn_tpu.telemetry.jaxmon import watch_xla_cache

log = logging.getLogger(__name__)

# Bump to orphan every existing entry (layout/semantics change in the
# store itself — entries are format-versioned independently of the
# content key). v4: graftvault durable layout — immutable per-save
# blob generations (``<key>@g<N>.bin``) committed by one checksummed
# manifest replace (``<key>.json``), fsync'd writes, store locking.
_STORE_VERSION = 4

_pjrt_support: bool | None = None
_export_types_registered = False


def _blob_gen(filename: str, key: str) -> int | None:
    """The generation of a ``<key>@g<N>.bin`` blob name, else None."""
    prefix = f"{key}@g"
    if not (filename.startswith(prefix) and filename.endswith(".bin")):
        return None
    try:
        return int(filename[len(prefix):-len(".bin")])
    except ValueError:
        return None


def pjrt_roundtrip_supported() -> bool:
    """Whether this backend's compiled executables survive
    serialize -> deserialize_and_load (probed ONCE per process with a
    trivial program; ~100 ms). XLA:CPU serializes without complaint but
    fails at load ("Symbols not found"), which is why the probe must
    round-trip, not just serialize."""
    global _pjrt_support
    if _pjrt_support is None:
        try:
            from jax.experimental import serialize_executable as se
            probe = jax.jit(lambda x: x + 1).lower(
                jax.ShapeDtypeStruct((), "int32")).compile()
            exe = se.deserialize_and_load(*se.serialize(probe))
            _pjrt_support = exe is not None
        except Exception as e:
            log.info("pjrt executable serialization unsupported on this "
                     "backend (%s: %s); using stablehlo entries",
                     type(e).__name__, e)
            _pjrt_support = False
    return _pjrt_support


def register_export_types() -> None:
    """Register this repo's pytree node types (and the optax states
    inside TrainState) with jax.export's serializer. Idempotent; lazy —
    called on first export/deserialize so importing the store never
    drags in the train stack."""
    global _export_types_registered
    if _export_types_registered:
        return
    import optax
    from jax import export

    from pertgnn_tpu.batching.arena import CompactBatch
    from pertgnn_tpu.batching.pack import PackedBatch
    from pertgnn_tpu.train.loop import TrainState

    for nt, name in ((optax.ScaleByAdamState, "optax.ScaleByAdamState"),
                     (optax.EmptyState, "optax.EmptyState"),
                     (PackedBatch, "pertgnn.PackedBatch"),
                     (CompactBatch, "pertgnn.CompactBatch")):
        try:
            export.register_namedtuple_serialization(nt,
                                                     serialized_name=name)
        except ValueError:
            pass  # a previous partial registration pass got here
    try:
        # TrainState is a flax struct dataclass: every field is pytree
        # data, so its auxdata is the empty tuple
        export.register_pytree_node_serialization(
            TrainState, serialized_name="pertgnn.TrainState",
            serialize_auxdata=lambda aux: b"",
            deserialize_auxdata=lambda b: ())
    except ValueError:
        pass
    _export_types_registered = True


class ExecutableStore:
    """Content-addressed serialized executables under ``root``.

    Layout: ``<root>/<name>/<key>@g<N>.bin`` (the pickled payload — an
    immutable per-save generation) + ``<root>/<name>/<key>.json`` (a
    graftvault checksummed manifest: the blob's name + CRC32C, plus the
    key's components — the diff source for loud invalidation). ``name``
    is a logical slot ("which program"), ``key`` the content hash
    ("compiled against what"). The manifest replace is the ONE commit
    point; saves serialize under the store lock (``<root>/.lock``)."""

    def __init__(self, root: str, bus=None):
        self.root = root
        self._injected_bus = bus
        os.makedirs(root, exist_ok=True)

    @property
    def _bus(self):
        return (self._injected_bus if self._injected_bus is not None
                else telemetry.get_bus())

    def _meta_path(self, name: str, key: str) -> str:
        return os.path.join(self.root, name, f"{key}.json")

    # -- the one-stop entry point ---------------------------------------

    def load_or_build(self, name: str, key: str, components: dict,
                      jit_fn, abstract_args, *,
                      donate_argnums: tuple = ()) -> tuple[object, str]:
        """(executable, outcome) for (name, key): outcome is
        "deserialized" (store hit — zero fresh model compiles) or
        "compiled" (miss — built fresh, persisted for the next process).
        ``jit_fn`` must be the already-``jax.jit``-wrapped function
        (donation flags and all); ``abstract_args`` its
        ShapeDtypeStruct calling signature.  ``donate_argnums`` must
        MIRROR the jit's own donation: the stablehlo replay form wraps
        the deserialized artifact in a fresh ``jax.jit``, and a
        donating program replayed WITHOUT the flag leaves jax unaware
        that XLA aliases the input buffers in place — the caller keeps
        "live" arrays whose memory the executable reuses, which
        corrupts the heap the first time anything (e.g. orbax's async
        checkpoint serializer) still reads them (found by
        benchmarks/stream_bench.py's warm-restart phase: restored
        TrainState + replayed donating train step = SIGSEGV)."""
        exe = self.load(name, key, components, abstract_args=abstract_args)
        if exe is not None:
            return exe, "deserialized"
        bus = self._bus
        t0 = time.perf_counter()
        with bus.span("aot.compile", program=name):
            if pjrt_roundtrip_supported():
                exe = jit_fn.lower(*abstract_args).compile()
                self.save(name, key, components, exe, jit_fn=jit_fn,
                          abstract_args=abstract_args)
            else:
                exe = self._build_and_save_stablehlo(
                    name, key, components, jit_fn, abstract_args,
                    donate_argnums=donate_argnums)
        bus.histogram("aot.compile_seconds", time.perf_counter() - t0,
                      program=name)
        return exe, "compiled"

    def _build_and_save_stablehlo(self, name, key, components, jit_fn,
                                  abstract_args, donate_argnums=()):
        """Export first, then compile the REPLAYED form and make it the
        live executable — the warm path re-lowers the identical
        deserialized artifact, so its backend compile hits the
        persistent-cache entry this one writes. Falls back to a plain
        (unserialized) compile when export cannot carry the program."""
        from jax import export

        try:
            register_export_types()
            blob = export.export(jit_fn)(*abstract_args).serialize()
        except Exception as e:
            log.warning(
                "could not export %s (%s: %s) — compiling unserialized; "
                "this program will recompile on every process start "
                "(the persistent XLA cache may still shortcut it)",
                name, type(e).__name__, e)
            self._bus.counter("aot.serialize_failed", program=name)
            return jit_fn.lower(*abstract_args).compile()
        exe = self._replay(blob, abstract_args, donate_argnums)
        self._save(name, key, components,
                   {"format": "stablehlo", "payload": blob,
                    "donate_argnums": list(donate_argnums)})
        return exe

    # -- load ------------------------------------------------------------

    def load(self, name: str, key: str, components: dict, *,
             abstract_args=None):
        """The executable for (name, key), or None (miss/corrupt —
        callers compile fresh and save). ``abstract_args`` is required
        to replay ``stablehlo`` entries (the re-lowering target)."""
        bus = self._bus
        meta_path = self._meta_path(name, key)
        if not os.path.exists(meta_path):
            self._log_invalidation(name, key, components)
            bus.counter("aot.cache_miss", program=name, reason="absent")
            return None
        t0 = time.perf_counter()
        try:
            with bus.span("aot.deserialize", program=name):
                meta = durable.read_json(meta_path, store="aot")
                blob = str(meta.get("blob", ""))
                if not blob.startswith(f"{key}@g"):
                    raise StoreCorruption(
                        f"manifest names a foreign blob {blob!r}",
                        store="aot", path=meta_path, reason="bad_dir")
                with open(os.path.join(self.root, name, blob),
                          "rb") as f:
                    data = f.read()
                # CRC gate BEFORE unpickle: bit-rot in a pickled
                # payload must never reach the deserializer (the trust
                # boundary in the module docstring assumes intact
                # writer-produced bytes)
                if (durable.crc32c(data) != meta.get("blob_crc32c")
                        or len(data) != meta.get("blob_bytes")):
                    raise StoreCorruption(
                        "blob CRC32C mismatch — refusing to unpickle",
                        store="aot", path=meta_path,
                        reason="crc_mismatch")
                entry = pickle.loads(data)
                if entry.get("store_version") != _STORE_VERSION:
                    raise ValueError(
                        f"store version {entry.get('store_version')!r} != "
                        f"{_STORE_VERSION}")
                exe = self._deserialize(entry, abstract_args)
        except Exception as e:
            # corrupt/truncated/stale entry: NEVER crash the caller —
            # fall back to a fresh compile (whose save overwrites this)
            log.warning(
                "corrupt AOT store entry %s/%s (%s: %s) — falling back "
                "to fresh compile", name, key, type(e).__name__, e)
            bus.counter("aot.cache_miss", program=name, reason="corrupt")
            return None
        dt = time.perf_counter() - t0
        bus.counter("aot.cache_hit", program=name, format=entry["format"])
        bus.histogram("aot.deserialize_seconds", dt, program=name,
                      format=entry["format"])
        return exe

    def _deserialize(self, entry: dict, abstract_args):
        if entry["format"] == "pjrt":
            from jax.experimental import serialize_executable as se
            return se.deserialize_and_load(entry["payload"],
                                           entry["in_tree"],
                                           entry["out_tree"])
        if entry["format"] == "stablehlo":
            if abstract_args is None:
                raise ValueError(
                    "stablehlo entry needs abstract_args to replay")
            with watch_xla_cache() as cache:
                exe = self._replay(entry["payload"], abstract_args,
                                   tuple(entry.get("donate_argnums", ())))
            if cache["misses"]:
                # the save-time compile of this exact form should have
                # landed in the persistent cache — a miss means that
                # cache was cleared/moved out from under the store:
                # still correct, but this "deserialize" paid a compile
                log.warning(
                    "stablehlo replay was NOT served by the persistent "
                    "compilation cache (%d fresh XLA compiles) — was "
                    "the cache dir cleared?", cache["misses"])
                self._bus.counter("aot.replay_uncached")
            return exe
        raise ValueError(f"unknown entry format {entry['format']!r}")

    @staticmethod
    def _replay(blob: bytes, abstract_args, donate_argnums: tuple = ()):
        from jax import export

        register_export_types()
        # donate_argnums MUST mirror the exported program's own
        # donation (see load_or_build) — the exported module's
        # input/output aliasing is invisible to this fresh jit wrapper
        return jax.jit(export.deserialize(blob).call,
                       donate_argnums=donate_argnums).lower(
            *abstract_args).compile()

    # -- save ------------------------------------------------------------

    def _serialize_pjrt(self, compiled) -> dict:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        return {"format": "pjrt", "payload": payload,
                "in_tree": in_tree, "out_tree": out_tree}

    def save(self, name: str, key: str, components: dict, compiled, *,
             jit_fn=None, abstract_args=None) -> str | None:
        """Persist an already-compiled executable under (name, key);
        returns the format written or None. Prefer ``load_or_build``,
        which picks the format BEFORE compiling; this entry point is for
        callers that already hold a compiled program (pjrt backends
        only — on stablehlo backends it exports the function when
        ``jit_fn``/``abstract_args`` are given, but the caller's live
        executable then differs in form from what later processes
        deserialize; bit-equality between the two is pinned by
        tests/test_aot.py)."""
        entry = None
        if pjrt_roundtrip_supported():
            try:
                entry = self._serialize_pjrt(compiled)
                # validate THIS entry, not just the probe: XLA:CPU
                # reloads trivial programs fine but rejects ones whose
                # kernels JIT'd runtime symbols ("Symbols not found")
                self._deserialize(
                    {**entry, "store_version": _STORE_VERSION}, None)
            except Exception as e:
                entry = None
                log.info("pjrt serialization of %s failed validation "
                         "(%s: %s); trying stablehlo", name,
                         type(e).__name__, e)
        if entry is None and jit_fn is not None and abstract_args is not None:
            from jax import export

            try:
                register_export_types()
                entry = {"format": "stablehlo",
                         "payload": export.export(jit_fn)(
                             *abstract_args).serialize()}
                # prime the replay form so the next process's load is a
                # persistent-cache hit, not a fresh compile
                self._replay(entry["payload"], abstract_args)
            except Exception as e:
                log.warning("could not serialize %s in any format "
                            "(%s: %s) — it will recompile on every "
                            "process start", name, type(e).__name__, e)
                self._bus.counter("aot.serialize_failed", program=name)
                return None
        if entry is None:
            return None
        return self._save(name, key, components, entry)

    def _save(self, name: str, key: str, components: dict,
              entry: dict) -> str:
        bus = self._bus
        t0 = time.perf_counter()
        entry["store_version"] = _STORE_VERSION
        slot = os.path.join(self.root, name)
        os.makedirs(slot, exist_ok=True)
        data = pickle.dumps(entry)
        # durable commit: the blob lands as an IMMUTABLE generation
        # first, then one checksummed-manifest replace makes it live —
        # a kill at any instant leaves the previous (blob, manifest)
        # pair fully intact, never a new blob under an old manifest.
        # The store lock serializes concurrent warmers (two autoscale
        # spares saving the same rung) instead of racing renames.
        with StoreLock(os.path.join(self.root, ".lock"), store="aot",
                       bus=bus):
            gen = 1 + max(
                (g for g in (_blob_gen(f, key)
                             for f in os.listdir(slot)) if g is not None),
                default=0)
            blob = f"{key}@g{gen}.bin"
            blob_path = os.path.join(slot, blob)
            durable.durable_write(blob_path, data, store="aot", bus=bus)
            durable.write_json(
                self._meta_path(name, key),
                {"key": key, "format": entry["format"],
                 "created_unix_time": time.time(), "blob": blob,
                 "blob_crc32c": durable.crc32c(data),
                 "blob_bytes": len(data), **components},
                store="aot", bus=bus)
            for f in os.listdir(slot):  # GC superseded generations
                if _blob_gen(f, key) not in (None, gen):
                    try:
                        os.unlink(os.path.join(slot, f))
                    except OSError:
                        pass
        dt = time.perf_counter() - t0
        bus.histogram("aot.serialize_seconds", dt, program=name,
                      format=entry["format"])
        log.info("AOT store: saved %s/%s (%s, %.0f KiB) in %.2fs",
                 name, key, entry["format"], len(data) / 1024, dt)
        return entry["format"]

    # -- invalidation diagnostics ---------------------------------------

    def _log_invalidation(self, name: str, key: str,
                          components: dict) -> None:
        """A miss while OTHER entries exist under this name means
        something about the environment/config changed since they were
        saved — name the ingredient instead of recompiling silently."""
        d = os.path.join(self.root, name)
        try:
            metas = [f for f in os.listdir(d) if f.endswith(".json")]
        except OSError:
            return
        if not metas:
            return
        # diff against the NEWEST entry (by its recorded creation time,
        # not the arbitrary hex-hash filename order): with several
        # entries in a slot, naming the ingredient that changed since
        # the latest save is the message an operator can act on
        prev = None
        for f in metas:
            try:
                m = durable.read_json(os.path.join(d, f), store="aot")
            except (StoreCorruption, OSError, ValueError):
                continue
            if (prev is None or m.get("created_unix_time", 0)
                    > prev.get("created_unix_time", 0)):
                prev = m
        if prev is None:
            log.warning("AOT store: %s has entries but unreadable "
                        "metadata; recompiling fresh", name)
            return
        changed = diff_components(prev, components)
        log.warning(
            "AOT store: invalidating %s (saved key %s != wanted %s); "
            "changed: %s — recompiling fresh", name,
            prev.get("key", "?")[:12], key[:12],
            "; ".join(changed) if changed else "unknown (metadata "
            "predates these components)")
        self._bus.counter("aot.invalidated", program=name)


def diff_components(prev: dict, now: dict) -> list[str]:
    """Human-readable 'what changed' between two key-component dicts
    (dotted paths, saved vs wanted)."""
    out: list[str] = []

    def walk(path, a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                walk(f"{path}.{k}" if path else str(k),
                     a.get(k), b.get(k))
        elif a != b:
            out.append(f"{path}: saved={a!r} vs now={b!r}")

    for field in ("fn", "env", "config", "args"):
        walk(field, prev.get(field), now.get(field))
    return out
