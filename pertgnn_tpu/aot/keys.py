"""Content-hash keys for persisted executables.

A serialized executable is only reusable when EVERYTHING that shaped its
compilation is identical: the jax/jaxlib pair that lowered it, the
backend and device kind it was compiled for, the mesh/topology it was
sharded over, the Config semantics baked into the program as constants
(label_scale, model arch), and the abstract calling signature. The key
is a sha256 over a canonical JSON of all of those components; the
components themselves are persisted next to each entry so a miss can say
loudly WHICH ingredient changed (store.py) instead of silently
recompiling forever after an invisible drift.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any

import jax


def environment_fingerprint(mesh=None) -> dict:
    """The lowering environment a compiled artifact is welded to:
    jax/jaxlib versions, backend platform + device kind, local device
    count, and (when given) the mesh axis layout."""
    import jaxlib

    dev = jax.devices()[0]
    fp = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "") or "",
        "num_devices": jax.device_count(),
    }
    if mesh is not None:
        fp["mesh"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    return fp


def _canonical(obj: Any) -> Any:
    """JSON-stable view: dataclasses -> dicts, tuples -> lists, sets
    sorted; anything else must already be JSON-serializable (enforced by
    json.dumps below — an unserializable component should fail loudly at
    key time, not silently hash its repr)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _canonical(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(k): _canonical(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return sorted(_canonical(v) for v in obj)
    return obj


def abstract_signature(tree) -> dict:
    """The calling convention of a pytree of ShapeDtypeStructs (or
    arrays): per-leaf shape:dtype plus the treedef — what a compiled
    executable actually binds to at dispatch."""
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    sig = []
    for x in leaves:
        if not hasattr(x, "shape") or not hasattr(x, "dtype"):
            x = np.asarray(x)
        sig.append(f"{tuple(x.shape)}:{np.dtype(x.dtype).name}")
    return {"leaves": sig, "treedef": str(treedef)}


def cache_key(*, fn_id: str, config: dict, args_sig: dict,
              env: dict | None = None) -> tuple[str, dict]:
    """(hex key, components) for one executable.

    `fn_id` names the Python function AND its revision — bump it when
    the function's body changes meaning without changing its signature
    (the one ingredient a content hash over inputs cannot see).
    `config` carries the Config subtrees whose values are baked into the
    program as constants; `args_sig` the abstract_signature of the call
    args; `env` defaults to the live environment_fingerprint()."""
    components = {
        "fn": fn_id,
        "env": _canonical(env if env is not None
                          else environment_fingerprint()),
        "config": _canonical(config),
        "args": _canonical(args_sig),
    }
    blob = json.dumps(components, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:32], components
