"""Console-script launcher for graftscope (docs/OBSERVABILITY.md).

Same pattern as graftlint_cli.py / graftaudit_cli.py: the launcher
lives inside `pertgnn_tpu` so the wheel never ships a generic
top-level `tools` package (namespace squatting), while the
`graftscope` entry point still works in the install mode where the
collector's sibling source exists — an editable (in-repo) install —
and fails with a clear message, not a ModuleNotFoundError, everywhere
else. Unlike the two analyzers, graftscope reads telemetry JSONL (not
the source tree), but it ships with the repo the same way.
"""

from __future__ import annotations

import os
import sys


def main(argv: list[str] | None = None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo, "tools", "graftscope")):
        print(
            "graftscope: no tools/graftscope next to this package — "
            "the collector ships as a sibling of an editable (in-repo) "
            "install. From a checkout, run "
            "`python -m tools.graftscope` (docs/OBSERVABILITY.md).",
            file=sys.stderr)
        return 2
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftscope.cli import main as graftscope_main

    return graftscope_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
