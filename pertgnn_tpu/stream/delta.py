"""Vocab-stable shard ingest: one trace shard -> one ``ShardDelta``.

The live-traffic data path (ROADMAP item 1): the batch pipeline keys the
whole corpus on one fingerprint, so one new trace shard invalidates
everything and forces a full re-ingest.  This module makes the shard the
unit of ingest instead.  Each shard runs the SAME preprocessing passes
the batch path runs (dedupe -> sort -> factorize -> entry detection ->
resource aggregation -> runtime-pattern dedup -> graph construction) but
with the base corpus's string vocabularies PINNED, so the codes a delta
shard produces are exactly the codes a from-scratch rebuild of the union
corpus would produce — which is what lets stream/merge.py reconstitute
the merged dataset from base + deltas with bit-identical packed batches
(benchmarks/stream_bench.py exit-code-asserts it against the real batch
path).

Vocabulary contract (docs/GUIDE.md "Live traffic"):

- ``ms`` / ``interface`` / ``rpctype`` are PINNED: their codes are baked
  into graph node orderings (sorted-unique compaction), embedding rows,
  and runtime-pattern identities, and the ms vocabulary is sorted so any
  insertion relabels everything after it.  A delta shard containing an
  unseen value raises :class:`VocabGrowth` — the LOUD signal that this
  shard needs the full-rebuild path, not the delta path.
- ``entryid`` is APPEND-ONLY: a new entry is a new combination of
  existing strings; its code appends at the end exactly where the union
  rebuild's first-appearance factorization would put it.  Delta shards
  store entry strings locally; global codes are assigned at merge time,
  which is what makes shard ingest order-independent.
- ``traceid`` / ``rpcid`` are shard-local: trace codes are offset into
  the global space at merge (shards are time-ordered, see merge.py);
  rpcid codes only ever feed within-trace equality tests (edge
  sanitizing), which any bijective relabeling preserves.

A shard's expensive work — CSV parse, the vectorized preprocess passes,
runtime-pattern dedup, and per-pattern graph construction — happens HERE,
once, at ingest time; the merge only concatenates, filters, and re-derives
the cheap global tails (mixture weights, splits, budget).
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np
import pandas as pd

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.graphs.construct import GraphSpec, build_runtime_graphs
from pertgnn_tpu.ingest.assemble import TraceTable, assemble
from pertgnn_tpu.ingest.preprocess import (PreprocessResult,
                                           build_resource_table,
                                           detect_entries,
                                           factorize_columns)

log = logging.getLogger(__name__)


class VocabGrowth(RuntimeError):
    """A delta shard contains string values outside the base corpus's
    pinned vocabulary (new microservice / interface / rpctype).  The
    delta path CANNOT absorb these — ms codes are sorted (insertion
    relabels every later code) and interface/rpctype sizes are baked
    into embedding shapes beneath the serving checkpoint — so the caller
    must route this shard through the loud full-rebuild path
    (stream/merge.py docs; counter ``stream.rebuild``)."""

    def __init__(self, column: str, values: list):
        shown = ", ".join(repr(v) for v in values[:5])
        more = f" (+{len(values) - 5} more)" if len(values) > 5 else ""
        super().__init__(
            f"vocab growth in {column!r}: {shown}{more} not in the base "
            f"corpus's pinned vocabulary — this shard needs a full "
            f"rebuild, not a delta ingest")
        self.column = column
        self.values = values


@dataclasses.dataclass
class ShardDelta:
    """One ingested shard: everything the merge needs, as plain arrays.

    For the BASE shard the trace rows are the batch build's survivors
    (its filter decisions are final — the stream filters forward-only);
    for DELTA shards they are the entry-detection survivors, with the
    corpus-global filters (resource coverage, entry occurrence) deferred
    to merge time where the cumulative statistics live."""

    kind: str                    # "base" | "delta"
    # -- per-trace rows (aligned arrays) --------------------------------
    traceid: np.ndarray          # int64 shard-local codes
    entry_local: np.ndarray      # int64 index into entry_vocab
    runtime_local: np.ndarray    # int64 shard-local pattern ids
    ts_bucket: np.ndarray        # int64
    y: np.ndarray                # float64
    # -- shard identity / ordering --------------------------------------
    n_traces_total: int          # local traceid code space (incl. dropped)
    span_ts_min: int             # RAW span time range (pre-filter)
    span_ts_max: int
    traceid_strings: np.ndarray  # raw trace ids (cross-shard disjointness)
    entry_vocab: list            # entry strings, local first-appearance
    # -- runtime patterns ------------------------------------------------
    pat_tokens: np.ndarray       # (T, 3) int64 (um, dm, interface) rows
    pat_offsets: np.ndarray      # (P+1,) int64 — pattern p = rows [p, p+1)
    pat_rep_trace: np.ndarray    # (P,) int64 local rep trace per pattern
    graphs: dict                 # local pattern id -> GraphSpec
    # -- coverage incidence (delta: distinct (trace, ms); base: empty) --
    inc_trace: np.ndarray
    inc_ms: np.ndarray
    # -- aggregated resources -------------------------------------------
    res_ts: np.ndarray           # int64
    res_ms: np.ndarray           # int64 (pinned codes)
    res_values: np.ndarray       # (rows, 8) float32
    # -- base-only -------------------------------------------------------
    vocabs: dict | None = None   # {"ms","interface","rpctype","entryid"}
    entry_occ_prefilter: dict | None = None  # entry string -> raw count
    base_vocab_hash: str | None = None       # deltas: the base they bind to
    # traces the base's resource-coverage filter dropped (None =
    # unknown, pre-stats artifacts): when 0, no delta resource rows can
    # resurrect a base trace and the merge's coverage-drift guard can
    # safely admit first-time resource coverage of a vocab ms
    coverage_dropped: int | None = None

    @property
    def num_patterns(self) -> int:
        return len(self.pat_rep_trace)

    def pattern_key(self, local_id: int) -> bytes:
        """The shard-independent identity of one runtime pattern: its
        (um, dm, interface) token sequence in trace row order — exactly
        the equality ``ingest/assemble.py`` dedups traces by."""
        s, e = self.pat_offsets[local_id], self.pat_offsets[local_id + 1]
        return np.ascontiguousarray(self.pat_tokens[s:e]).tobytes()


def vocab_hash(vocabs: dict) -> str:
    """Content hash of the pinned vocabularies — a delta shard is only
    mergeable against the exact base it was coded with."""
    import hashlib

    h = hashlib.sha256()
    for name in ("ms", "interface", "rpctype", "entryid"):
        arr = np.asarray(vocabs[name])
        h.update(name.encode())
        for v in arr.tolist():
            h.update(str(v).encode())
            h.update(b"\x00")
    return h.hexdigest()[:16]


def _pattern_table(pre_spans: pd.DataFrame, table: TraceTable
                   ) -> tuple[np.ndarray, np.ndarray]:
    """(tokens (T,3), offsets (P+1,)) in local pattern-id order, taken
    from each pattern's representative trace (every trace of a pattern
    shares the sequence — that IS the pattern identity)."""
    reps = table.runtime2trace
    rep_rows = pre_spans[pre_spans["traceid"].isin(set(reps.values()))]
    by_trace = {tid: grp for tid, grp in rep_rows.groupby("traceid")}
    tokens: list[np.ndarray] = []
    offsets = [0]
    for rid in sorted(reps):
        grp = by_trace[reps[rid]]
        t = np.stack([grp["um"].to_numpy(np.int64),
                      grp["dm"].to_numpy(np.int64),
                      grp["interface"].to_numpy(np.int64)], axis=1)
        tokens.append(t)
        offsets.append(offsets[-1] + len(t))
    flat = (np.concatenate(tokens) if tokens
            else np.empty((0, 3), np.int64))
    return flat, np.asarray(offsets, np.int64)


def _meta_arrays(table: TraceTable) -> dict:
    m = table.meta
    return {
        "traceid": m["traceid"].to_numpy(np.int64),
        "runtime_local": m["runtime_id"].to_numpy(np.int64),
        "ts_bucket": m["ts_bucket"].to_numpy(np.int64),
        "y": m["y"].to_numpy(np.float64),
    }


def _resource_arrays(resource_df: pd.DataFrame
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    feat_cols = [c for c in resource_df.columns
                 if c not in ("timestamp", "msname")]
    return (resource_df["timestamp"].to_numpy(np.int64),
            resource_df["msname"].to_numpy(np.int64),
            resource_df[feat_cols].to_numpy(np.float32))


def base_shard(pre: PreprocessResult, table: TraceTable, graph_type: str,
               cfg: IngestConfig = IngestConfig()) -> ShardDelta:
    """Wrap a batch-built corpus as the stream's shard 0.

    The base is exactly the artifact pair the batch path produced —
    same filters, same codes — so a stream that never receives a delta
    IS the batch build.  Its vocabularies become the pin every later
    delta ingests against."""
    graphs = build_runtime_graphs(pre, table, graph_type)
    pat_tokens, pat_offsets = _pattern_table(pre.spans, table)
    reps = np.asarray([table.runtime2trace[r]
                       for r in sorted(table.runtime2trace)], np.int64)
    res_ts, res_ms, res_values = _resource_arrays(pre.resources)
    stats = pre.stats or {}
    if "span_ts_min" in stats:
        ts_min, ts_max = int(stats["span_ts_min"]), int(stats["span_ts_max"])
    else:
        # older artifact caches predate the raw-range stats: fall back to
        # the survivors' range (dropped-trace rows can extend past it, so
        # the merge-ordering guard is slightly laxer — say so once)
        log.warning("base artifacts predate span_ts_min/max stats; the "
                    "shard-ordering guard uses the filtered range")
        ts_min = int(pre.spans["timestamp"].min())
        ts_max = int(pre.spans["timestamp"].max())
    # raw occurrence counts per entry string BEFORE the occurrence filter
    # — lets the merge detect (loudly) when delta growth would have
    # resurrected base traces the batch build dropped (filter drift)
    occ = stats.get("entry_occ_prefilter")
    vocabs = {"ms": np.asarray(pre.ms_vocab),
              "interface": np.asarray(pre.interface_vocab),
              "rpctype": np.asarray(pre.rpctype_vocab),
              "entryid": np.asarray(pre.entryid_vocab)}
    meta = _meta_arrays(table)
    return ShardDelta(
        kind="base",
        entry_local=table.meta["entry_id"].to_numpy(np.int64),
        n_traces_total=len(pre.traceid_vocab),
        span_ts_min=ts_min, span_ts_max=ts_max,
        traceid_strings=np.asarray(pre.traceid_vocab, dtype=object),
        entry_vocab=[str(v) for v in np.asarray(pre.entryid_vocab)],
        pat_tokens=pat_tokens, pat_offsets=pat_offsets,
        pat_rep_trace=reps, graphs=graphs,
        inc_trace=np.empty(0, np.int64), inc_ms=np.empty(0, np.int64),
        res_ts=res_ts, res_ms=res_ms, res_values=res_values,
        vocabs=vocabs, entry_occ_prefilter=occ,
        base_vocab_hash=None,
        coverage_dropped=(int(stats["num_coverage_dropped"])
                          if "num_coverage_dropped" in stats else None),
        **meta)


def _pinned_codes(col: pd.Series, vocab: np.ndarray,
                  column: str) -> np.ndarray:
    """Map raw strings to the base vocabulary's codes (code = position);
    any unseen value is VocabGrowth, never a silent -1."""
    mapping = {v: i for i, v in enumerate(np.asarray(vocab).tolist())}
    codes = col.map(mapping)
    if codes.isna().any():
        unknown = sorted(set(col[codes.isna()].astype(str).tolist()))
        raise VocabGrowth(column, unknown)
    return codes.to_numpy(np.int64)


def ingest_delta(spans: pd.DataFrame, resources: pd.DataFrame,
                 base: ShardDelta, graph_type: str,
                 cfg: IngestConfig = IngestConfig()) -> ShardDelta:
    """One raw trace shard -> ShardDelta, coded against `base`'s pinned
    vocabularies.  Mirrors ``ingest.preprocess._preprocess`` pass for
    pass (order matters: codes depend on it) with three deltas: string
    vocabs are pinned (VocabGrowth on growth), entry ids stay shard-local
    (globalized at merge), and the corpus-global filters are deferred to
    the merge, which owns the cumulative statistics."""
    from pertgnn_tpu import telemetry

    if base.vocabs is None:
        raise ValueError("ingest_delta needs the BASE shard (it carries "
                         "the pinned vocabularies)")
    with telemetry.get_bus().span("stream.shard_ingest", rows=len(spans)):
        return _ingest_delta(spans, resources, base, graph_type, cfg)


def _ingest_delta(spans: pd.DataFrame, resources: pd.DataFrame,
                  base: ShardDelta, graph_type: str,
                  cfg: IngestConfig) -> ShardDelta:
    vocabs = base.vocabs
    df = spans.drop_duplicates()
    df = df.sort_values(by=["timestamp"], kind="stable")
    if len(df) == 0:
        raise ValueError("empty shard: no span rows after dedupe")
    ts_min = int(df["timestamp"].min())
    ts_max = int(df["timestamp"].max())

    # the batch pipeline's exact pass order (codes depend on it):
    # traceid -> interface -> entry detection -> entryid -> rpcid ->
    # rpctype -> resources/filters -> ms mapping -> endTimestamp
    df, traceid_vocab = factorize_columns(df, ["traceid"])
    df = df.copy(deep=False)
    df["interface"] = _pinned_codes(df["interface"], vocabs["interface"],
                                    "interface")
    df, _entry_stats = detect_entries(df, cfg)
    df = df.copy(deep=False)
    # entry strings stay LOCAL: the merge assigns global codes in
    # canonical shard order, which keeps ingest order-independent
    df, entry_vocab_local = factorize_columns(df, ["entryid"])
    df, _ = factorize_columns(df, ["rpcid"])
    df["rpctype"] = _pinned_codes(df["rpctype"], vocabs["rpctype"],
                                  "rpctype")

    resource_df = build_resource_table(resources, cfg)
    resource_df = resource_df.copy(deep=False)
    resource_df["msname"] = _pinned_codes(resource_df["msname"],
                                          vocabs["ms"], "ms")
    df["um"] = _pinned_codes(df["um"], vocabs["ms"], "ms")
    df["dm"] = _pinned_codes(df["dm"], vocabs["ms"], "ms")
    df["endTimestamp"] = df["timestamp"] + df["rt"].abs()
    df = df.reset_index(drop=True)

    # distinct (trace, ms) incidence — what the merge's deferred
    # resource-coverage filter consumes (preprocess.py's packed-key
    # idiom; ms codes < 2^32 by vocabulary construction)
    t = df["traceid"].to_numpy(np.int64)
    key = np.concatenate([(t << 32) | df["um"].to_numpy(np.int64),
                          (t << 32) | df["dm"].to_numpy(np.int64)])
    pairs = np.unique(key)
    inc_trace = pairs >> 32
    inc_ms = pairs & np.int64(0xFFFFFFFF)

    pre_local = PreprocessResult(
        spans=df, resources=resource_df,
        traceid_vocab=np.asarray(traceid_vocab),
        interface_vocab=np.asarray(vocabs["interface"]),
        entryid_vocab=np.asarray(entry_vocab_local),
        rpctype_vocab=np.asarray(vocabs["rpctype"]),
        ms_vocab=np.asarray(vocabs["ms"]), stats={})
    table = assemble(pre_local, cfg)
    graphs = build_runtime_graphs(pre_local, table, graph_type)
    pat_tokens, pat_offsets = _pattern_table(df, table)
    reps = np.asarray([table.runtime2trace[r]
                       for r in sorted(table.runtime2trace)], np.int64)
    res_ts, res_ms, res_values = _resource_arrays(resource_df)
    meta = _meta_arrays(table)
    return ShardDelta(
        kind="delta",
        entry_local=table.meta["entry_id"].to_numpy(np.int64),
        n_traces_total=len(traceid_vocab),
        span_ts_min=ts_min, span_ts_max=ts_max,
        traceid_strings=np.asarray(traceid_vocab, dtype=object),
        entry_vocab=[str(v) for v in np.asarray(entry_vocab_local)],
        pat_tokens=pat_tokens, pat_offsets=pat_offsets,
        pat_rep_trace=reps, graphs=graphs,
        inc_trace=inc_trace, inc_ms=inc_ms,
        res_ts=res_ts, res_ms=res_ms, res_values=res_values,
        base_vocab_hash=vocab_hash(vocabs), **meta)


def shard_frames_by_window(spans: pd.DataFrame, resources: pd.DataFrame,
                           boundaries_ms: list[int],
                           ) -> list[tuple[pd.DataFrame, pd.DataFrame]]:
    """Slice one raw corpus into time-window shards: a trace belongs to
    the window of its FIRST span, and traces whose span range crosses a
    boundary are DROPPED (so the shards' raw time ranges cannot
    interleave and the merge-ordering guard holds by construction) —
    the shard generator for tests and stream_bench, and the documented
    recipe for slicing real feeds (docs/GUIDE.md "Live traffic")."""
    bounds = sorted(boundaries_ms)
    g = spans.groupby("traceid")["timestamp"]
    t_lo, t_hi = g.min(), g.max()
    edges = [-np.inf, *bounds, np.inf]
    out = []
    for i in range(len(edges) - 1):
        lo, hi = edges[i], edges[i + 1]
        keep = t_lo[(t_lo >= lo) & (t_lo < hi) & (t_hi < hi)].index
        shard_spans = spans[spans["traceid"].isin(keep)]
        rmask = (resources["timestamp"] >= lo) & (resources["timestamp"] < hi)
        out.append((shard_spans.reset_index(drop=True),
                    resources[rmask].reset_index(drop=True)))
    return out
