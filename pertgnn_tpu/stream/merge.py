"""Mixture-merge: base + delta shards -> one Dataset, no full rebuild.

The merge concatenates what the shards already computed (per-trace meta
rows, per-pattern graphs, aggregated resource rows) and re-derives only
the corpus-global tails that are cheap and vectorized: the cumulative
filters, runtime-pattern code assignment, mixture weights
(``assemble.table_from_meta``), the resource lookup, and the dataset
tail (``dataset.dataset_from_parts``).  Everything expensive — CSV
parse, the preprocess passes, pattern dedup, graph construction — was
paid once, at each shard's OWN ingest (stream/delta.py), so a merge over
N cached shards is seconds where a rebuild is minutes.

THE CONTRACT (exit-code-asserted by benchmarks/stream_bench.py and the
order-independence property test in tests/test_stream.py): the merged
dataset packs BIT-IDENTICAL batches to a from-scratch batch build over
the concatenated raw shards.  The guards below exist to keep that claim
honest rather than hopeful — every situation the delta algebra cannot
reproduce exactly raises :class:`StreamRebuildRequired` (counter
``stream.rebuild`` with the reason) instead of merging approximately:

- ``shard_overlap``     — shard raw time ranges interleave: trace codes
                          are assigned in global timestamp order, so
                          out-of-order shards cannot be appended;
- ``trace_overlap``     — a trace id appears in two shards (the batch
                          path would cross-shard-dedupe, which per-shard
                          ingest cannot see);
- ``resource_overlap``  — two shards carry the same (ts_bucket, ms)
                          resource group (the batch path would aggregate
                          the union's raw rows);
- ``base_changed``      — a delta was coded against a different base
                          vocabulary than the one being merged;
- ``filter_drift``      — delta growth would change a BASE filter
                          verdict: an entry the base occurrence filter
                          dropped crosses back over the threshold, or a
                          delta carries the first resource rows for an
                          ms the base never resourced while the base's
                          coverage filter dropped traces — either way
                          the batch rebuild would resurrect base traces
                          the stream no longer has;
- ``representative_drift`` — a pattern's globally-first surviving trace
                          is not the trace its shard built the graph
                          from (PERT edge event order and span durations
                          are representative-trace-specific).

Vocabulary growth (new ms/interface/rpctype strings) is refused earlier,
at delta INGEST (stream/delta.VocabGrowth).  New ENTRIES and new
TOPOLOGIES are the supported live cases and merge cleanly; the per-shard
counts ride the bus as ``stream.shard_new_entries`` /
``stream.shard_new_topologies`` — the drift gauges continual training
watches (stream/continual.py).
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np
import pandas as pd

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.dataset import Split, dataset_from_parts
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import build_mixtures
from pertgnn_tpu.config import Config
from pertgnn_tpu.ingest.assemble import table_from_meta
from pertgnn_tpu.stream.delta import ShardDelta, vocab_hash

log = logging.getLogger(__name__)


class StreamRebuildRequired(RuntimeError):
    """The delta algebra cannot reproduce the batch build for this shard
    set — the caller must route through the full-rebuild path (and the
    operator must see why: counter ``stream.rebuild`` with `reason`)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"stream merge requires a full rebuild "
                         f"({reason}){': ' + detail if detail else ''}")
        self.reason = reason


@dataclasses.dataclass
class MergeInfo:
    """What the merge learned, for continual training and telemetry."""

    # canonical shard order: [(kind, global trace offset, n_traces_total,
    #                          admitted_rows)]
    shards: list
    new_entries: list        # per shard (base = 0)
    new_topologies: list     # per shard (base = 0)
    dropped_coverage: int    # delta traces dropped by the coverage filter
    dropped_occurrence: int  # delta traces dropped by the occurrence filter
    meta: pd.DataFrame       # merged, sorted, max_traces-truncated

    def window_split(self, window_shards: int) -> Split:
        """The sliding fine-tune window: every example of the LAST
        `window_shards` shards (<= 0 = all shards) as a Split the
        continual trainer swaps in as its train split."""
        n = len(self.shards)
        w = n if window_shards <= 0 else min(window_shards, n)
        boundary = self.shards[n - w][1]  # first window shard's offset
        m = self.meta[self.meta["traceid"] >= boundary]
        return Split(entry_ids=m["entry_id"].to_numpy(np.int64),
                     ts_buckets=m["ts_bucket"].to_numpy(np.int64),
                     ys=m["y"].to_numpy(np.float32))


# -- reusable phases -----------------------------------------------------
# The functions below are the merge's statistics phases, factored so the
# scale-out path (parallel/scale.py) computes the SAME quantities from
# per-host partials + exchanged summaries.  Where the single-host merge
# holds every ShardDelta, a host holds only its assignment — so each
# phase takes plain summaries (spans, id sets, vocab lists, key bytes),
# never the shard objects, and both paths call the identical code.  Any
# behavior change here moves BOTH the oracle and the sharded twin, which
# is what keeps the bit-identity contract between them testable.

def canonical_key(s: ShardDelta) -> tuple:
    """The content key that totally orders delta shards: raw time span
    first, first trace-id string as the tiebreak.  Shard-to-host
    assignment (parallel/scale.py) sorts by this SAME key, which is what
    makes the assignment a pure function of shard content — invariant
    under arrival order."""
    return (s.span_ts_min, s.span_ts_max,
            str(s.traceid_strings[0]) if len(s.traceid_strings) else "")


def _canonical_order(deltas: list[ShardDelta]) -> list[ShardDelta]:
    return sorted(deltas, key=canonical_key)


def check_ordering(spans: list[tuple[int, int]]) -> None:
    """``shard_overlap`` guard over (span_ts_min, span_ts_max) pairs in
    canonical order — summaries, so hosts can run it after exchanging
    spans without shipping shard bodies."""
    for prev, nxt in zip(spans, spans[1:]):
        if nxt[0] < prev[1]:
            raise StreamRebuildRequired(
                "shard_overlap",
                f"shard [{nxt[0]}, {nxt[1]}] interleaves "
                f"[{prev[0]}, {prev[1]}] — trace codes "
                f"are assigned in global timestamp order")


def check_trace_disjoint(id_sets: list[set]) -> None:
    """``trace_overlap`` guard over per-shard trace-id string sets."""
    seen: set = set()
    for i, ids in enumerate(id_sets):
        dup = seen & ids
        if dup:
            raise StreamRebuildRequired(
                "trace_overlap",
                f"shard #{i} repeats {len(dup)} trace id(s) from earlier "
                f"shards (e.g. {sorted(dup)[:3]})")
        seen |= ids


def entry_union(base: ShardDelta, shard_vocabs: list,
                shard_counts: list, thr: int, bus) -> tuple:
    """Append-only global entry vocabulary over the base + delta shards
    (canonical order), with the occurrence filter-drift guards.

    ``shard_vocabs[k]`` is delta k's entry-vocab string list;
    ``shard_counts[k]`` its per-local-entry trace counts (bincount of
    ``entry_local`` — exchanged as summaries in the sharded path).
    Returns ``(entry_code, entry_maps, new_entries,
    delta_count_by_string)``.
    """
    entry_code: dict[str, int] = {s: i
                                  for i, s in enumerate(base.entry_vocab)}
    entry_maps: list[np.ndarray] = []
    new_entries = [0]
    occ_prefilter = base.entry_occ_prefilter or {}
    delta_count_by_string: dict[str, int] = {}
    for vocab, loc in zip(shard_vocabs, shard_counts):
        remap = np.empty(len(vocab), np.int64)
        fresh = 0
        for j, name in enumerate(vocab):
            if name not in entry_code:
                entry_code[name] = len(entry_code)
                fresh += 1
            remap[j] = entry_code[name]
        entry_maps.append(remap)
        new_entries.append(fresh)
        for j, name in enumerate(vocab):
            delta_count_by_string[name] = (
                delta_count_by_string.get(name, 0) + int(loc[j]))

    # filter-drift guard: an entry the BASE build dropped at its
    # occurrence filter (prefilter count <= threshold — NB dropped
    # entries still sit in the entryid vocabulary, which factorizes
    # before the filters) that delta growth would push OVER the
    # threshold — the batch rebuild would resurrect base traces the
    # stream no longer has, so a bit-identical merge is impossible
    if base.entry_occ_prefilter is None:
        # legacy base (pre-stats artifacts): the counts are unknown, so
        # fail CLOSED like the coverage twin below — refuse any delta
        # entry the base KNEW (it is in the vocabulary) but dropped
        # (no surviving rows); we cannot prove the rebuild would not
        # resurrect it
        base_live = set(np.unique(base.entry_local).tolist())
        for name, n_delta in delta_count_by_string.items():
            code = entry_code[name]
            if code < len(base.entry_vocab) and code not in base_live:
                bus.counter("stream.rebuild", reason="filter_drift")
                raise StreamRebuildRequired(
                    "filter_drift",
                    f"entry {name!r} is in the base vocabulary but has "
                    f"no surviving base traces, and the base predates "
                    f"the prefilter occurrence stats — cannot prove a "
                    f"batch rebuild would not resurrect it")
    for name, n_delta in delta_count_by_string.items():
        n_base = occ_prefilter.get(name, 0)
        if 0 < n_base <= thr and n_base + n_delta > thr:
            bus.counter("stream.rebuild", reason="filter_drift")
            raise StreamRebuildRequired(
                "filter_drift",
                f"entry {name!r} was dropped by the base occurrence "
                f"filter ({n_base} <= {thr}) but base+delta "
                f"({n_base}+{n_delta}) now passes — a batch rebuild "
                f"would resurrect base traces the stream dropped")
    return entry_code, entry_maps, new_entries, delta_count_by_string


def pattern_union(shard_keys: list) -> tuple:
    """Universal pattern identity over per-shard pattern-key byte lists
    (base first, deltas in canonical order).  Returns ``(pat_uidx,
    shard_uidx, shard_pid_by_uidx, new_topologies)`` — uidx assignment
    is first-appearance in shard order, exactly the single-host walk."""
    pat_uidx: dict[bytes, int] = {}
    shard_uidx: list[np.ndarray] = []       # local pattern id -> uidx
    shard_pid_by_uidx: list[dict] = []      # uidx -> local pattern id
    new_topologies = []
    for keys in shard_keys:
        u = np.empty(len(keys), np.int64)
        fresh = 0
        inv: dict[int, int] = {}
        for pid, key in enumerate(keys):
            if key not in pat_uidx:
                pat_uidx[key] = len(pat_uidx)
                fresh += 1
            u[pid] = pat_uidx[key]
            inv[int(u[pid])] = pid
        shard_uidx.append(u)
        shard_pid_by_uidx.append(inv)
        new_topologies.append(fresh)
    if new_topologies:
        new_topologies[0] = 0  # the base defines the universe
    return pat_uidx, shard_uidx, shard_pid_by_uidx, new_topologies


def check_coverage_drift(base: ShardDelta, shard_res_ms: list,
                         bus) -> None:
    """Coverage-drift guard, the resource-side twin of the occurrence
    guard in :func:`entry_union`: a delta carrying the FIRST resource
    rows for an ms the base never resourced changes base traces'
    coverage verdicts in a from-scratch rebuild (ms-with-resources is
    corpus-global).  Safe exactly when the base's coverage filter
    dropped nothing — otherwise refuse loudly.  ``shard_res_ms[k]`` is
    delta k's unique resource-ms codes (a summary, exchangeable)."""
    base_res_ms = np.unique(base.res_ms)
    for i, ms in enumerate(shard_res_ms, 1):
        fresh_ms = np.setdiff1d(np.unique(ms), base_res_ms)
        if len(fresh_ms) and (base.coverage_dropped is None
                              or base.coverage_dropped > 0):
            bus.counter("stream.rebuild", reason="filter_drift")
            raise StreamRebuildRequired(
                "filter_drift",
                f"shard #{i} carries the first resource rows for "
                f"{len(fresh_ms)} microservice(s) the base never "
                f"resourced (e.g. ms code {int(fresh_ms[0])}) while the "
                f"base's coverage filter dropped "
                f"{base.coverage_dropped if base.coverage_dropped is not None else 'an unknown number of'} "
                f"trace(s) — a batch rebuild could resurrect them")


def finalize_dataset(tid_a, ent_a, runtime, tsb_a, y_a, graphs,
                     res_ts, res_ms, res_values, cfg: Config, bus):
    """The merge's assembly tail: resource-overlap guard, merged
    resource lookup, mixture build, dataset tail.  Takes the ADMITTED
    meta columns with final runtime codes — everything after this point
    is identical whether the stats came from one host or a mesh.
    Returns ``(dataset, table)``."""
    dup = pd.MultiIndex.from_arrays([res_ts, res_ms]).duplicated()
    if dup.any():
        bus.counter("stream.rebuild", reason="resource_overlap")
        raise StreamRebuildRequired(
            "resource_overlap",
            f"{int(dup.sum())} (ts_bucket, ms) resource group(s) appear "
            f"in more than one shard — the batch path would aggregate "
            f"the union's raw rows")
    lookup = ResourceLookup.from_arrays(
        res_ts, res_ms, res_values,
        missing_indicator_is_one=cfg.model.missing_indicator_is_one)

    meta = pd.DataFrame({"traceid": tid_a, "entry_id": ent_a,
                         "runtime_id": runtime, "ts_bucket": tsb_a,
                         "y": y_a})
    table = table_from_meta(meta)
    mixtures = build_mixtures(
        graphs, table.entry2runtimes,
        feature_all_stage_copies=cfg.model.feature_all_stage_copies)
    dataset = dataset_from_parts(mixtures, lookup, table.meta, cfg)
    return dataset, table


def coverage_mask(s: ShardDelta, covered_ms: np.ndarray,
                   threshold: float) -> np.ndarray:
    """Per-local-trace coverage verdict for one delta shard, from its
    stored (trace, ms) incidence — the same >= threshold rule as
    ingest.preprocess.filter_by_resource_coverage, against the UNION
    resource table's microservice set."""
    ok = np.zeros(s.n_traces_total, dtype=bool)
    if len(s.inc_trace) == 0:
        return ok
    cov = np.isin(s.inc_ms, covered_ms)
    uniq_tr, start = np.unique(s.inc_trace, return_index=True)
    n_pairs = np.diff(np.concatenate([start, [len(s.inc_trace)]]))
    n_cov = np.add.reduceat(cov.astype(np.int64), start)
    ok[uniq_tr] = (n_cov / n_pairs) >= threshold
    return ok


def merge_shards(base: ShardDelta, deltas: list[ShardDelta],
                 cfg: Config, bus=None):
    """(Dataset, MergeInfo) for base + deltas, in any delta order."""
    bus = bus if bus is not None else telemetry.get_bus()
    t0 = time.perf_counter()
    if base.kind != "base" or base.vocabs is None:
        raise ValueError("merge_shards needs the BASE shard first")
    base_hash = vocab_hash(base.vocabs)
    try:
        for d in deltas:
            if d.base_vocab_hash != base_hash:
                raise StreamRebuildRequired(
                    "base_changed",
                    f"delta coded against base {d.base_vocab_hash}, "
                    f"merging against {base_hash}")
        shards = [base, *_canonical_order(deltas)]
        check_ordering([(s.span_ts_min, s.span_ts_max) for s in shards])
        check_trace_disjoint([set(np.asarray(s.traceid_strings).tolist())
                              for s in shards])
    except StreamRebuildRequired as e:
        # every refusal reason rides the SAME counter — the rebuild
        # signal operators alarm on (docs/OBSERVABILITY.md)
        bus.counter("stream.rebuild", reason=e.reason)
        raise

    # global trace-code offsets (the union build factorizes trace ids
    # over the time-sorted concatenation, so shard k's codes are its
    # local codes plus the earlier shards' PRE-FILTER trace counts)
    offsets = np.concatenate(
        [[0], np.cumsum([s.n_traces_total for s in shards])[:-1]])

    # -- global entry vocabulary (append-only) --------------------------
    thr = cfg.ingest.min_traces_per_entry
    entry_code, entry_maps, new_entries, _ = entry_union(
        base,
        [s.entry_vocab for s in shards[1:]],
        [np.bincount(s.entry_local, minlength=len(s.entry_vocab))
         for s in shards[1:]], thr, bus)

    # -- universal pattern identity -------------------------------------
    _, shard_uidx, shard_pid_by_uidx, new_topologies = pattern_union(
        [[s.pattern_key(pid) for pid in range(s.num_patterns)]
         for s in shards])

    check_coverage_drift(base, [s.res_ms for s in shards[1:]], bus)

    # -- deferred corpus-global filters (delta rows only) ---------------
    covered_ms = np.unique(np.concatenate([s.res_ms for s in shards]))
    cov_masks = [None] + [
        coverage_mask(s, covered_ms, cfg.ingest.min_resource_coverage)
        for s in shards[1:]]
    occ = np.zeros(len(entry_code), np.int64)
    np.add.at(occ, base.entry_local, 1)
    for s, remap, cov in zip(shards[1:], entry_maps, cov_masks[1:]):
        rows = cov[s.traceid]
        np.add.at(occ, remap[s.entry_local[rows]], 1)
    entry_ok = occ > thr

    # -- merged meta rows ------------------------------------------------
    tids, entries, uidxs, tsbs, ys = [], [], [], [], []
    admitted = []
    info_shards = []
    dropped_cov = dropped_occ = 0
    for i, s in enumerate(shards):
        tid = s.traceid + offsets[i]
        if i == 0:
            ent = s.entry_local
            ok = np.ones(len(tid), dtype=bool)
        else:
            ent = entry_maps[i - 1][s.entry_local]
            cov_ok = cov_masks[i][s.traceid]
            occ_ok = entry_ok[ent]
            ok = cov_ok & occ_ok
            dropped_cov += int((~cov_ok).sum())
            dropped_occ += int((cov_ok & ~occ_ok).sum())
        tids.append(tid)
        entries.append(ent)
        uidxs.append(shard_uidx[i][s.runtime_local])
        tsbs.append(s.ts_bucket)
        ys.append(s.y)
        admitted.append(ok)
        info_shards.append((s.kind, int(offsets[i]), s.n_traces_total,
                            int(ok.sum())))
    tid = np.concatenate(tids)
    ent = np.concatenate(entries)
    uidx = np.concatenate(uidxs)
    tsb = np.concatenate(tsbs)
    y = np.concatenate(ys)
    ok = np.concatenate(admitted)

    tid_a, ent_a, uidx_a = tid[ok], ent[ok], uidx[ok]
    tsb_a, y_a = tsb[ok], y[ok]
    order = np.argsort(tid_a, kind="stable")
    # final runtime codes: first appearance over ascending global trace
    # id among ADMITTED traces — the batch path's assignment exactly
    # (base patterns keep their base ids because base traces come first)
    codes_sorted, _ = pd.factorize(uidx_a[order])
    runtime = np.empty(len(tid_a), np.int64)
    runtime[order] = codes_sorted

    # -- representatives + graphs ---------------------------------------
    first_pos = np.full(int(codes_sorted.max(initial=-1)) + 1, -1, np.int64)
    seen_first = np.unique(codes_sorted, return_index=True)
    first_pos[seen_first[0]] = seen_first[1]
    graphs: dict = {}
    starts = offsets
    ends = offsets + np.asarray([s.n_traces_total for s in shards])
    # materialize the sorted views ONCE — inside the loop each fancy
    # index would copy all N admitted rows per pattern (O(P*N))
    tid_sorted = tid_a[order]
    uidx_sorted = uidx_a[order]
    for rid in range(len(first_pos)):
        rep_tid = int(tid_sorted[first_pos[rid]])
        si = int(np.searchsorted(ends, rep_tid, side="right"))
        s = shards[si]
        local = rep_tid - int(starts[si])
        u = int(uidx_sorted[first_pos[rid]])
        pid = shard_pid_by_uidx[si].get(u)
        if pid is None or int(s.pat_rep_trace[pid]) != local:
            bus.counter("stream.rebuild", reason="representative_drift")
            raise StreamRebuildRequired(
                "representative_drift",
                f"runtime pattern {rid}: first surviving trace {rep_tid} "
                f"is not the trace its shard built the graph from "
                f"(filters moved the representative)")
        graphs[rid] = s.graphs[pid]

    # -- merged resource lookup + assembly tail -------------------------
    dataset, table = finalize_dataset(
        tid_a, ent_a, runtime, tsb_a, y_a, graphs,
        np.concatenate([s.res_ts for s in shards]),
        np.concatenate([s.res_ms for s in shards]),
        np.concatenate([s.res_values for s in shards]), cfg, bus)
    meta = table.meta

    dt = time.perf_counter() - t0
    bus.histogram("stream.merge_seconds", dt)
    bus.gauge("stream.merged_shards", len(shards))
    bus.gauge("stream.merged_traces", len(meta))
    for i in range(1, len(shards)):
        bus.counter("stream.shard_new_entries", new_entries[i], shard=i)
        bus.counter("stream.shard_new_topologies", new_topologies[i],
                    shard=i)
    if dropped_cov:
        bus.counter("stream.dropped_traces", dropped_cov,
                    reason="coverage")
    if dropped_occ:
        bus.counter("stream.dropped_traces", dropped_occ,
                    reason="occurrence")
    log.info(
        "stream merge: %d shard(s), %d traces (%d dropped by filters), "
        "%d entries, %d patterns in %.2fs",
        len(shards), len(meta), dropped_cov + dropped_occ,
        len(entry_code), len(first_pos), dt)
    info = MergeInfo(shards=info_shards, new_entries=new_entries,
                     new_topologies=new_topologies,
                     dropped_coverage=dropped_cov,
                     dropped_occurrence=dropped_occ,
                     meta=table.meta.iloc[:cfg.data.max_traces])
    return dataset, info
