"""Streaming subsystem: append-only delta arenas, mixture merge,
sliding-window continual training (ROADMAP item 1 — the live-traffic
scenario).  See stream/delta.py for the vocab-stability contract,
stream/merge.py for the bit-identical-merge contract and its loud
rebuild guards, stream/continual.py for warm-restart fine-tuning, and
fleet/rollout.py for the blue/green checkpoint rollout the stream
feeds.  benchmarks/stream_bench.py exit-code-asserts the whole loop."""

from pertgnn_tpu.stream.continual import (check_capacity, finetune_programs,
                                          finetune_round, window_dataset)
from pertgnn_tpu.stream.delta import (ShardDelta, VocabGrowth, base_shard,
                                      ingest_delta, shard_frames_by_window,
                                      vocab_hash)
from pertgnn_tpu.stream.merge import (MergeInfo, StreamRebuildRequired,
                                      merge_shards)
from pertgnn_tpu.stream.store import DeltaArenaStore, shard_cache_key

__all__ = [
    "ShardDelta", "VocabGrowth", "base_shard", "ingest_delta",
    "shard_frames_by_window", "vocab_hash", "MergeInfo",
    "StreamRebuildRequired", "merge_shards", "DeltaArenaStore",
    "shard_cache_key", "check_capacity", "finetune_programs",
    "finetune_round", "window_dataset",
]
