"""Append-only delta arena store: one directory entry per trace shard.

The persistence twin of ``batching/arena_store.py``, with the
invalidation unit shrunk from the whole corpus to ONE SHARD: each entry
is keyed on its own fingerprint (plus the ingest/graph config subtree
that shapes shard content, plus — for deltas — the base vocabulary hash
it was coded against), so a new shard ingests and persists without
touching any existing entry, and a changed shard invalidates itself
alone.  ``stream/merge.py`` then reconstitutes the serving/training
corpus from base + deltas without a full rebuild.

Layout (graftvault, store/durable.py): immutable generation dirs
``<root>/<key>@g<N>/`` holding one ``.npy`` per array and one ``.txt``
(one JSON string per line) per string list plus ``meta.json``,
committed by ONE durable replace of the checksummed
``<root>/<key>.manifest.json`` (which records a CRC32C per file — what
``graftvault scrub`` verifies).  TRUST BOUNDARY: the
same as the arena store — entries are plain arrays, JSON, and text (no
pickle, no code execution at load), but they ARE the training data;
whoever can write this directory controls every later run's features
and labels (docs/GUIDE.md §8).

A corrupt or truncated entry logs a warning, counts a
``stream.shard_cache_miss`` with reason ``corrupt``, and falls back to a
fresh ingest OF THAT SHARD ONLY — the surviving entries stay warm
(tests/test_stream.py pins it).
"""

from __future__ import annotations

import json
import logging
import os
import time

import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.graphs.construct import GraphSpec
from pertgnn_tpu.store import durable
from pertgnn_tpu.store.durable import StoreCorruption, StoreLock
from pertgnn_tpu.stream.delta import ShardDelta

log = logging.getLogger(__name__)

# Bump to orphan every entry on a layout/semantics change (rides fn_id).
# v2: graftvault generation-dir layout with checksummed manifests.
_STORE_VERSION = 2
_FN_ID = f"stream.delta_store.v{_STORE_VERSION}"

_ARRAY_FIELDS = ("traceid", "entry_local", "runtime_local", "ts_bucket",
                 "y", "pat_tokens", "pat_offsets", "pat_rep_trace",
                 "inc_trace", "inc_ms", "res_ts", "res_ms", "res_values")
_STRING_FIELDS = ("traceid_strings", "entry_vocab")
_VOCAB_NAMES = ("ms", "interface", "rpctype", "entryid")


def shard_cache_key(cfg, fingerprint: dict, *, kind: str,
                    base_vocab_hash: str | None) -> tuple[str, dict]:
    """(hex key, components) for one shard entry.  Only what shapes the
    SHARD's content is keyed: the IngestConfig (filters, bucketing,
    aggregations), graph_type (the stored GraphSpecs), the shard's own
    raw-input fingerprint, and — for deltas — the base vocabulary they
    were coded against.  Batch/budget/model knobs shape the MERGED
    dataset, which is derived fresh per merge, never persisted here."""
    from pertgnn_tpu import aot

    config = {"ingest": cfg.ingest, "graph_type": cfg.graph_type}
    args = {"kind": kind, "fingerprint": fingerprint,
            "base_vocab_hash": base_vocab_hash}
    # env={}: shard entries are host artifacts (see arena_cache_key)
    return aot.cache_key(fn_id=_FN_ID, config=config, args_sig=args,
                         env={})


def _read_strings(path: str) -> list[str]:
    # one JSON string per line (EntryWriter.put_text_lines writes the
    # same framing): raw ids can contain anything — newlines, backslash
    # sequences a hand-rolled escape would round-trip wrong
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f]


class DeltaArenaStore:
    """Content-addressed shard entries under ``root``."""

    def __init__(self, root: str, bus=None):
        self.root = root
        self._injected_bus = bus
        os.makedirs(root, exist_ok=True)

    @property
    def _bus(self):
        return (self._injected_bus if self._injected_bus is not None
                else telemetry.get_bus())

    def _entry_dir(self, key: str) -> str | None:
        """The committed generation dir for ``key``, or None when the
        entry is absent.  Raises StoreCorruption on a torn manifest or
        a manifest whose generation dir is gone."""
        resolved = durable.resolve_entry(self.root, key, store="delta")
        return None if resolved is None else resolved[0]

    # -- entry points ----------------------------------------------------

    def load_or_ingest_base(self, cfg, fingerprint: dict,
                            pre_table_fn) -> ShardDelta:
        """The base shard for (cfg, fingerprint): a hit reconstructs it
        from disk; a miss calls ``pre_table_fn()`` (the full batch
        ingest returning (pre, table)) and persists."""
        from pertgnn_tpu.stream.delta import base_shard

        key, components = shard_cache_key(cfg, fingerprint, kind="base",
                                          base_vocab_hash=None)
        shard = self._load(key)
        if shard is not None:
            return shard
        t0 = time.perf_counter()
        pre, table = pre_table_fn()
        shard = base_shard(pre, table, cfg.graph_type, cfg.ingest)
        self._bus.histogram("stream.shard_ingest_seconds",
                            time.perf_counter() - t0)
        self._save(key, components, shard)
        return shard

    def load_or_ingest_delta(self, cfg, fingerprint: dict, frames_fn,
                             base: ShardDelta) -> ShardDelta:
        """One delta shard for (cfg, fingerprint, base): a hit
        reconstructs it from disk; a miss calls ``frames_fn()`` (raw
        (spans, resources) frames for THIS shard only), runs the
        vocab-stable ingest, and persists.  Raises
        stream.delta.VocabGrowth when the shard cannot be coded against
        the base — the caller routes to the rebuild path."""
        from pertgnn_tpu.stream.delta import ingest_delta, vocab_hash

        if base.vocabs is None:
            raise ValueError("load_or_ingest_delta needs the base shard")
        bh = vocab_hash(base.vocabs)
        key, components = shard_cache_key(cfg, fingerprint, kind="delta",
                                          base_vocab_hash=bh)
        shard = self._load(key)
        if shard is not None:
            return shard
        t0 = time.perf_counter()
        spans, resources = frames_fn()
        shard = ingest_delta(spans, resources, base, cfg.graph_type,
                             cfg.ingest)
        self._bus.histogram("stream.shard_ingest_seconds",
                            time.perf_counter() - t0)
        self._save(key, components, shard)
        return shard

    def open_shards(self, keys) -> dict[str, ShardDelta]:
        """Per-host slice open: reconstruct ONLY the listed entries.

        The scale-out path (parallel/scale.py) assigns each delta shard
        to exactly one host; that host calls ``open_shards`` with its
        slice of the assignment so it never mmaps (or copies) entries
        outside it — on a giant corpus the difference between opening
        1/N of the store and all of it IS the scaling win.  Emits
        ``stream.shard_mmap_bytes`` (gauge, per host) — the on-disk
        bytes of every ``.npy`` this call actually opened — so the
        per-host footprint is observable (docs/OBSERVABILITY.md).

        Unlike the load-or-ingest entry points there is no fallback:
        a missing or corrupt entry raises ``KeyError`` — the caller
        owns the assignment and must route to a rebuild, because a
        silently re-ingested shard on one host would diverge from the
        fingerprint the other hosts agreed on.
        """
        bus = self._bus
        shards: dict[str, ShardDelta] = {}
        mmap_bytes = 0
        for key in keys:
            shard = self._load(key)
            if shard is None:
                raise KeyError(
                    f"delta-store entry {key!r} absent or corrupt — "
                    "sharded open has no re-ingest fallback; rebuild "
                    "the assignment")
            shards[key] = shard
            d = self._entry_dir(key)
            for name in os.listdir(d):
                if name.endswith(".npy"):
                    mmap_bytes += os.path.getsize(os.path.join(d, name))
        bus.gauge("stream.shard_mmap_bytes", mmap_bytes)
        total = sum(1 for _ in durable.iter_manifests(self.root))
        log.info("delta store: sharded open of %d/%d entries (%d mmap "
                 "bytes)", len(shards), total, mmap_bytes)
        return shards

    # -- load ------------------------------------------------------------

    def _load(self, key: str) -> ShardDelta | None:
        bus = self._bus
        try:
            d = self._entry_dir(key)
        except StoreCorruption as e:
            # a torn/bit-rotted manifest: never crash the stream — THIS
            # shard re-ingests (graftvault scrub quarantines the entry)
            log.warning("corrupt delta-store manifest for %s (%s) — "
                        "re-ingesting this shard fresh", key, e)
            bus.counter("stream.shard_cache_miss", reason="corrupt")
            return None
        if d is None:
            bus.counter("stream.shard_cache_miss", reason="absent")
            return None
        t0 = time.perf_counter()
        try:
            shard = self._load_entry(d)
        except Exception as e:
            # corrupt/truncated/stale entry: never crash the stream —
            # THIS shard re-ingests, the others stay warm
            log.warning("corrupt delta-store entry %s (%s: %s) — "
                        "re-ingesting this shard fresh", key,
                        type(e).__name__, e)
            bus.counter("stream.shard_cache_miss", reason="corrupt")
            return None
        dt = time.perf_counter() - t0
        bus.counter("stream.shard_cache_hit", kind=shard.kind)
        bus.histogram("stream.shard_load_seconds", dt)
        log.info("delta store: hit %s (%s, %d traces) in %.3fs — shard "
                 "ingest skipped", key, shard.kind, len(shard.traceid), dt)
        return shard

    def _load_entry(self, d: str) -> ShardDelta:
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("store_version") != _STORE_VERSION:
            raise ValueError(f"store version {meta.get('store_version')!r}"
                             f" != {_STORE_VERSION}")

        def arr(name: str):
            return np.load(os.path.join(d, f"{name}.npy"),
                           mmap_mode="r", allow_pickle=False)

        fields = {f: np.asarray(arr(f)) for f in _ARRAY_FIELDS}
        strings = {f: _read_strings(os.path.join(d, f"{f}.txt"))
                   for f in _STRING_FIELDS}
        g_noff = arr("g_node_offsets")
        g_eoff = arr("g_edge_offsets")
        g_send = arr("g_senders")
        g_recv = arr("g_receivers")
        g_attr = arr("g_edge_attr")
        g_ms = arr("g_ms_id")
        g_depth = arr("g_node_depth")
        has_dur = bool(meta["has_edge_durations"])
        g_dur = arr("g_edge_durations") if has_dur else None
        graphs: dict[int, GraphSpec] = {}
        for p in range(len(g_noff) - 1):
            ns, ne = int(g_noff[p]), int(g_noff[p + 1])
            es, ee = int(g_eoff[p]), int(g_eoff[p + 1])
            graphs[p] = GraphSpec(
                senders=np.asarray(g_send[es:ee]),
                receivers=np.asarray(g_recv[es:ee]),
                edge_attr=np.asarray(g_attr[es:ee]),
                ms_id=np.asarray(g_ms[ns:ne]),
                node_depth=np.asarray(g_depth[ns:ne]),
                num_nodes=ne - ns,
                edge_durations=(np.asarray(g_dur[es:ee]) if has_dur
                                else None))
        vocabs = None
        if meta["kind"] == "base":
            vocabs = {n: np.asarray(
                _read_strings(os.path.join(d, f"vocab_{n}.txt")),
                dtype=object) for n in _VOCAB_NAMES}
        s = meta["scalars"]
        return ShardDelta(
            kind=meta["kind"], graphs=graphs,
            traceid_strings=np.asarray(strings["traceid_strings"],
                                       dtype=object),
            entry_vocab=strings["entry_vocab"],
            n_traces_total=s["n_traces_total"],
            span_ts_min=s["span_ts_min"], span_ts_max=s["span_ts_max"],
            vocabs=vocabs,
            entry_occ_prefilter=meta.get("entry_occ_prefilter"),
            base_vocab_hash=meta.get("base_vocab_hash"),
            coverage_dropped=meta.get("coverage_dropped"), **fields)

    # -- save ------------------------------------------------------------

    def _save(self, key: str, components: dict,
              shard: ShardDelta) -> str | None:
        """Durable (store/durable.py), like the arena store: arrays
        land fsync'd in an immutable generation dir and ONE checksummed
        manifest replace commits the entry — a kill mid-write costs one
        shard re-ingest, never a torn entry, and never the old
        double-replace window where the live entry was briefly gone."""
        bus = self._bus
        t0 = time.perf_counter()
        try:
            with StoreLock(os.path.join(self.root, ".lock"),
                           store="delta", bus=bus), \
                    durable.EntryWriter(self.root, key, store="delta",
                                        bus=bus) as w:
                final = self._save_entry(w, key, components, shard)
        except Exception as e:
            # a failed save must not fail the run the shard was built
            # FOR — next process re-ingests
            log.warning("delta store: could not persist %s (%s: %s)",
                        key, type(e).__name__, e)
            return None
        bus.histogram("stream.shard_save_seconds",
                      time.perf_counter() - t0)
        log.info("delta store: saved %s (%s, %d traces, %d patterns)",
                 key, shard.kind, len(shard.traceid), shard.num_patterns)
        return final

    def _save_entry(self, w, key: str, components: dict,
                    shard: ShardDelta) -> str:
        def put(name: str, a) -> None:
            w.put_array(f"{name}.npy", a)

        for f in _ARRAY_FIELDS:
            put(f, getattr(shard, f))
        for f in _STRING_FIELDS:
            w.put_text_lines(f"{f}.txt", getattr(shard, f))
        P = shard.num_patterns
        noff = [0]
        eoff = [0]
        send, recv, attr, ms, depth, dur = [], [], [], [], [], []
        has_dur = any(shard.graphs[p].edge_durations is not None
                      for p in range(P))
        for p in range(P):
            g = shard.graphs[p]
            noff.append(noff[-1] + g.num_nodes)
            eoff.append(eoff[-1] + g.num_edges)
            send.append(g.senders)
            recv.append(g.receivers)
            attr.append(g.edge_attr)
            ms.append(g.ms_id)
            depth.append(g.node_depth)
            if has_dur:
                dur.append(g.edge_durations
                           if g.edge_durations is not None
                           else np.zeros(g.num_edges, np.float32))
        attr_w = shard.graphs[0].edge_attr.shape[1] if P else 2
        put("g_node_offsets", np.asarray(noff, np.int64))
        put("g_edge_offsets", np.asarray(eoff, np.int64))
        put("g_senders", np.concatenate(send)
            if P else np.empty(0, np.int32))
        put("g_receivers", np.concatenate(recv)
            if P else np.empty(0, np.int32))
        put("g_edge_attr", np.concatenate(attr)
            if P else np.empty((0, attr_w), np.int32))
        put("g_ms_id", np.concatenate(ms)
            if P else np.empty(0, np.int32))
        put("g_node_depth", np.concatenate(depth)
            if P else np.empty(0, np.float32))
        if has_dur:
            put("g_edge_durations", np.concatenate(dur))
        if shard.vocabs is not None:
            for n in _VOCAB_NAMES:
                w.put_text_lines(f"vocab_{n}.txt",
                                 np.asarray(shard.vocabs[n]).tolist())
        meta = {
            "key": key, "kind": shard.kind,
            "store_version": _STORE_VERSION,
            "created_unix_time": time.time(),
            "has_edge_durations": has_dur,
            "scalars": {"n_traces_total": shard.n_traces_total,
                        "span_ts_min": shard.span_ts_min,
                        "span_ts_max": shard.span_ts_max},
            "entry_occ_prefilter": shard.entry_occ_prefilter,
            "base_vocab_hash": shard.base_vocab_hash,
            "coverage_dropped": shard.coverage_dropped,
            **components,
        }
        return w.commit(meta)
