"""Sliding-window continual training: warm-restart fine-tuning.

One continual round = (1) reconstitute the merged corpus from the delta
arena store (zero ingest when the shards are cached), (2) swap the train
split for the sliding window of recent shards (``MergeInfo.window_split``),
(3) warm-restart ``fit()`` from the latest checkpoint for a few epochs.
Because the programs resolve through the AOT executable store and the
data through the delta store, restart-to-first-step is seconds — the
``ttfs_s`` row fit() already emits is the metric, and
benchmarks/stream_bench.py exit-code-asserts the structural evidence
(zero shard ingests, zero AOT store misses) in a REAL fresh process.

Drift gauges on the bus (docs/OBSERVABILITY.md): the merge emits
``stream.shard_new_entries`` / ``stream.shard_new_topologies`` per
shard; this module adds ``stream.finetune_window`` (examples in the
window) and ``stream.qloss_drift`` — the relative quantile-loss drift of
the refreshed model on the FROZEN base eval split, the one number an
operator alarms on before rolling the checkpoint out
(fleet/rollout.py).

Model-capacity contract: the string vocabularies are pinned
(stream/delta.py), so ``num_ms`` / ``num_interfaces`` / ``num_rpctypes``
cannot outgrow the checkpoint; NEW ENTRIES can.  With
``ModelConfig.vocab_headroom_entries`` the entry embedding is sized to a
capacity window (models/pert_model.entry_capacity) and growth within it
warm-restarts cleanly; growth past it — or any growth with headroom 0 —
raises :class:`~pertgnn_tpu.stream.merge.StreamRebuildRequired` naming
the grown dimension, because silently re-initializing embeddings under a
serving fleet is the bug this check exists to prevent.
"""

from __future__ import annotations

import dataclasses
import logging

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.dataset import Dataset, Split
from pertgnn_tpu.config import Config
from pertgnn_tpu.stream.merge import StreamRebuildRequired

log = logging.getLogger(__name__)


def window_dataset(dataset: Dataset, window: Split,
                   frozen_eval: dict[str, Split]) -> Dataset:
    """The merged dataset with the train split replaced by the sliding
    window and valid/test pinned to the FROZEN base eval splits (drift
    must be measured against a fixed yardstick — a positional split
    over the grown corpus would move with every shard).  Feature-arena
    and device-arena caches reset: they are split-shaped."""
    return dataclasses.replace(
        dataset,
        splits={"train": window, "valid": frozen_eval["valid"],
                "test": frozen_eval["test"]},
        _feat_all=None, _feat_slices={}, _epoch_cache={},
        _device_arenas=None)


def check_capacity(dataset: Dataset, cfg: Config,
                   checkpoint_vocab: dict | None) -> None:
    """Refuse (loudly) to warm-restart onto embeddings the merged corpus
    has outgrown.  `checkpoint_vocab` is the vocab-size dict the
    checkpointed model was built with ({num_ms, num_entries,
    num_interfaces, num_rpctypes}); None skips the check (orbax then
    fails on the shape mismatch, just less helpfully)."""
    if checkpoint_vocab is None:
        return
    from pertgnn_tpu.models.pert_model import entry_capacity

    h = cfg.model.vocab_headroom_entries
    grown = []
    if (entry_capacity(dataset.num_entries, h)
            != entry_capacity(int(checkpoint_vocab["num_entries"]), h)):
        grown.append(
            f"num_entries {checkpoint_vocab['num_entries']} -> "
            f"{dataset.num_entries} (capacity multiple "
            f"vocab_headroom_entries={h})")
    for dim in ("num_ms", "num_interfaces", "num_rpctypes"):
        if getattr(dataset, dim) > int(checkpoint_vocab[dim]):
            grown.append(f"{dim} {checkpoint_vocab[dim]} -> "
                         f"{getattr(dataset, dim)}")
    if grown:
        raise StreamRebuildRequired(
            "model_capacity",
            "merged corpus outgrew the checkpointed embeddings ("
            + "; ".join(grown) + ") — cold-retrain on the merged corpus "
            "(and consider --vocab_headroom_entries so future new "
            "entries land in pre-allocated rows)")


def finetune_round(dataset: Dataset, window: Split,
                   frozen_eval: dict[str, Split], cfg: Config,
                   checkpoint_dir: str, *, bus=None,
                   baseline_qloss: float | None = None,
                   checkpoint_vocab: dict | None = None):
    """One warm-restart fine-tune round.  Returns (state, history).

    Restores the LATEST checkpoint in `checkpoint_dir` (refusing to run
    cold — a continual round without a checkpoint is a configuration
    error, not a silent full train), trains
    ``cfg.stream.finetune_epochs`` epochs on the window, checkpoints,
    and emits the drift gauges."""
    from pertgnn_tpu.train.checkpoint import CheckpointManager
    from pertgnn_tpu.train.loop import fit

    bus = bus if bus is not None else telemetry.get_bus()
    check_capacity(dataset, cfg, checkpoint_vocab)
    ds = window_dataset(dataset, window, frozen_eval)
    ckpt = CheckpointManager(checkpoint_dir, keep=cfg.train.checkpoint_keep)
    latest = ckpt.latest_step()
    if latest is None:
        raise ValueError(
            f"no checkpoint in {checkpoint_dir!r} to warm-restart from — "
            f"train the base model first (continual rounds fine-tune, "
            f"they never cold-start)")
    start = latest + 1
    epochs = start + max(1, cfg.stream.finetune_epochs)
    bus.gauge("stream.finetune_window", len(window))
    log.info("continual round: warm restart from epoch %d, %d window "
             "example(s), %d fine-tune epoch(s)", latest, len(window),
             epochs - start)
    state, history = fit(ds, cfg, epochs=epochs, checkpoint_manager=ckpt,
                         bus=bus)
    if history and baseline_qloss is not None and baseline_qloss > 0:
        q = history[-1]["valid_qloss"]
        drift = (q - baseline_qloss) / baseline_qloss
        bus.gauge("stream.qloss_drift", drift, qloss=q,
                  baseline=baseline_qloss)
        log.info("continual round: frozen-eval qloss %.4f vs baseline "
                 "%.4f (drift %+.2f%%)", q, baseline_qloss, drift * 100)
    return state, history


def finetune_programs(dataset: Dataset, cfg: Config):
    """(model, state, train_jit, eval_jit, compact) — the programs one
    continual round dispatches for `dataset` (the window dataset), built
    through fit()'s OWN construction path (build_single_device_programs'
    maker selection) with the AOT store side effects off.  Exposed so
    tools/graftaudit/programs.py can trace the continual-training
    program as a first-class audit subject (``continual/finetune_*``):
    if continual training ever diverges from fit()'s construction, the
    audit coverage pin in tests/test_graftaudit.py breaks."""
    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_resolve_device_materialize,
                                        _train_sample,
                                        build_single_device_programs,
                                        make_tx)

    cfg = cfg.replace(aot=dataclasses.replace(cfg.aot, cache_dir=""))
    model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                       dataset.num_interfaces, dataset.num_rpctypes)
    tx = make_tx(cfg)
    sample = _train_sample(dataset)
    compact = _resolve_device_materialize(dataset, cfg)
    state, train_jit, eval_jit = build_single_device_programs(
        dataset, cfg, model=model, tx=tx, sample=sample,
        device_materialize=compact)
    return model, state, train_jit, eval_jit, compact
