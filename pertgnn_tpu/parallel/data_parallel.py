"""Data-parallel training over a device mesh.

D per-device packed batches are concatenated into ONE global batch (graph /
node / edge ids offset so segments stay disjoint) whose arrays are sharded on
their leading dimension over the `data` axis. The train step is the same
single jitted program as single-chip training — the loss mean, metric sums,
and BatchNorm statistics aggregate over the global batch, so the SPMD
partitioner inserts the psum/all-reduce collectives over ICI itself. This
replaces what a GPU scale-out of the reference would have done with
DDP/NCCL (SURVEY.md §5.8; BASELINE config 3).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Sequence

import jax
import numpy as np
import optax

from pertgnn_tpu.batching.arena import (CompactBatch, IndexBatch,
                                        zero_masked_compact)
from pertgnn_tpu.batching.materialize import (DeviceArenas,
                                              materialize_compact_sharded)
from pertgnn_tpu.batching.pack import (PackedBatch, receiver_sort_edges,
                                        zero_masked)
from pertgnn_tpu.config import Config
from pertgnn_tpu.models.pert_model import PertGNN
from pertgnn_tpu.parallel.mesh import (batch_shardings,
                                       chunk_batch_shardings,
                                       index_batch_shardings,
                                       place_state,
                                       replicated_batch_shardings,
                                       state_shardings)
from pertgnn_tpu.train import loop as train_loop


def stack_batches(batches: Sequence[PackedBatch]) -> PackedBatch:
    """Concatenate equal-shape packed batches into one global batch.

    Node ids in senders/receivers and graph ids in node_graph are offset per
    shard; pad nodes keep pointing at their shard's pad graph slot, so
    per-shard padding stays inert in the global program.
    """
    n = batches[0].x.shape[0]
    g = batches[0].num_graphs
    for b in batches:
        if b.x.shape[0] != n or b.num_graphs != g:
            raise ValueError("stack_batches requires equal-shape batches")
    out = {}
    for field in PackedBatch._fields:
        parts = []
        for d, b in enumerate(batches):
            a = getattr(b, field)
            if field in ("senders", "receivers"):
                a = a + d * n
            elif field == "node_graph":
                a = a + d * g
            parts.append(a)
        out[field] = np.concatenate(parts)
    # Restore the PackedBatch receiver-sorted invariant (pack.py): the
    # concatenation interleaves each shard's pad-edge tail between shards'
    # sorted runs, which would silently break the Pallas kernel's
    # searchsorted block-skipping on the global edge array.
    return PackedBatch(**receiver_sort_edges(out, n * len(batches)))


def _grouped(stream: Iterator, num_shards: int, stacker: Callable,
             filler: Callable) -> Iterator:
    """Group a per-shard stream into `num_shards`-wide global items; the
    tail group is completed with inert `filler` clones of its last item so
    every global item has identical shape."""
    group: list = []
    for b in stream:
        group.append(b)
        if len(group) == num_shards:
            yield stacker(group)
            group = []
    if group:
        pad = filler(group[-1])
        while len(group) < num_shards:
            group.append(pad)
        yield stacker(group)


def grouped_batches(batches: Iterator[PackedBatch],
                    num_shards: int) -> Iterator[PackedBatch]:
    """Group a batch stream into global batches of `num_shards` shards."""
    return _grouped(batches, num_shards, stack_batches, zero_masked)


def stack_index_batches(idxs: Sequence[IndexBatch]) -> IndexBatch:
    """Concatenate equal-shape gather recipes into one global recipe.

    The IndexBatch analog of `stack_batches`: graph slots (node_graph) and
    node offsets (edge_node_off) are offset per shard so the materialized
    global PackedBatch has disjoint node/graph segments per shard. Arena
    indices (src_*) are untouched — the arenas are replicated over the mesh.
    No edge re-sort (order-free segment attention; the Pallas kernel's
    sorted-edge fast path is not mesh-capable — RESULTS.md).

    This is the HOST ORACLE for the production O(graphs) path: the
    shard-local device expansion (materialize.expand_compact_sharded) is
    parity-tested against it field-for-field (tests/test_parallel.py)."""
    n = idxs[0].src_node.shape[0]
    g = idxs[0].num_graphs
    for b in idxs:
        if b.src_node.shape[0] != n or b.num_graphs != g:
            raise ValueError(
                "stack_index_batches requires equal-shape recipes")
    out = {}
    for field in IndexBatch._fields:
        parts = []
        for d, b in enumerate(idxs):
            a = getattr(b, field)
            if field == "node_graph":
                a = a + d * g
            elif field == "edge_node_off":
                # pad edges (src_edge == sentinel) get a real-node offset
                # here; they stay inert because edge_mask is recovered from
                # src_edge on device (materialize_device)
                a = a + d * n
            parts.append(a)
        out[field] = np.concatenate(parts)
    return IndexBatch(**out)


def stack_compact_batches(cbs: Sequence[CompactBatch]) -> CompactBatch:
    """Concatenate per-shard compact recipes into one global recipe.

    NO offsets here — the per-shard graph/node offsets are added on device
    by the shard-local expansion (materialize.expand_compact_sharded uses
    axis_index), so single-host and multi-host stacking are the same plain
    concat."""
    return CompactBatch(*(np.concatenate([getattr(b, f) for b in cbs])
                          for f in CompactBatch._fields))


def grouped_compact_batches(cbs: Iterator[CompactBatch],
                            num_shards: int) -> Iterator[CompactBatch]:
    """Group a compact-recipe stream into global recipes."""
    return _grouped(cbs, num_shards, stack_compact_batches,
                    zero_masked_compact)


def shard_batch(batch: PackedBatch, mesh,
                shardings: PackedBatch | None = None) -> PackedBatch:
    """Place a host batch directly into its mesh sharding (no device-0 hop).

    Pass `shardings=batch_shardings(mesh)` precomputed when calling per step.
    """
    if shardings is None:
        if isinstance(batch, IndexBatch):
            shardings = index_batch_shardings(mesh)
        elif isinstance(batch, CompactBatch):
            shardings = compact_batch_shardings(mesh)
        else:
            shardings = batch_shardings(mesh)
    return jax.tree.map(
        jax.device_put, batch, shardings,
        is_leaf=lambda x: isinstance(x, np.ndarray))


def make_sharded_train_step(model: PertGNN, cfg: Config,
                            tx: optax.GradientTransformation, mesh,
                            state) -> tuple[Callable, Any]:
    """The single-chip train step (train/loop.py `train_step_fn` — one source
    of truth) jitted with mesh shardings.

    Returns (step_fn, sharded_state): state placed according to the
    tensor-parallel rule, batch expected sharded over `data`.
    """
    st_sh = state_shardings(state, mesh)
    b_sh = batch_shardings(mesh)
    state = place_state(state, st_sh)
    jitted = jax.jit(train_loop.train_step_fn(model, cfg, tx),
                     in_shardings=(st_sh, b_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_sharded_eval_step(model: PertGNN, cfg: Config, mesh,
                           state) -> Callable:
    st_sh = state_shardings(state, mesh)
    b_sh = batch_shardings(mesh)
    return jax.jit(train_loop.eval_step_fn(model, cfg),
                   in_shardings=(st_sh, b_sh), out_shardings=None)


def make_sharded_train_chunk(model: PertGNN, cfg: Config,
                             tx: optax.GradientTransformation, mesh,
                             state) -> tuple[Callable, Any]:
    """Scan-fused sharded stepping: `scan_chunk` global-batch steps in ONE
    dispatched SPMD program (loop.train_chunk_fn jitted with mesh
    shardings). The chunk's leading axis is the scan dim; each slice is a
    global batch sharded over `data`. Same dispatch-amortization win as the
    single-chip path — one launch per K steps instead of K.

    Returns (chunk_fn, sharded_state)."""
    st_sh = state_shardings(state, mesh)
    cb_sh = chunk_batch_shardings(mesh)
    state = place_state(state, st_sh)
    jitted = jax.jit(train_loop.train_chunk_fn(model, cfg, tx),
                     in_shardings=(st_sh, cb_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_sharded_eval_chunk(model: PertGNN, cfg: Config, mesh,
                            state) -> Callable:
    st_sh = state_shardings(state, mesh)
    cb_sh = chunk_batch_shardings(mesh)
    return jax.jit(train_loop.eval_chunk_fn(model, cfg),
                   in_shardings=(st_sh, cb_sh), out_shardings=None)


def _compact_shardings(mesh, chunked: bool):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pertgnn_tpu.parallel.mesh import DATA_AXIS
    s = NamedSharding(mesh, P(None, DATA_AXIS) if chunked else P(DATA_AXIS))
    return CompactBatch(*([s] * len(CompactBatch._fields)))


def compact_batch_shardings(mesh) -> CompactBatch:
    """Graph-dim `data` sharding for a global compact recipe."""
    return _compact_shardings(mesh, chunked=False)


def chunk_compact_batch_shardings(mesh) -> CompactBatch:
    return _compact_shardings(mesh, chunked=True)


def make_sharded_train_step_compact(model: PertGNN, cfg: Config,
                                    tx: optax.GradientTransformation, mesh,
                                    state, dev: DeviceArenas,
                                    max_nodes: int, max_edges: int,
                                    chunked: bool = False
                                    ) -> tuple[Callable, Any]:
    """O(graphs) SPMD stepping: the per-step transfer is the global
    compact recipe (graph dim sharded over `data`); the SPMD program
    expands each shard's block locally (shard_map + axis_index offsets)
    and materializes the global batch from mesh-replicated arenas.
    `max_nodes`/`max_edges` are PER-SHARD budgets."""
    from pertgnn_tpu.parallel.mesh import DATA_AXIS
    st_sh = state_shardings(state, mesh)
    c_sh = _compact_shardings(mesh, chunked)
    state = place_state(state, st_sh)
    base = train_loop.train_step_fn(model, cfg, tx)
    step = lambda s, c: base(s, materialize_compact_sharded(
        dev, c, max_nodes, max_edges, mesh, DATA_AXIS))
    fn = train_loop._train_chunk_from_step(step) if chunked else step
    jitted = jax.jit(fn, in_shardings=(st_sh, c_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_sharded_eval_step_compact(model: PertGNN, cfg: Config, mesh,
                                   state, dev: DeviceArenas,
                                   max_nodes: int, max_edges: int,
                                   chunked: bool = False) -> Callable:
    from pertgnn_tpu.parallel.mesh import DATA_AXIS
    st_sh = state_shardings(state, mesh)
    c_sh = _compact_shardings(mesh, chunked)
    base = train_loop.eval_step_fn(model, cfg)
    step = lambda s, c: base(s, materialize_compact_sharded(
        dev, c, max_nodes, max_edges, mesh, DATA_AXIS))
    fn = train_loop._eval_chunk_from_step(step) if chunked else step
    return jax.jit(fn, in_shardings=(st_sh, c_sh), out_shardings=None)


def make_edge_sharded_train_step(model: PertGNN, cfg: Config,
                                 tx: optax.GradientTransformation, mesh,
                                 state, chunked: bool = False
                                 ) -> tuple[Callable, Any]:
    """Giant-graph mode (ParallelConfig.shard_edges): the model was built
    with `edge_shard_mesh`, so its attention layers shard the EDGE set over
    the mesh's `data` axis internally (graph_shard.sharded_edge_attention,
    psum/pmax over ICI); batch and node arrays stay replicated. `chunked`
    jits the scan-fused chunk instead of the single step."""
    st_sh = state_shardings(state, mesh)
    b_sh = replicated_batch_shardings(mesh)
    state = place_state(state, st_sh)
    fn = (train_loop.train_chunk_fn(model, cfg, tx) if chunked
          else train_loop.train_step_fn(model, cfg, tx))
    jitted = jax.jit(fn, in_shardings=(st_sh, b_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_edge_sharded_eval_step(model: PertGNN, cfg: Config, mesh,
                                state, chunked: bool = False) -> Callable:
    st_sh = state_shardings(state, mesh)
    b_sh = replicated_batch_shardings(mesh)
    fn = (train_loop.eval_chunk_fn(model, cfg) if chunked
          else train_loop.eval_step_fn(model, cfg))
    return jax.jit(fn, in_shardings=(st_sh, b_sh), out_shardings=None)
