"""Data-parallel training over a device mesh.

D per-device packed batches are concatenated into ONE global batch (graph /
node / edge ids offset so segments stay disjoint) whose arrays are sharded on
their leading dimension over the `data` axis. The train step is the same
single jitted program as single-chip training — the loss mean, metric sums,
and BatchNorm statistics aggregate over the global batch, so the SPMD
partitioner inserts the psum/all-reduce collectives over ICI itself. This
replaces what a GPU scale-out of the reference would have done with
DDP/NCCL (SURVEY.md §5.8; BASELINE config 3).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pertgnn_tpu.batching.pack import (PackedBatch, receiver_sort_edges,
                                        zero_masked)
from pertgnn_tpu.config import Config
from pertgnn_tpu.models.pert_model import PertGNN
from pertgnn_tpu.parallel.mesh import (batch_shardings,
                                       chunk_batch_shardings,
                                       state_shardings)
from pertgnn_tpu.train import loop as train_loop


def stack_batches(batches: Sequence[PackedBatch]) -> PackedBatch:
    """Concatenate equal-shape packed batches into one global batch.

    Node ids in senders/receivers and graph ids in node_graph are offset per
    shard; pad nodes keep pointing at their shard's pad graph slot, so
    per-shard padding stays inert in the global program.
    """
    n = batches[0].x.shape[0]
    g = batches[0].num_graphs
    for b in batches:
        if b.x.shape[0] != n or b.num_graphs != g:
            raise ValueError("stack_batches requires equal-shape batches")
    out = {}
    for field in PackedBatch._fields:
        parts = []
        for d, b in enumerate(batches):
            a = getattr(b, field)
            if field in ("senders", "receivers"):
                a = a + d * n
            elif field == "node_graph":
                a = a + d * g
            parts.append(a)
        out[field] = np.concatenate(parts)
    # Restore the PackedBatch receiver-sorted invariant (pack.py): the
    # concatenation interleaves each shard's pad-edge tail between shards'
    # sorted runs, which would silently break the Pallas kernel's
    # searchsorted block-skipping on the global edge array.
    return PackedBatch(**receiver_sort_edges(out, n * len(batches)))


def grouped_batches(batches: Iterator[PackedBatch],
                    num_shards: int) -> Iterator[PackedBatch]:
    """Group a batch stream into global batches of `num_shards` shards.

    The tail is completed by repeating the last batch with its masks zeroed
    (pure padding), so every global batch has identical shape.
    """
    group: list[PackedBatch] = []
    for b in batches:
        group.append(b)
        if len(group) == num_shards:
            yield stack_batches(group)
            group = []
    if group:
        pad = zero_masked(group[-1])
        while len(group) < num_shards:
            group.append(pad)
        yield stack_batches(group)


def shard_batch(batch: PackedBatch, mesh,
                shardings: PackedBatch | None = None) -> PackedBatch:
    """Place a host batch directly into its mesh sharding (no device-0 hop).

    Pass `shardings=batch_shardings(mesh)` precomputed when calling per step.
    """
    if shardings is None:
        shardings = batch_shardings(mesh)
    return jax.tree.map(
        jax.device_put, batch, shardings,
        is_leaf=lambda x: isinstance(x, np.ndarray))


def make_sharded_train_step(model: PertGNN, cfg: Config,
                            tx: optax.GradientTransformation, mesh,
                            state) -> tuple[Callable, Any]:
    """The single-chip train step (train/loop.py `train_step_fn` — one source
    of truth) jitted with mesh shardings.

    Returns (step_fn, sharded_state): state placed according to the
    tensor-parallel rule, batch expected sharded over `data`.
    """
    st_sh = state_shardings(state, mesh)
    b_sh = batch_shardings(mesh)
    # copy before placement: device_put may alias the caller's buffers, and
    # the donated step would otherwise delete the caller's state arrays
    state = jax.device_put(jax.tree.map(jnp.copy, state), st_sh)
    jitted = jax.jit(train_loop.train_step_fn(model, cfg, tx),
                     in_shardings=(st_sh, b_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_sharded_eval_step(model: PertGNN, cfg: Config, mesh,
                           state) -> Callable:
    st_sh = state_shardings(state, mesh)
    b_sh = batch_shardings(mesh)
    return jax.jit(train_loop.eval_step_fn(model, cfg),
                   in_shardings=(st_sh, b_sh), out_shardings=None)


def make_sharded_train_chunk(model: PertGNN, cfg: Config,
                             tx: optax.GradientTransformation, mesh,
                             state) -> tuple[Callable, Any]:
    """Scan-fused sharded stepping: `scan_chunk` global-batch steps in ONE
    dispatched SPMD program (loop.train_chunk_fn jitted with mesh
    shardings). The chunk's leading axis is the scan dim; each slice is a
    global batch sharded over `data`. Same dispatch-amortization win as the
    single-chip path — one launch per K steps instead of K.

    Returns (chunk_fn, sharded_state)."""
    st_sh = state_shardings(state, mesh)
    cb_sh = chunk_batch_shardings(mesh)
    state = jax.device_put(jax.tree.map(jnp.copy, state), st_sh)
    jitted = jax.jit(train_loop.train_chunk_fn(model, cfg, tx),
                     in_shardings=(st_sh, cb_sh),
                     out_shardings=(st_sh, None), donate_argnums=0)
    return jitted, state


def make_sharded_eval_chunk(model: PertGNN, cfg: Config, mesh,
                            state) -> Callable:
    st_sh = state_shardings(state, mesh)
    cb_sh = chunk_batch_shardings(mesh)
    return jax.jit(train_loop.eval_chunk_fn(model, cfg),
                   in_shardings=(st_sh, cb_sh), out_shardings=None)
