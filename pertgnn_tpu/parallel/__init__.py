from pertgnn_tpu.parallel.mesh import (
    make_mesh,
    batch_shardings,
    param_shardings,
    place_state,
    replicated_sharding,
    state_shardings,
)
from pertgnn_tpu.parallel.data_parallel import (
    stack_batches,
    stack_compact_batches,
    shard_batch,
    make_sharded_train_step,
    make_sharded_eval_step,
    make_sharded_train_step_compact,
    make_sharded_eval_step_compact,
    make_edge_sharded_train_step,
    make_edge_sharded_eval_step,
    grouped_batches,
    grouped_compact_batches,
    compact_batch_shardings,
)
from pertgnn_tpu.parallel.graph_shard import sharded_edge_attention
from pertgnn_tpu.parallel.multihost import (
    initialize as initialize_distributed,
    assemble_global,
    host_grouped_batches,
    host_grouped_compact_batches,
    process_shard_slice,
    put_replicated,
)
