from pertgnn_tpu.parallel.mesh import (
    make_mesh,
    batch_shardings,
    param_shardings,
    state_shardings,
)
from pertgnn_tpu.parallel.data_parallel import (
    stack_batches,
    shard_batch,
    make_sharded_train_step,
    make_sharded_eval_step,
    grouped_batches,
)
from pertgnn_tpu.parallel.graph_shard import sharded_edge_attention
