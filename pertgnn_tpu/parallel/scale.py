"""Giant-corpus scale-out: per-host sharded delta arenas + SAR training.

Two halves, one regime (ISSUE 18 — the corpus no longer fits one host):

**Per-host sharded delta arenas.**  Delta shards are assigned to hosts
deterministically — sorted by the SAME content key the single-host merge
uses to canonically order them (``stream.merge.canonical_key``), then
round-robin — so every host derives the identical assignment from shard
content alone, with no coordinator.  Each host opens ONLY its slice of
the stream store (``DeltaArenaStore.open_shards``), computes per-shard
partial statistics, and the corpus-global statistics merge via REAL
collectives over the existing mesh's ``data`` axis (psum for occurrence
/ coverage counts, pmin for first-appearance trace ids) instead of a
single-host rebuild.  The merged dataset is pinned BIT-IDENTICAL to the
single-host ``stream/merge.py`` oracle (tests/test_scale.py,
benchmarks/scale_bench.py): both paths run the same factored phases
(``entry_union``, ``pattern_union``, guard checks, assembly tail) on the
same summaries; only the numeric reductions travel a different route,
and integer psum/pmin are order-exact.

**SAR-style rematerialized training** (after SAR, arXiv:2111.06483):
entry mixtures larger than one device's memory train as a sequential
aggregation over TOPOLOGY BUCKETS — the epoch's packed mixture batches,
grouped into a fixed-capacity leading-stacked pytree — with gradient
accumulation expressed as ``jax.grad`` of a ``lax.scan`` over the
buckets.  The scan carries the pinball numerator and mask count per
term (``quantile_loss_sums``) and divides ONCE after the scan, so the
accumulated gradient is the gradient of the same scalar loss whether or
not the per-bucket body is rematerialized.  With ``remat=True`` the
bucket body runs under ``jax.checkpoint``: XLA stores O(1 bucket) of
residuals and recomputes per-bucket activations on the backward pass —
peak memory is bounded by ONE bucket instead of the whole mixture
(asserted via ``device.mem.peak_bytes`` on chips and the compiled
program's temp-buffer analysis in CI).  The checkpoint policy is NOT
``nothing_saveable``: recomputed values are only bit-identical to the
stored forward when every op whose result depends on evaluation detail
— transcendental approximations (fusion-context-dependent codegen) and
multi-element reductions/scatters (accumulation order) — is SAVED
rather than replayed (:data:`BIT_STABLE_SAVE`,
:func:`bit_stable_policy`).  Everything else (gathers, adds, muls,
selects, broadcasts — the bulk of the residual footprint) recomputes
exactly.  ``remat=False`` is the aggregation-held monolithic twin: the
SAME arithmetic, residuals for all buckets held live — its gradients
are the tolerance-0 reference (benchmarks/scale_bench.py asserts
bit-equivalence in f32).  Dead
(all-masked padding) buckets skip under ``lax.cond``, so the bucket
CAPACITY is a compile-time constant while the LIVE count varies freely
— zero fresh compiles across bucket counts, and donation
(``donate_argnums=0``) is preserved because the accumulated step is one
jitted ``(state, buckets) -> (state, metrics)`` program like every
other train step.

Refusals (docs/RELIABILITY.md): hosts whose derived assignments
disagree raise :class:`HostAssignmentMismatch` (counter
``scale.host_assignment_mismatch``) before any partial statistics are
computed — a half-sharded merge would be silently wrong; a mixture that
needs more buckets than the configured capacity raises
:class:`AccumulationOverflow` (counter ``scale.accum_overflow``)
instead of truncating the epoch; and every situation the single-host
merge refuses (``StreamRebuildRequired``) refuses identically here —
the guards are the same code.
"""

from __future__ import annotations

import functools
import hashlib
import logging
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.pack import PackedBatch, zero_masked
from pertgnn_tpu.config import Config
from pertgnn_tpu.models.pert_model import PertGNN
from pertgnn_tpu.parallel.mesh import DATA_AXIS
from pertgnn_tpu.stream.delta import ShardDelta, vocab_hash
from pertgnn_tpu.stream.merge import (MergeInfo, StreamRebuildRequired,
                                      canonical_key, check_coverage_drift,
                                      check_ordering, check_trace_disjoint,
                                      coverage_mask, entry_union,
                                      finalize_dataset, pattern_union)
from pertgnn_tpu.train.loop import (TrainState, _METRIC_KEYS,
                                    _resolved_taus)
from pertgnn_tpu.train.metrics import (masked_metric_sums,
                                       quantile_loss_sums)

log = logging.getLogger(__name__)

# pmin identity for global trace ids (int32 — a corpus would need >2.1B
# traces to overflow, far past this repo's regime)
_INT_INF = np.iinfo(np.int32).max


class HostAssignmentMismatch(RuntimeError):
    """Two hosts derived different shard-to-host assignments — their
    views of the delta store disagree (stale listing, partial sync).
    Merging would double- or zero-count shards; refuse before any
    statistics are computed.  Counter: ``scale.host_assignment_mismatch``."""


class AccumulationOverflow(RuntimeError):
    """The mixture needs more topology buckets than the configured
    capacity (``ScaleConfig.accum_buckets``) — truncating would silently
    train on a subset.  Raise the flag or shrink the batch budget.
    Counter: ``scale.accum_overflow``."""


# -- shard-to-host assignment --------------------------------------------

def assign_shards(deltas: list[ShardDelta], num_hosts: int
                  ) -> list[list[int]]:
    """host -> indices into `deltas` (the CALLER's order), derived from
    shard content alone: canonical-key sort, then round-robin.  A pure
    function of the shard SET — permutation-invariant in the input
    order, so every host computes the identical assignment without
    coordination (hypothesis-pinned in tests/test_scale.py)."""
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    order = sorted(range(len(deltas)), key=lambda i: canonical_key(deltas[i]))
    out: list[list[int]] = [[] for _ in range(num_hosts)]
    for rank, i in enumerate(order):
        out[rank % num_hosts].append(i)
    return out


def assignment_fingerprint(deltas: list[ShardDelta],
                           num_hosts: int) -> str:
    """Content hash of the full assignment as THIS host derives it.
    Hosts exchange fingerprints and cross-check (verify_assignment)
    before computing partials — the cheap proof their store views
    agree."""
    h = hashlib.sha256()
    h.update(str(num_hosts).encode())
    for host_slice in assign_shards(deltas, num_hosts):
        h.update(b"|host|")
        for i in host_slice:
            h.update(repr(canonical_key(deltas[i])).encode())
    return h.hexdigest()[:16]


def verify_assignment(fingerprints: list[str], bus=None) -> None:
    """Refuse (HostAssignmentMismatch) unless every host's assignment
    fingerprint agrees."""
    distinct = sorted(set(fingerprints))
    if len(distinct) > 1:
        bus = bus if bus is not None else telemetry.get_bus()
        bus.counter("scale.host_assignment_mismatch",
                    hosts=len(fingerprints), distinct=len(distinct))
        raise HostAssignmentMismatch(
            f"{len(fingerprints)} host(s) derived {len(distinct)} "
            f"different shard assignments ({distinct}) — store views "
            f"disagree; re-sync the delta store before merging")


# -- collective statistics rounds ----------------------------------------

def allreduce_fn(mesh: Mesh, op: str) -> Callable:
    """One statistics round as a shard_map'd collective kernel over the
    mesh's ``data`` axis: input is a (slots, K) stack of per-slot
    partials sharded on dim 0; each device folds its local slot then
    psum ("sum") or pmin ("min") completes the global (K,) statistic,
    replicated.  Exposed standalone so graftaudit traces exactly the
    program the merge runs (collective-audit: the only axis name used
    is a mesh axis)."""
    if op not in ("sum", "min"):
        raise ValueError(f"op must be 'sum' or 'min', got {op!r}")

    def f(x):
        local = x.sum(0) if op == "sum" else x.min(0)
        red = jax.lax.psum if op == "sum" else jax.lax.pmin
        return red(local, DATA_AXIS)

    return _shard_map(f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())


def mesh_allreduce(parts: list[np.ndarray], mesh: Mesh,
                   op: str) -> np.ndarray:
    """Merge per-host 1-D integer partials into the global statistic
    with a REAL collective.  Hosts fold into the mesh's data-axis slots
    (host h -> slot h % D, identity-padded) so any host count runs on
    any mesh; integer psum/pmin are order-exact, which is what keeps
    the collective route bit-identical to the single-host loop."""
    ndev = mesh.shape[DATA_AXIS]
    ident = 0 if op == "sum" else _INT_INF
    slots = np.full((ndev,) + parts[0].shape, ident, np.int32)
    for h, p in enumerate(parts):
        if op == "sum":
            slots[h % ndev] += np.asarray(p, np.int32)
        else:
            slots[h % ndev] = np.minimum(slots[h % ndev],
                                         np.asarray(p, np.int32))
    out = jax.jit(allreduce_fn(mesh, op))(jnp.asarray(slots))
    return np.asarray(jax.device_get(out))


# -- the sharded merge ----------------------------------------------------

def sharded_merge(base: ShardDelta, deltas: list[ShardDelta], cfg: Config,
                  mesh: Mesh, num_hosts: int | None = None, bus=None):
    """(Dataset, MergeInfo) for base + deltas with the statistics merged
    over `mesh` — BIT-IDENTICAL to ``merge_shards(base, deltas, cfg)``
    for any delta order and any host count.

    The base shard is replicated (every host holds it — it defines the
    vocabulary and is a single mmap); deltas are per-host.  Cheap
    summaries (spans, trace-id sets, entry vocab lists + counts,
    pattern key bytes, unique resource-ms codes) are exchanged
    host-side and walked identically on every host through the factored
    merge phases; the per-trace numeric statistics (coverage universe,
    occurrence counts, first-appearance trace ids, drop counts) merge
    via psum/pmin rounds over the mesh.  Multiple small rounds are
    inherent: coverage feeds occurrence feeds admission feeds
    first-appearance — the same dependency chain the single-host loop
    walks in order.
    """
    bus = bus if bus is not None else telemetry.get_bus()
    t0 = time.perf_counter()
    if base.kind != "base" or base.vocabs is None:
        raise ValueError("sharded_merge needs the BASE shard first")
    # host count: explicit argument > --scale_hosts config > mesh data axis
    if num_hosts is not None:
        hosts = int(num_hosts)
    elif cfg.scale.scale_hosts > 1:
        hosts = cfg.scale.scale_hosts
    else:
        hosts = mesh.shape[DATA_AXIS]
    assignment = assign_shards(deltas, hosts)
    # every host derives the assignment from ITS store view; fingerprints
    # cross-check before any partials are computed (simulated hosts share
    # one view in-process — multi-process wiring exchanges the strings)
    verify_assignment([assignment_fingerprint(deltas, hosts)
                       for _ in range(hosts)], bus)

    base_hash = vocab_hash(base.vocabs)
    ordered_idx = sorted(range(len(deltas)),
                         key=lambda i: canonical_key(deltas[i]))
    ordered = [base] + [deltas[i] for i in ordered_idx]
    # delta position in canonical order -> owning host (round-robin over
    # the SAME sort assign_shards used, so rank r lives on host r % H)
    owner_of_pos = {pos + 1: pos % hosts
                    for pos in range(len(ordered_idx))}
    try:
        for d in deltas:
            if d.base_vocab_hash != base_hash:
                raise StreamRebuildRequired(
                    "base_changed",
                    f"delta coded against base {d.base_vocab_hash}, "
                    f"merging against {base_hash}")
        check_ordering([(s.span_ts_min, s.span_ts_max) for s in ordered])
        check_trace_disjoint([set(np.asarray(s.traceid_strings).tolist())
                              for s in ordered])
    except StreamRebuildRequired as e:
        bus.counter("stream.rebuild", reason=e.reason)
        raise

    offsets = np.concatenate(
        [[0], np.cumsum([s.n_traces_total for s in ordered])[:-1]])
    ends = offsets + np.asarray([s.n_traces_total for s in ordered])
    thr = cfg.ingest.min_traces_per_entry

    # -- exchanged summaries: identical walk on every host --------------
    entry_code, entry_maps, new_entries, _ = entry_union(
        base,
        [s.entry_vocab for s in ordered[1:]],
        [np.bincount(s.entry_local, minlength=len(s.entry_vocab))
         for s in ordered[1:]], thr, bus)
    _, shard_uidx, shard_pid_by_uidx, new_topologies = pattern_union(
        [[s.pattern_key(pid) for pid in range(s.num_patterns)]
         for s in ordered])
    check_coverage_drift(base, [s.res_ms for s in ordered[1:]], bus)

    def host_positions(h: int) -> list[int]:
        """Canonical-order positions (>= 1) of host h's deltas."""
        return [pos for pos, hh in owner_of_pos.items() if hh == h]

    # -- round 1 (psum): coverage universe -------------------------------
    num_ms = len(base.vocabs["ms"])
    cov_parts = []
    for h in range(hosts):
        part = np.zeros(num_ms, np.int32)
        for pos in host_positions(h):
            ms = np.unique(np.asarray(ordered[pos].res_ms))
            part[ms] = 1
        cov_parts.append(part)
    cov_global = mesh_allreduce(cov_parts, mesh, "sum")
    base_bitmap = np.zeros(num_ms, np.int32)
    base_bitmap[np.unique(np.asarray(base.res_ms))] = 1
    covered_ms = np.flatnonzero((cov_global > 0) | (base_bitmap > 0))

    cov_masks: dict[int, np.ndarray] = {}
    for pos in range(1, len(ordered)):
        cov_masks[pos] = coverage_mask(ordered[pos], covered_ms,
                                       cfg.ingest.min_resource_coverage)

    # -- round 2 (psum): occurrence counts over coverage-admitted rows --
    occ_parts = []
    for h in range(hosts):
        part = np.zeros(len(entry_code), np.int32)
        for pos in host_positions(h):
            s = ordered[pos]
            rows = cov_masks[pos][s.traceid]
            np.add.at(part, entry_maps[pos - 1][s.entry_local[rows]], 1)
        occ_parts.append(part)
    occ = mesh_allreduce(occ_parts, mesh, "sum").astype(np.int64)
    occ += np.bincount(base.entry_local,
                       minlength=len(entry_code)).astype(np.int64)
    entry_ok = occ > thr

    def admitted_mask(pos: int) -> np.ndarray:
        s = ordered[pos]
        if pos == 0:
            return np.ones(len(s.traceid), dtype=bool)
        ent = entry_maps[pos - 1][s.entry_local]
        return cov_masks[pos][s.traceid] & entry_ok[ent]

    # -- round 3 (pmin + psum): first-appearance tids and drop counts ---
    num_uidx = max((int(u.max(initial=-1)) for u in shard_uidx),
                   default=-1) + 1
    tid_parts, drop_parts = [], []
    for h in range(hosts):
        part = np.full(num_uidx, _INT_INF, np.int32)
        drops = np.zeros(2, np.int32)  # [coverage, occurrence]
        for pos in host_positions(h):
            s = ordered[pos]
            ent = entry_maps[pos - 1][s.entry_local]
            cov_ok = cov_masks[pos][s.traceid]
            occ_ok = entry_ok[ent]
            ok = cov_ok & occ_ok
            tid = (s.traceid + offsets[pos]).astype(np.int32)
            u = shard_uidx[pos][s.runtime_local]
            np.minimum.at(part, u[ok], tid[ok])
            drops[0] += int((~cov_ok).sum())
            drops[1] += int((cov_ok & ~occ_ok).sum())
        tid_parts.append(part)
        drop_parts.append(drops)
    min_tid = mesh_allreduce(tid_parts, mesh, "min")
    dropped_cov, dropped_occ = (int(x) for x in
                                mesh_allreduce(drop_parts, mesh, "sum"))
    base_part = np.full(num_uidx, _INT_INF, np.int32)
    np.minimum.at(base_part, shard_uidx[0][base.runtime_local],
                  base.traceid.astype(np.int32))
    min_tid = np.minimum(min_tid, base_part)

    # final runtime codes: rank of first-appearance tid among live
    # patterns — pd.factorize over the tid-sorted admitted rows assigns
    # codes in exactly this order (each tid belongs to one trace of one
    # pattern, so the minima are distinct)
    live = np.flatnonzero(min_tid < _INT_INF)
    runtime_of_uidx = np.full(num_uidx, -1, np.int64)
    runtime_of_uidx[live[np.argsort(min_tid[live], kind="stable")]] = (
        np.arange(len(live)))

    # -- representatives + graphs (owner-host checked) -------------------
    graphs: dict = {}
    for u in live:
        rep_tid = int(min_tid[u])
        si = int(np.searchsorted(ends, rep_tid, side="right"))
        s = ordered[si]
        local = rep_tid - int(offsets[si])
        pid = shard_pid_by_uidx[si].get(int(u))
        if pid is None or int(s.pat_rep_trace[pid]) != local:
            bus.counter("stream.rebuild", reason="representative_drift")
            raise StreamRebuildRequired(
                "representative_drift",
                f"runtime pattern {int(runtime_of_uidx[u])}: first "
                f"surviving trace {rep_tid} is not the trace its shard "
                f"built the graph from (filters moved the "
                f"representative)")
        graphs[int(runtime_of_uidx[u])] = s.graphs[pid]

    # -- per-shard meta rows, concatenated in canonical order ------------
    tids, entries, runtimes, tsbs, ys = [], [], [], [], []
    info_shards = []
    for pos, s in enumerate(ordered):
        ok = admitted_mask(pos)
        ent = (s.entry_local if pos == 0
               else entry_maps[pos - 1][s.entry_local])
        tids.append((s.traceid + offsets[pos])[ok])
        entries.append(ent[ok])
        runtimes.append(runtime_of_uidx[shard_uidx[pos][s.runtime_local]][ok])
        tsbs.append(s.ts_bucket[ok])
        ys.append(s.y[ok])
        info_shards.append((s.kind, int(offsets[pos]), s.n_traces_total,
                            int(ok.sum())))

    dataset, table = finalize_dataset(
        np.concatenate(tids), np.concatenate(entries),
        np.concatenate(runtimes), np.concatenate(tsbs),
        np.concatenate(ys), graphs,
        np.concatenate([s.res_ts for s in ordered]),
        np.concatenate([s.res_ms for s in ordered]),
        np.concatenate([s.res_values for s in ordered]), cfg, bus)

    dt = time.perf_counter() - t0
    bus.histogram("scale.merge_seconds", dt, hosts=hosts)
    bus.gauge("scale.merge_hosts", hosts)
    log.info(
        "sharded merge: %d shard(s) over %d host(s), %d traces "
        "(%d dropped by filters), %d entries, %d patterns in %.2fs",
        len(ordered), hosts, len(table.meta), dropped_cov + dropped_occ,
        len(entry_code), len(live), dt)
    info = MergeInfo(shards=info_shards, new_entries=new_entries,
                     new_topologies=new_topologies,
                     dropped_coverage=dropped_cov,
                     dropped_occurrence=dropped_occ,
                     meta=table.meta.iloc[:cfg.data.max_traces])
    return dataset, info


# -- SAR-style rematerialized training -----------------------------------

#: Primitives whose recomputation is NOT guaranteed bit-identical to the
#: stored forward value, so the remat policy saves them instead of
#: replaying them on the backward pass.  Two families:
#:
#: - transcendentals: XLA emits polynomial/Newton approximations whose
#:   exact bits depend on the fusion context they are compiled into
#:   (observed on XLA:CPU — a rematerialized ``exp``/``rsqrt`` chain can
#:   differ by 1 ulp from the forward program's, which is enough to break
#:   the tolerance-0 gradient assert);
#: - multi-element reductions and scatters: accumulation ORDER is a
#:   scheduling choice, stable within one program but not across the
#:   remat/no-remat pair (and genuinely nondeterministic for scatters on
#:   some accelerator backends).
#:
#: Everything else — gathers, element-wise arithmetic, selects,
#: broadcasts, which dominate the residual footprint of the attention
#: bucket body — replays bit-exactly, so rematerializing it keeps the
#: accumulated gradient bitwise equal to the monolithic one while still
#: dropping the bulk of the stored residuals (~60% of temp bytes on the
#: CI model; ``benchmarks/scale_bench.py`` prints the measured pair).
BIT_STABLE_SAVE = frozenset({
    # transcendental / approximated element-wise
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "div", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "asinh",
    "acosh", "atanh", "pow", "integer_pow", "digamma", "lgamma",
    # order-sensitive reductions / scatters (dot_general excluded: its
    # blocking is shape-deterministic, and its outputs are the largest
    # residuals — saving them would forfeit most of the memory win)
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "cumlogsumexp", "scatter", "scatter-add",
    "scatter-mul", "scatter-min", "scatter-max", "sort", "top_k",
})


def bit_stable_policy(prim, *_, **__) -> bool:
    """``jax.checkpoint`` policy: save exactly the primitives whose
    replay is not bit-stable (:data:`BIT_STABLE_SAVE`), rematerialize
    the rest.  This is what pins grad(remat) == grad(monolithic) at
    tolerance 0 — see the module docstring."""
    return getattr(prim, "name", None) in BIT_STABLE_SAVE


def bucket_batches(batches: list[PackedBatch], capacity: int,
                   bus=None) -> PackedBatch:
    """Leading-stack `batches` into the fixed bucket capacity, padded
    with inert zero-mask clones.  The CAPACITY is the compile-time
    constant; live counts up to it reuse one program.  A mixture that
    needs MORE buckets than capacity refuses loudly
    (AccumulationOverflow + ``scale.accum_overflow``) — truncation
    would silently train on a subset of the corpus."""
    if not batches:
        raise ValueError("bucket_batches needs at least one batch")
    if len(batches) > capacity:
        bus = bus if bus is not None else telemetry.get_bus()
        bus.counter("scale.accum_overflow", need=len(batches),
                    capacity=capacity)
        raise AccumulationOverflow(
            f"mixture needs {len(batches)} topology bucket(s) but "
            f"accum_buckets={capacity}; raise --accum_buckets (or "
            f"shrink the batch budget so fewer buckets cover an epoch)")
    group = list(batches) + [zero_masked(batches[-1])] * (capacity
                                                          - len(batches))
    return jax.tree.map(lambda *xs: np.stack(xs), *group)


def sar_bucket_terms_fn(model: PertGNN, cfg: Config) -> Callable:
    """``(params, batch_stats, batch, dropout_key) -> (pinball_num,
    graph_cnt, local_num, local_cnt, new_batch_stats, metric_sums)`` —
    ONE bucket's additive contribution to the accumulated step, exactly
    as :func:`_sar_loss` scans it (this IS the scanned body, factored
    out so graftaudit traces the real program: every sum here is masked,
    which is what lets a zero-masked padding bucket ride a scan slot
    without touching the gradients).  ``dropout_key`` may be None when
    ``cfg.model.dropout == 0``."""
    taus, pi = _resolved_taus(cfg)
    scale = cfg.train.label_scale
    lw = cfg.model.local_loss_weight

    def terms(params, batch_stats, b, dropout_key):
        variables = {"params": params, "batch_stats": batch_stats}
        rngs = ({"dropout": dropout_key} if dropout_key is not None
                else {})
        (global_pred, local_pred), updates = model.apply(
            variables, b, training=True, mutable=["batch_stats"],
            rngs=rngs)
        y_scaled = b.y / scale
        if len(taus) == 1:
            pnum, gcnt = quantile_loss_sums(y_scaled, global_pred,
                                            taus[0], b.graph_mask)
            primary = global_pred
        else:
            tau_terms = [quantile_loss_sums(y_scaled, global_pred[:, j],
                                            t, b.graph_mask)
                         for j, t in enumerate(taus)]
            # the mask count is identical across taus, so summing the
            # numerators and dividing once equals the sum of per-tau
            # means
            pnum = sum(t[0] for t in tau_terms)
            gcnt = tau_terms[pi][1]
            primary = global_pred[:, pi]
        lnum = lcnt = jnp.zeros((), jnp.float32)
        if lw > 0:
            lnum, lcnt = quantile_loss_sums(y_scaled[b.node_graph],
                                            local_pred, taus[pi],
                                            b.node_mask)
        metrics = masked_metric_sums(b.y, primary * scale, taus[pi],
                                     b.graph_mask)
        return (pnum, gcnt, lnum, lcnt, updates["batch_stats"], metrics)

    return terms


def _sar_loss(model: PertGNN, cfg: Config, params, batch_stats, buckets,
              rng, *, remat: bool):
    """Scalar loss of the bucket-scanned epoch slice, carrying the
    pinball numerator/count pairs through the scan and dividing ONCE at
    the end — half of what makes grad(scan-with-remat) equal
    grad(scan-without-remat) BITWISE: both differentiate the identical
    arithmetic.  The other half is :func:`bit_stable_policy` — remat
    must only replay ops whose recomputation is bit-exact.
    batch_stats thread sequentially bucket-to-bucket
    (training-mode BatchNorm normalizes each bucket with ITS batch
    statistics, so the gradients are unaffected; the running-stats
    bookkeeping is sequential by construction — GUIDE §15)."""
    lw = cfg.model.local_loss_weight
    terms = sar_bucket_terms_fn(model, cfg)

    def bucket_terms(stats, b, i):
        key = (jax.random.fold_in(rng, i) if cfg.model.dropout > 0
               else None)
        return terms(params, stats, b, key)

    if remat:
        bucket_terms = jax.checkpoint(bucket_terms,
                                      policy=bit_stable_policy)

    def body(carry, xb):
        b, i = xb
        pnum, gcnt, lnum, lcnt, stats = carry

        def run(stats):
            pn, gc, ln, lc, new_stats, m = bucket_terms(stats, b, i)
            return (pnum + pn, gcnt + gc, lnum + ln, lcnt + lc,
                    new_stats), m

        def skip(stats):
            return (pnum, gcnt, lnum, lcnt, stats), {
                k: jnp.zeros((), jnp.float32) for k in _METRIC_KEYS}

        return jax.lax.cond(jnp.any(b.graph_mask), run, skip, stats)

    num_buckets = jax.tree.leaves(buckets)[0].shape[0]
    zero = jnp.zeros((), jnp.float32)
    (pnum, gcnt, lnum, lcnt, stats), ms = jax.lax.scan(
        body, (zero, zero, zero, zero, batch_stats),
        (buckets, jnp.arange(num_buckets)))
    loss = pnum / jnp.maximum(gcnt, 1.0)
    if lw > 0:
        loss = loss + lw * (lnum / jnp.maximum(lcnt, 1.0))
    metrics = jax.tree.map(lambda a: a.sum(0), ms)
    return loss, (stats, metrics)


def sar_step_fn(model: PertGNN, cfg: Config,
                tx: optax.GradientTransformation, *,
                remat: bool = True) -> Callable:
    """UNJITTED accumulated step: ``(state, buckets) -> (state,
    metrics)`` with ONE optimizer update for the whole bucket stack —
    the SAR counterpart of ``train_step_fn``.  ``remat=False`` is the
    aggregation-held monolithic twin (same arithmetic, all residuals
    live) used as the tolerance-0 gradient reference."""

    def step(state: TrainState, buckets: PackedBatch):
        rng = jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed),
                                 state.step)
        grad_fn = jax.value_and_grad(
            lambda p: _sar_loss(model, cfg, p, state.batch_stats,
                                buckets, rng, remat=remat),
            has_aux=True)
        (_, (new_stats, metrics)), grads = grad_fn(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return state.replace(params=new_params, batch_stats=new_stats,
                             opt_state=new_opt,
                             step=state.step + 1), metrics

    return step


def make_sar_train_step(model: PertGNN, cfg: Config,
                        tx: optax.GradientTransformation, *,
                        remat: bool = True) -> Callable:
    """Jitted accumulated step, state buffers donated like every other
    train step (the replaced state dies with the dispatch)."""
    return jax.jit(sar_step_fn(model, cfg, tx, remat=remat),
                   donate_argnums=0)


def sar_grads_fn(model: PertGNN, cfg: Config, *,
                 remat: bool = True) -> Callable:
    """``(params, batch_stats, buckets) -> grads`` with a fixed rng —
    the comparable gradient surface for the bit-equivalence asserts
    (tests/test_scale.py, benchmarks/scale_bench.py compare
    ``remat=True`` against ``remat=False`` at tolerance 0, f32)."""
    rng = jax.random.PRNGKey(cfg.train.seed)

    def grads(params, batch_stats, buckets):
        return jax.grad(
            lambda p: _sar_loss(model, cfg, p, batch_stats, buckets,
                                rng, remat=remat)[0])(params)

    return grads


# -- memory accounting ----------------------------------------------------

def step_temp_bytes(jit_fn: Callable, *abs_args) -> int | None:
    """Compiled temp-buffer bytes of `jit_fn` at the given abstract
    signature — the backend-portable peak proxy (XLA's
    ``memory_analysis``; live on CPU where ``device.mem.peak_bytes``
    gauges are not).  Residual storage for the backward pass lands in
    temp buffers, which is exactly what rematerialization bounds — the
    remat-vs-monolithic headroom the bench exit-asserts.  None when the
    backend offers no analysis."""
    try:
        analysis = jit_fn.lower(*abs_args).compile().memory_analysis()
    except Exception as e:  # backend without the analysis surface
        log.debug("memory_analysis unavailable: %s", e)
        return None
    if analysis is None:
        return None
    v = getattr(analysis, "temp_size_in_bytes", None)
    return int(v) if v is not None else None


def sample_bucket_memory(bus, *, buckets: int, where: str = "sar_step",
                         device=None) -> dict | None:
    """Per-bucket-count allocator sample: ``device.mem.*`` gauges
    tagged with the bucket capacity (None-safe no-op on CPU — the
    bench then leans on :func:`step_temp_bytes`)."""
    from pertgnn_tpu.telemetry.devmem import sample_device_memory

    return sample_device_memory(bus, device=device, where=where,
                                buckets=buckets)
