"""Edge-sharded attention for giant graphs (the "sequence parallelism" of
this domain).

There is no token-sequence axis in a graph regressor (SURVEY.md §5.7); the
scaling axis is graph size. For one giant DAG (BASELINE config 5: 5k-node
synthetic microservice graphs) whose edge set exceeds a single chip's
appetite, the edge set is sharded across the `data` axis with nodes
replicated: each device scores its edge shard, and the per-destination
softmax is completed with a pmax (running max) + psum (denominator,
numerator) over ICI — a ring-attention-style exact decomposition of softmax
aggregation, expressed with XLA collectives under shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from pertgnn_tpu.ops.segment import segment_max, segment_sum
from pertgnn_tpu.parallel.mesh import DATA_AXIS


def sharded_edge_attention(q, k, v, e, senders, receivers, edge_mask,
                           mesh: Mesh, axis: str = DATA_AXIS):
    """Exact TransformerConv attention with the edge set sharded over `axis`.

    q, k, v: (N, H, C) node-level projections, replicated.
    e: (E, H, C) edge-feature projections; senders/receivers/edge_mask: (E,).
    Edge arrays must have E divisible by the axis size. Returns (N, H*C),
    replicated (matches the unsharded layer bit-for-bit up to reduction
    order).
    """
    num_nodes, H, C = q.shape

    def local(q, k, v, e, snd, rcv, msk):
        k_e = k[snd] + e
        v_e = v[snd] + e
        scores = (q[rcv] * k_e).sum(-1) / jnp.sqrt(
            jnp.asarray(C, q.dtype))                     # (E_loc, H)
        neg = jnp.asarray(-jnp.inf, scores.dtype)
        scores = jnp.where(msk[:, None], scores, neg)
        # The running max only stabilizes the softmax — its gradient
        # contribution cancels exactly, and pmax has no differentiation
        # rule, so compute it outside the autodiff graph.
        m = segment_max(jax.lax.stop_gradient(scores), rcv, num_nodes)
        m = jax.lax.pmax(m, axis)                        # global max
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        ex = jnp.where(msk[:, None], jnp.exp(scores - m[rcv]), 0.0)
        den = jax.lax.psum(segment_sum(ex, rcv, num_nodes), axis)
        num = jax.lax.psum(
            segment_sum((v_e * ex[..., None]).reshape(ex.shape[0], -1),
                        rcv, num_nodes), axis)           # (N, H*C)
        den = jnp.where(den > 0, den, 1.0)
        return (num.reshape(num_nodes, H, C)
                / den[..., None]).reshape(num_nodes, H * C)

    return _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(), P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(),
    )(q, k, v, e, senders, receivers, edge_mask)
