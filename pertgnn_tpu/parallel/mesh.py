"""Device mesh and sharding layout.

The reference is single-device by construction (pert_gnn.py:36-37; no
torch.distributed anywhere — SURVEY.md §5.8). Distribution here is designed
the XLA way ("How to Scale Your Model" recipe): pick a mesh, annotate input
and parameter shardings, and let the SPMD partitioner insert the collectives
(psum over ICI for gradient/segment reductions) — NOT hand-written NCCL-style
point-to-point.

Axes:
- ``data``  — data parallelism: the packed batch's node/edge/graph arrays are
  sharded on their leading dimension. Because the loss and BatchNorm
  statistics aggregate over the global batch inside ONE jitted program, XLA
  emits the gradient all-reduce automatically.
- ``model`` — tensor parallelism: hidden dimensions of Dense kernels and
  embedding tables are sharded; activations follow (data, model).

Pipeline and expert axes are deliberately absent: the model has no
sequential stage structure deep enough to pipeline (max(2, L) small convs)
and no MoE — the analogous long-context axis for GNNs is GRAPH size, served
by edge sharding in `graph_shard.py` (SURVEY.md §5.7).

Multi-host: the same mesh spans all processes' devices after
`multihost.initialize` (jax.distributed); input is sharded per host and
assembled with make_array_from_process_local_data (parallel/multihost.py;
2-process CPU equivalence test in tests/test_multihost.py).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pertgnn_tpu.batching.arena import IndexBatch
from pertgnn_tpu.batching.pack import PackedBatch

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: int = -1, model: int = 1,
              devices: list | None = None) -> Mesh:
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    need = data * model
    if need > n:
        raise ValueError(f"mesh {data}x{model} needs {need} devices, have {n}")
    arr = np.array(devices[:need]).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def batch_shardings(mesh: Mesh) -> PackedBatch:
    """Leading-dim `data` sharding for every array in a packed batch."""
    s = NamedSharding(mesh, P(DATA_AXIS))
    return PackedBatch(*([s] * len(PackedBatch._fields)))


def chunk_batch_shardings(mesh: Mesh) -> PackedBatch:
    """Shardings for a leading-STACKED packed batch (scan chunk of global
    batches): dim 0 is the scan axis (replicated), dim 1 is sharded over
    `data`."""
    s = NamedSharding(mesh, P(None, DATA_AXIS))
    return PackedBatch(*([s] * len(PackedBatch._fields)))


def index_batch_shardings(mesh: Mesh) -> IndexBatch:
    """Leading-dim `data` sharding for a global gather recipe
    (stack_index_batches output): the int32 index arrays shard exactly like
    the PackedBatch arrays they materialize into."""
    s = NamedSharding(mesh, P(DATA_AXIS))
    return IndexBatch(*([s] * len(IndexBatch._fields)))


def chunk_index_batch_shardings(mesh: Mesh) -> IndexBatch:
    """Shardings for a leading-STACKED global gather recipe (scan chunk)."""
    s = NamedSharding(mesh, P(None, DATA_AXIS))
    return IndexBatch(*([s] * len(IndexBatch._fields)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Full replication over the mesh (device arenas, giant-graph batches)."""
    return NamedSharding(mesh, P())


def replicated_batch_shardings(mesh: Mesh) -> PackedBatch:
    """Replicated shardings for a packed batch — the giant-graph
    (shard_edges) mode: nodes/graphs replicated, the layers shard the edge
    set internally via shard_map (graph_shard.py). P() covers any rank, so
    this serves plain and leading-stacked (scan chunk) batches alike."""
    s = NamedSharding(mesh, P())
    return PackedBatch(*([s] * len(PackedBatch._fields)))


def _param_spec(path: tuple, leaf) -> P:
    """Tensor-parallel rule per parameter.

    - Dense kernels (in, out): shard `out` over `model` — except the scalar
      output heads, which are replicated;
    - Dense biases (out,): follow their kernel;
    - Embedding tables (vocab, features): shard `features` over `model`;
    - BatchNorm scale/bias/stats (features,): follow the hidden sharding.
    """
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    if "local_head" in joined or "global_head2" in joined:
        return P()
    if leaf.ndim == 2:
        return P(None, MODEL_AXIS)
    if leaf.ndim == 1:
        return P(MODEL_AXIS)
    return P()


def param_shardings(params: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _param_spec(path, leaf)),
        params)


def place_state(state: Any, st_sh: Any) -> Any:
    """Place a host-initialized TrainState into its mesh shardings.

    Single-host: device_put of a copy (the donated step would otherwise
    delete the caller's arrays). Multi-host: every process initialized the
    identical state (same seed), so each process's local slab of a
    replicated/within-host-sharded leaf is the full array —
    make_array_from_process_local_data assembles the global arrays
    (device_put cannot target non-addressable devices)."""
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jax.device_put(jax.tree.map(jnp.copy, state), st_sh)
    from pertgnn_tpu.parallel.multihost import put_replicated
    return put_replicated(state, st_sh)


def state_shardings(state: Any, mesh: Mesh) -> Any:
    """Shardings for a full TrainState: params/opt_state follow the TP rule
    (optax states mirror the param tree), batch_stats follow features,
    scalars replicate."""

    def spec(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _param_spec(path, leaf))

    return jax.tree_util.tree_map_with_path(spec, state)
