"""Multi-host distribution: process init, per-host input sharding, global
batch assembly.

The reference is single-process, single-device by construction
(/root/reference/pert_gnn.py:36-37 — no torch.distributed anywhere,
SURVEY.md §5.8). Multi-host here follows the JAX SPMD recipe end-to-end:

- every process runs the SAME program; `initialize` wires the processes
  together (jax.distributed / coordinator service — the TPU-native stand-in
  for what a GPU scale-out of the reference would do with NCCL ranks);
- the device mesh spans ALL processes' devices; the jitted train step is
  the identical SPMD program as single-host — XLA routes collectives over
  ICI within a host/slice and DCN across;
- input is sharded BY HOST: each process materializes only the batch
  shards its own devices consume (`process_shard_slice`), stacks them with
  GLOBAL node/graph offsets, and the global device array is assembled with
  `jax.make_array_from_process_local_data` — no host ever touches the full
  global batch, so host packing cost divides by process count.

CPU multi-process (tests, 2-process CPU smoke): gloo collectives are
enabled automatically when the backend is CPU.
"""

from __future__ import annotations

import logging
from typing import Callable, Iterator, Sequence

import jax
import numpy as np

from pertgnn_tpu.batching.arena import IndexBatch
from pertgnn_tpu.batching.pack import PackedBatch

log = logging.getLogger(__name__)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """`jax.distributed.initialize` entry point.

    No-op (returns False) when num_processes is absent or 1, so single-host
    callers can pass CLI flags through unconditionally. On CPU backends the
    gloo collectives implementation is selected first (required for
    cross-process psum on CPU; local device count comes from
    --xla_force_host_platform_device_count)."""
    if not num_processes or num_processes <= 1:
        return False
    import os
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jaxlib without the option: let init try
            log.debug("jax_cpu_collectives_implementation unavailable; "
                      "distributed init will pick its own transport")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    from pertgnn_tpu.utils.logging import set_process_context
    set_process_context(jax.process_index(), jax.process_count())
    log.info("jax.distributed initialized: process %d/%d, %d local / %d "
             "global devices", jax.process_index(), jax.process_count(),
             len(jax.local_devices()), len(jax.devices()))
    return True


def process_shard_slice(n_shards: int) -> slice:
    """The contiguous range of global batch-shard ids this process's
    devices own (device order in `make_mesh` is `jax.devices()`, which
    orders devices by process index)."""
    pc, pi = jax.process_count(), jax.process_index()
    if n_shards % pc:
        raise ValueError(
            f"data-axis size {n_shards} not divisible by process count {pc}")
    spp = n_shards // pc
    return slice(pi * spp, (pi + 1) * spp)


def _stack_with_global_offsets(parts_cls, batches: Sequence,
                               shard_offset: int, offset_rules: dict
                               ) -> "parts_cls":
    out = {}
    per = {f: getattr(batches[0], f).shape[0] for f in parts_cls._fields}
    for field in parts_cls._fields:
        cols = []
        for d, b in enumerate(batches):
            a = getattr(b, field)
            rule = offset_rules.get(field)
            if rule is not None:
                a = a + (shard_offset + d) * per[rule]
            cols.append(a)
        out[field] = np.concatenate(cols)
    return parts_cls(**out)


def stack_local_shards(batches: Sequence[PackedBatch],
                       shard_offset: int) -> PackedBatch:
    """This host's per-shard batches concatenated with GLOBAL node/graph
    offsets — the host-local slab of the global batch. No receiver re-sort:
    multi-host runs the order-free segment attention (data_parallel.
    stack_index_batches has the same contract)."""
    return _stack_with_global_offsets(
        PackedBatch, batches, shard_offset,
        {"senders": "x", "receivers": "x", "node_graph": "entry_id"})


def assemble_global(local, shardings, axis: int = 0):
    """Build global device arrays from each process's local slab
    (jax.make_array_from_process_local_data per leaf). `axis` is the
    host-sharded dim: 0 for plain global batches, 1 for leading-STACKED
    scan chunks (dim 0 is the scan axis, replicated)."""
    pc = jax.process_count()

    def mk(a, sh):
        a = np.asarray(a)
        shape = list(a.shape)
        shape[axis] *= pc
        return jax.make_array_from_process_local_data(sh, a, tuple(shape))

    return jax.tree.map(mk, local, shardings,
                        is_leaf=lambda x: isinstance(x, np.ndarray))


def put_replicated(tree, shardings):
    """Place host arrays fully replicated over a (possibly multi-host)
    mesh: every process holds the identical value, so the local slab IS the
    global array (works for single-host too)."""
    return jax.tree.map(
        lambda a, sh: jax.make_array_from_process_local_data(
            sh, np.asarray(a)),
        tree, shardings, is_leaf=lambda x: isinstance(x, np.ndarray))


def host_grouped_batches(index_stream: Iterator[IndexBatch], n_shards: int,
                         materialize: Callable[[IndexBatch], PackedBatch],
                         filler: Callable[[IndexBatch], IndexBatch]
                         ) -> Iterator[PackedBatch]:
    """Per-host input pipeline: walk the (cheap) whole-epoch gather-recipe
    stream, but materialize ONLY this host's shards of each global batch.
    The greedy packer is sequential, so every process must see the same
    recipe order; the expensive materialization divides by process count."""
    from pertgnn_tpu.parallel.data_parallel import _grouped
    sl = process_shard_slice(n_shards)
    return _grouped(
        index_stream, n_shards,
        lambda g: stack_local_shards([materialize(i) for i in g[sl]],
                                     sl.start),
        filler)


def host_grouped_compact_batches(stream, n_shards: int, filler):
    """Per-host O(graphs) recipe pipeline: this process concatenates only
    its own shards' compact recipes (offsets are applied on DEVICE by the
    shard-local expansion, so the local slab is a plain concat)."""
    from pertgnn_tpu.parallel.data_parallel import (_grouped,
                                                    stack_compact_batches)
    sl = process_shard_slice(n_shards)
    return _grouped(stream, n_shards,
                    lambda g: stack_compact_batches(g[sl]), filler)
