"""Console-script launcher for graftsync (docs/LINTS.md).

Same pattern as graftlint_cli.py / graftaudit_cli.py: graftsync
analyzes a SOURCE TREE's thread protocols, so it only makes sense
where one exists — an editable (in-repo) install, where this package
sits inside the repo checkout and `tools/graftsync/` is its sibling.
The launcher lives inside `pertgnn_tpu` so the wheel never ships a
generic top-level `tools` package (namespace squatting), while the
`graftsync` entry point still works in the install mode where the
tool is usable — and fails with a clear message, not a
ModuleNotFoundError, everywhere else.
"""

from __future__ import annotations

import os
import sys


def main(argv: list[str] | None = None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo, "tools", "graftsync")):
        print(
            "graftsync: no tools/graftsync next to this package — the "
            "analyzer reads a repo working tree's thread protocols, "
            "which only an editable (in-repo) install has. From a "
            "checkout, run `python -m tools.graftsync` "
            "(docs/LINTS.md).",
            file=sys.stderr)
        return 2
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftsync.cli import main as graftsync_main

    return graftsync_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
