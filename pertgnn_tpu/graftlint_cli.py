"""Console-script launcher for graftlint (docs/LINTS.md).

graftlint lints a SOURCE TREE, so it only makes sense where one exists:
an editable (in-repo) install, where this package sits inside the repo
checkout and `tools/graftlint/` is its sibling. This launcher lives
inside `pertgnn_tpu` so the wheel never ships a generic top-level
`tools` package (namespace squatting), while the `graftlint` entry
point still works in the install mode where the tool is usable — and
fails with a clear message, not a ModuleNotFoundError, everywhere else.
"""

from __future__ import annotations

import os
import sys


def main(argv: list[str] | None = None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo, "tools", "graftlint")):
        print(
            "graftlint: no tools/graftlint next to this package — the "
            "linter analyzes a repo working tree, which only an "
            "editable (in-repo) install has. From a checkout, run "
            "`python -m tools.graftlint` (docs/LINTS.md).",
            file=sys.stderr)
        return 2
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftlint.cli import main as graftlint_main

    return graftlint_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
