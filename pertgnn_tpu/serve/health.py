"""The readiness probe, shared by serve_main and the fleet worker.

One probe, two consumers: a load balancer (or the fleet router's
membership poller) reads the STATUS CODE — 200 while the engine is
healthy and admissions are open, 503 while unhealthy or draining — and
humans (and the router's least-loaded policy, and autoscalers) read the
BODY, which since PR 7 carries live load alongside engine health:

    {
      "healthy": true, "reason": null, "warmed": true,
      "executables": 4, "buckets": 4, "rebuilds": 0, "nan_outputs": 0,
      "draining": false, "ready": true,
      "queue": {"depth": 3, "inflight": 8,
                "errors": {"QueueFull": 2, "DeadlineExceeded": 1}}
    }

``queue.depth`` is requests admitted but not yet dispatched,
``queue.inflight`` requests dispatched but not yet resolved, and
``queue.errors`` per-class typed-failure counts since process start
(serve/errors.py names) — load is readable from one GET without
scraping telemetry JSONL. The status-code contract predates the body
extension and is unchanged; nothing may key off body fields to decide
routability (that is what the code is for).

The server is a daemon-threaded stdlib ``ThreadingHTTPServer`` bound to
127.0.0.1: the probe must never compete with the request path for the
queue worker, and must never be reachable off-host by accident (the
fleet is a single-host co-process topology; see docs/GUIDE.md on the
shared-cache trust boundary).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def probe_payload(engine, queue, extra: dict | None = None
                  ) -> tuple[bool, dict]:
    """(ready, body) for one probe answer. `extra` lets a caller stamp
    identity fields (the fleet worker adds worker_id/port and its
    warm-start evidence) without forking the schema."""
    health = engine.health()
    draining = bool(queue.draining)
    ready = bool(health["healthy"]) and not draining
    body = {**health, "draining": draining, "ready": ready,
            "queue": queue.probe_dict()}
    if extra:
        body.update(extra)
    return ready, body


def start_health_server(port: int, engine, queue,
                        extra_fn=None) -> ThreadingHTTPServer:
    """Serve GET /healthz (any path answers — probes are not routed)
    on 127.0.0.1:`port` from a daemon thread; returns the server (call
    ``shutdown()`` on exit). `extra_fn` () -> dict is evaluated per
    probe so its fields stay live."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            ready, body = probe_payload(
                engine, queue, extra_fn() if extra_fn else None)
            payload = json.dumps(body).encode()
            self.send_response(200 if ready else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):  # probes are periodic; don't spam
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="serve-healthz").start()
    return server
