"""The serving failure vocabulary: every way a request can fail, typed.

The request path promises that a caller's Future ALWAYS resolves — to a
prediction or to one of these exceptions — and that the exception names
WHY, so an RPC front-end can map each to the right status code (429 for
shed, 504 for deadline, 503 for an unhealthy engine) instead of pattern-
matching message strings. docs/RELIABILITY.md tabulates failure mode ->
detection -> behavior -> telemetry counter.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class of all typed serving failures."""


class QueueFull(ServeError):
    """Admission control shed this request: the pending set is at
    ServeConfig.max_pending. Fast-fail at submit — under overload the
    queue rejects new work instead of growing without bound until every
    caller times out. Counter: ``serve.shed``."""


class Shed(QueueFull):
    """Class-aware admission shed (pertgnn_tpu/fleet/shield.py): the
    pending set is full and this request lost the priority comparison —
    either the submitted request itself (its SLO class is not strictly
    higher than everything already queued) or a lower-class victim
    EVICTED to admit a higher-class arrival (its Future resolves with
    this; never a lost Future). ``slo`` names the shed request's class.
    Subclasses QueueFull so pre-SLO callers matching on QueueFull keep
    working. Counters: ``serve.shed_by_class`` /
    ``router.shed_by_class`` (tags ``slo``, ``mode``: reject/evict)."""

    def __init__(self, message: str, *, slo: str = ""):
        super().__init__(message)
        self.slo = slo


class QueueClosed(ServeError):
    """Submit after close() or during a graceful drain. The message
    contains "closed" for callers matching on it."""


class DeadlineExceeded(ServeError):
    """The request waited past ServeConfig.request_deadline_ms without
    being dispatched; its Future resolves with this instead of waiting
    forever. Counter: ``serve.deadline_exceeded``."""


class RequestQuarantined(ServeError):
    """This entry_id poisoned >= ServeConfig.quarantine_threshold
    microbatches (isolated by bisect-retry) and is now rejected at
    submit so it cannot keep taking innocent co-batched requests down.
    Counter: ``serve.quarantined`` (on quarantine) /
    ``serve.quarantine_rejected`` (per rejected submit)."""


class DispatchTimeout(ServeError):
    """An engine dispatch exceeded ServeConfig.dispatch_timeout_s — the
    wedged-device-transport signature (a blocked device call raises
    nothing, ever). The watchdog abandons the dispatch, marks the engine
    unhealthy, and attempts a rebuild-from-AOT-store recovery. Counter:
    ``serve.watchdog_trip``."""


class EngineUnhealthy(ServeError):
    """Fast-fail during the post-watchdog cooldown: the engine is marked
    unhealthy and requests are rejected immediately instead of queuing
    behind a dead device. ``engine.health()`` (and serve_main's
    --health_port probe) reports the same state."""


class NonFiniteOutput(ServeError):
    """The model returned NaN/inf for this request. The output guard
    quarantines the batch rather than returning garbage to a caller.
    Counter: ``serve.nan_outputs``."""


class WhatIfRefused(ServeError):
    """A counterfactual topology edit (pertgnn_tpu/lens/whatif.py) names
    something the pure edit algebra cannot honor — an out-of-range
    node/edge index, a substitute id outside the embedding vocabulary,
    dropping a pattern's last node, an edit that would GROW the graph.
    Refused loudly at submit (the request never occupies a pending
    slot); never an approximate edit. Counter: ``lens.whatif_refused``.
    Semantics + the full refusal list: docs/GUIDE.md §13."""


class LensDisabled(ServeError):
    """An attribution request (lens.attribute_k > 0) reached an engine
    whose local-pred rung programs were not warmed
    (``LensConfig.lens_local`` off). Refused at submit: the engine NEVER
    compiles a program variant on the request path — enable
    ``--lens_local`` so warmup builds the attribution ladder."""
