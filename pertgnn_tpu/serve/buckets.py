"""Shape-bucket ladder for the serving engine.

One compiled executable per distinct input shape is the whole game on
dense hardware (DGL and "Fast Training of Sparse GNNs on Dense Hardware"
apply the same static-shape/padding discipline to training — PAPERS.md).
Serving cannot use the single training budget directly: padding every
1-graph request to a 170-graph epoch batch wastes >100x compute. Instead a
small geometric ladder of budgets covers the request-size range; each rung
is compiled once at warmup and every request pads up to the smallest rung
that fits, so steady-state serving never recompiles and pad waste stays
bounded by the ladder's growth factor.
"""

from __future__ import annotations

from pertgnn_tpu.batching.pack import (BatchBudget, _round_up,  # noqa: F401
                                       pad_waste)
from pertgnn_tpu.config import ServeConfig

# pad_waste lives next to BatchBudget in batching/pack.py (the metric is
# shared with the epoch packer's telemetry); re-exported here because the
# serving engine and bench reach it through this module.


def make_bucket_ladder(top: BatchBudget,
                       cfg: ServeConfig) -> tuple[BatchBudget, ...]:
    """Ascending ladder of bucket shapes whose last rung covers `top`.

    Rungs shrink geometrically from the dataset-derived training budget
    (`top`, which any single mixture fits by construction —
    pack.derive_budget's max-mixture floor) down to the configured
    minimum, nodes and edges in lockstep, every capacity rounded up to a
    multiple of 128 for TPU lane alignment. All rungs share the serving
    graph capacity `cfg.max_graphs_per_batch` (per-graph arrays are O(G)
    — padding them is free) except that no rung exceeds the training
    budget's graph count.
    """
    if cfg.bucket_growth <= 1.0:
        raise ValueError(
            f"bucket_growth must be > 1 (got {cfg.bucket_growth})")
    max_graphs = min(cfg.max_graphs_per_batch, top.max_graphs)
    rungs: list[BatchBudget] = []
    n, e = float(top.max_nodes), float(top.max_edges)
    while True:
        rung = BatchBudget(max_graphs=max_graphs,
                           max_nodes=_round_up(int(n)),
                           max_edges=_round_up(int(e)))
        if (rungs and rung.max_nodes >= rungs[-1].max_nodes
                and rung.max_edges >= rungs[-1].max_edges):
            break  # 128-rounding converged — smaller rungs are duplicates
        rungs.append(rung)
        if (rung.max_nodes <= cfg.min_bucket_nodes
                and rung.max_edges <= cfg.min_bucket_edges):
            break
        n, e = n / cfg.bucket_growth, e / cfg.bucket_growth
    return tuple(reversed(rungs))


def select_bucket(ladder: tuple[BatchBudget, ...], num_graphs: int,
                  num_nodes: int, num_edges: int) -> int | None:
    """Index of the smallest rung fitting the request, None if none does.

    The ladder is ascending and short (typically < 10 rungs), so a linear
    scan beats anything clever."""
    for i, b in enumerate(ladder):
        if (num_graphs <= b.max_graphs and num_nodes <= b.max_nodes
                and num_edges <= b.max_edges):
            return i
    return None
