"""Online inference engine: shape-bucketed AOT serving of trained models.

Training already pays the irregular-graph-on-dense-hardware tax exactly
once — every epoch batch has ONE static shape so the train step compiles
once (batching/pack.py). Serving faces the same problem at request
granularity: per-request graph shapes vary, and a naive per-request
`jax.jit` recompiles on every new shape, destroying tail latency. This
package re-applies the training discipline to the request path:

- `buckets`  — a small geometric ladder of `BatchBudget` shapes up to the
  dataset-derived training budget; every request pads up to the smallest
  fitting rung;
- `engine`   — per-rung executables AOT-compiled once at warmup
  (`jax.jit(...).lower(...).compile()`), a single-batch fast pack
  (batching/pack.py `pack_single`), and hit/miss/pad-waste counters;
- `queue`    — a deadline-based microbatching queue coalescing concurrent
  requests into one bucket-shaped dispatch, hardened with admission
  control, per-request deadlines, poisoned-batch quarantine, and a
  dispatch watchdog (docs/RELIABILITY.md);
- `errors`   — the typed serving failure vocabulary (every submitted
  Future resolves to a prediction or one of these).
"""

from pertgnn_tpu.serve.buckets import make_bucket_ladder, select_bucket
from pertgnn_tpu.serve.engine import InferenceEngine
from pertgnn_tpu.serve.errors import (DeadlineExceeded, DispatchTimeout,
                                      EngineUnhealthy, NonFiniteOutput,
                                      QueueClosed, QueueFull,
                                      RequestQuarantined, ServeError)
from pertgnn_tpu.serve.queue import MicrobatchQueue

__all__ = ["InferenceEngine", "MicrobatchQueue", "make_bucket_ladder",
           "select_bucket", "ServeError", "QueueFull", "QueueClosed",
           "DeadlineExceeded", "RequestQuarantined", "DispatchTimeout",
           "EngineUnhealthy", "NonFiniteOutput"]
