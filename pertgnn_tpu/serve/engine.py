"""The serving engine: shape-bucketed AOT executable cache + fast pack.

Offline prediction (`train/predict.py`) answers "what does the model say
about an entire split" by re-packing the split through the epoch packer.
Serving answers "what does the model say about THIS request, now" — and a
naive `jax.jit` forward retraces and recompiles on every unseen graph
shape, turning a sub-millisecond forward into a multi-second stall. The
engine removes compilation from the request path entirely:

1. at construction it derives a bucket ladder from the dataset's training
   budget (serve/buckets.py) and AOT-compiles ONE executable per rung via
   ``jax.jit(...).lower(...).compile()`` (warmup). With a
   CompileCacheConfig cache dir, each rung executable is persisted by
   the AOT store (pertgnn_tpu/aot/) under a content-hash key — a later
   process's warmup DESERIALIZES instead of compiling (zero fresh
   compiles; ``deserialized`` counts them), and any config/jax/device
   drift invalidates loudly and recompiles;
2. per request (or coalesced microbatch — serve/queue.py) it packs the
   entry mixtures into the smallest fitting rung with the training
   packer's own invariants (batching/pack.py ``pack_single``: receiver-
   sorted edges, reserved pad graph) and dispatches the precompiled
   executable — a pure cache hit in steady state (misses are counted and
   logged; after warmup any miss means the ladder no longer covers the
   request range);
3. every dispatch feeds the latency/pad-waste/bucket counters surfaced by
   ``stats_dict`` (utils/profiling.LatencyRecorder) — the serving metrics
   schema benchmarks/serve_bench.py reports.

The engine itself is single-threaded by design: concurrent callers go
through MicrobatchQueue, whose one worker owns all engine calls.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.telemetry.devmem import sample_device_memory
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import Mixture
from pertgnn_tpu.batching.pack import (ArenaLease, BatchBudget, PackArena,
                                       PackedBatch, pack_single)
from pertgnn_tpu.config import SERVE_DTYPES, Config, resolve_attention_impl
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.serve.buckets import (make_bucket_ladder, pad_waste,
                                       select_bucket)
from pertgnn_tpu.serve.errors import NonFiniteOutput
from pertgnn_tpu.testing import faults
from pertgnn_tpu.utils.profiling import LatencyRecorder

# The per-request lifecycle stages whose latency breakdown the engine
# (pack/dispatch/compute) and the microbatch queue (queue) record —
# stats_dict["stages"] and the serve-bench span percentiles share it.
STAGES = ("queue", "pack", "dispatch", "compute")

log = logging.getLogger(__name__)


class RequestTooLarge(ValueError):
    """The request exceeds the ladder's top rung (== training budget):
    no single batch can hold it. Callers split or reject."""


def abstract_batch(budget: BatchBudget, n_feat: int) -> PackedBatch:
    """The ShapeDtypeStruct tree of a budget-shaped PackedBatch — the AOT
    lowering target. Dtypes mirror pack.pack_examples' buffers exactly;
    any drift fails loudly at dispatch (compiled executables reject
    mismatched signatures)."""
    G = budget.max_graphs + 1
    N, E = budget.max_nodes, budget.max_edges

    def s(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    return PackedBatch(
        x=s((N, n_feat), np.float32), ms_id=s((N,), np.int32),
        node_depth=s((N,), np.float32), node_graph=s((N,), np.int32),
        node_mask=s((N,), np.bool_), pattern_prob=s((N,), np.float32),
        pattern_size=s((N,), np.float32), senders=s((E,), np.int32),
        receivers=s((E,), np.int32), edge_iface=s((E,), np.int32),
        edge_rpctype=s((E,), np.int32), edge_duration=s((E,), np.float32),
        edge_mask=s((E,), np.bool_), entry_id=s((G,), np.int32),
        y=s((G,), np.float32), graph_mask=s((G,), np.bool_))


@dataclasses.dataclass
class _BucketStats:
    dispatches: int = 0
    real_nodes: int = 0
    real_edges: int = 0
    padded_nodes: int = 0
    padded_edges: int = 0


@dataclasses.dataclass
class PackedMicrobatch:
    """A host-packed request microbatch awaiting dispatch — the output
    of ``pack_microbatch`` and the input of ``dispatch_packed``. Pure
    host arrays: building one is safe on any thread while the engine's
    single device thread computes a previous batch (the overlapped
    queue's pipeline, serve/queue.py)."""

    entry_ids: np.ndarray
    idx: int              # ladder rung
    batch: PackedBatch
    n: int                # real nodes
    e_tot: int            # real edges
    # engine-attributed seconds so far (pack, then + dispatch). The
    # aggregate `latency` recorder sums the three phase durations
    # rather than anchoring on wall time: under overlapped dispatch the
    # completion is DEFERRED past the next coalesce window, and that
    # queue idle must not masquerade as engine latency in stats_dict.
    engine_s: float
    # stage -> (tm0, tm1) CLOCK_MONOTONIC stamps of this batch's
    # pack/dispatch/compute phases — what the microbatch queue turns
    # into per-request trace spans (telemetry/tracing.py); monotonic,
    # not perf_counter, because the graftscope collector aligns these
    # stamps across processes
    stage_tm: dict = dataclasses.field(default_factory=dict)
    # lens (pertgnn_tpu/lens/): True = dispatch through the rung's
    # LOCAL-pred-returning program variant (attribution requests);
    # ``local`` is filled by complete_microbatch with the (N,)-shaped
    # local head output, pad rows pinned to -inf in-graph
    want_local: bool = False
    local: np.ndarray | None = None
    # graftwire arena custody: the lease whose buffers ``batch`` views,
    # or None for plain-allocated batches. complete_microbatch releases
    # it for non-lens batches (the np.asarray there forces the device
    # computation, so the buffers are reusable); lens batches keep the
    # lease forever because attribution_rows reads ``batch`` arrays
    # AFTER completion — a deliberate leak, the pool just refills
    arena_lease: ArenaLease | None = None


@dataclasses.dataclass
class InFlightBatch:
    """A dispatched microbatch whose device result has NOT been waited
    on yet — ``dispatch_packed``'s handle, resolved by
    ``complete_microbatch``. ``out`` is the engine's (async) device
    output; ``injected`` carries a fault-plan verdict for the completion
    step to enact."""

    packed: PackedMicrobatch
    out: object
    injected: str | None


class InferenceEngine:
    """Bucketed AOT inference over one trained state.

    Build with ``from_dataset`` (shares the dataset's mixtures, feature
    lookup, and derived budget), then ``warmup()`` once before taking
    traffic. ``predict_microbatch`` is one bucket-shaped dispatch;
    ``predict_many`` greedily splits an arbitrary request list into
    capacity-respecting microbatches (prefix order preserved, so outputs
    align 1:1 with inputs)."""

    def __init__(self, model, state, cfg: Config,
                 mixtures: dict[int, Mixture], lookup: ResourceLookup,
                 budget: BatchBudget, bus=None, store=None,
                 lens_names=None, lens_bounds=None):
        self._cfg = cfg
        # lens (pertgnn_tpu/lens/): optional (ms_vocab, interface_vocab)
        # string arrays so attribution rows carry NAMED calls, and the
        # (num_ms, num_interfaces, num_rpctypes) vocabulary bounds the
        # what-if validator refuses out-of-embedding substitutions
        # against (from_dataset wires the bounds; names need a
        # PreprocessResult, which not every construction path holds)
        self._lens_names = lens_names
        self._lens_bounds = lens_bounds
        # serialized-executable store (pertgnn_tpu/aot/); None = every
        # process compiles its own ladder
        self._store = store
        # injected telemetry bus; None = resolve the process-wide bus
        # LAZILY per emission (self._bus property) — an engine built
        # before telemetry.configure() must not freeze the NoopBus
        self._injected_bus = bus
        self._mixtures = mixtures
        self._lookup = lookup
        self._node_depth_in_x = cfg.model.use_node_depth
        self._n_feat = lookup.num_features + (
            1 if self._node_depth_in_x else 0)
        self.ladder = make_bucket_ladder(budget, cfg.serve)
        # --- quantized serve tier (ServeConfig.serve_dtype) ---
        # f32: params as trained. bf16: the model runs bf16 activations
        # (from_dataset builds it that way); params stay f32. int8: 2-D
        # weights live on device as int8 + per-channel scales
        # (ops/quantize.py) and dequantize IN-GRAPH to bf16 — the
        # compiled executable reads a quarter of the weight bytes.
        self.serve_dtype = cfg.serve.serve_dtype
        if self.serve_dtype not in SERVE_DTYPES:
            raise ValueError(
                f"unknown serve_dtype {self.serve_dtype!r} "
                f"(choose from {SERVE_DTYPES})")
        params = state.params
        if self.serve_dtype == "int8":
            from pertgnn_tpu.ops.quantize import quantize_tree
            params = quantize_tree(params)
        # device-resident once: per-dispatch H2D is then only the batch
        self._variables = jax.tree.map(
            jnp.asarray, {"params": params,
                          "batch_stats": state.batch_stats})
        label_scale = cfg.train.label_scale

        if self.serve_dtype == "int8":
            from pertgnn_tpu.ops.quantize import dequantize_tree

            def _apply(variables, batch):
                deq = {"params": dequantize_tree(variables["params"]),
                       "batch_stats": variables["batch_stats"]}
                return model.apply(deq, batch, training=False)
        else:
            def _apply(variables, batch):
                return model.apply(variables, batch, training=False)

        def step(variables, batch):
            global_pred, _ = _apply(variables, batch)
            return global_pred * label_scale

        def step_local(variables, batch):
            # the attribution variant (pertgnn_tpu/lens/): route the
            # already-computed per-node local head out of the program,
            # pad node rows pinned to -inf IN-GRAPH so downstream top-k
            # can never rank a padded node — graftaudit's padding-taint
            # pass verifies the pin on the traced program
            global_pred, local_pred = _apply(variables, batch)
            local = jnp.where(batch.node_mask,
                              local_pred * label_scale, -jnp.inf)
            return global_pred * label_scale, local

        self._step = step
        self._step_local = step_local
        # lens serving (LensConfig): whether warmup also builds the
        # local-pred program per rung; attribution requests against a
        # cold local ladder are refused at submit (LensDisabled) so
        # nothing ever compiles on the request path
        self.lens_local = cfg.lens.lens_local
        # (rung index, local variant) -> compiled executable
        self._exe: dict[tuple[int, bool], object] = {}
        self._warmed = False
        self.warmup_s: float | None = None
        self.latency = LatencyRecorder()
        # per-stage latency breakdown of the request lifecycle; "queue"
        # is fed by MicrobatchQueue (the engine itself never queues)
        self.stage_latency = {s: LatencyRecorder() for s in STAGES}
        # monotonic (tm0, tm1) stamps per stage of the most recently
        # COMPLETED batch (see complete_microbatch)
        self.last_stage_tm: dict[str, tuple[float, float]] = {}
        self._bucket_stats = {i: _BucketStats()
                              for i in range(len(self.ladder))}
        # graftwire: per-rung packing-buffer pools, built lazily on the
        # first dispatch through a rung (warmup touches every rung, so
        # steady-state serving never allocates a pool)
        self._arenas: dict[int, PackArena] = {}
        self.requests = 0
        self.batches = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.compiles = 0
        # rung executables deserialized from the AOT store instead of
        # freshly compiled (cross-process cold-start elimination)
        self.deserialized = 0
        # -- health (docs/RELIABILITY.md): flipped by the queue's
        # dispatch watchdog on a wedge signature, restored by rebuild()
        self.healthy = True
        self.unhealthy_reason: str | None = None
        self.nan_outputs = 0
        self.rebuilds = 0

    @classmethod
    def from_dataset(cls, dataset, cfg: Config, state, bus=None,
                     store=None, lens_names=None) -> "InferenceEngine":
        model_cfg = cfg.model
        if cfg.serve.serve_dtype in ("bf16", "int8"):
            # the quantized tiers run bf16 activations through the MXU;
            # the param TREE is unchanged (bf16_activations only sets
            # compute dtype), so the trained state binds as-is
            model_cfg = dataclasses.replace(cfg.model,
                                            bf16_activations=True)
        model = make_model(model_cfg, dataset.num_ms, dataset.num_entries,
                           dataset.num_interfaces, dataset.num_rpctypes)
        if store is None and cfg.aot.enabled:
            from pertgnn_tpu import aot
            store = aot.store_from_config(cfg, bus=bus)
        return cls(model, state, cfg, dataset.mixtures, dataset.lookup,
                   dataset.budget, bus=bus, store=store,
                   lens_names=lens_names,
                   lens_bounds=(dataset.num_ms, dataset.num_interfaces,
                                dataset.num_rpctypes))

    # -- executable cache ------------------------------------------------

    def _rung_entry(self, idx: int, local: bool = False):
        """(name, key, components, abstract_args) addressing rung `idx`
        in the AOT store. The name is the rung's shape (the logical
        slot); the key hashes everything the compiled program is welded
        to — so e.g. a hidden_channels or jax upgrade lands in the SAME
        slot with a DIFFERENT key, which is exactly the shape of miss
        the store diagnoses loudly (aot/store.py). ``local`` addresses
        the rung's attribution variant — a distinct slot AND key
        component, so the two program flavors coexist in the store."""
        from pertgnn_tpu import aot

        b = self.ladder[idx]
        abstract_args = (
            jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                         self._variables),
            abstract_batch(b, self._n_feat))
        cfg = self._cfg
        # deliberately NO ServeConfig fields: nothing in it is baked
        # into the step program — the ladder knobs only select WHICH
        # rung shapes exist (already in the slot name + args signature),
        # and queue/transport knobs (flush_deadline_ms, warmup) never
        # reach the compiled program. Keying the whole dataclass would
        # spuriously invalidate every rung on a queue-tuning change —
        # the same restraint _stored_train_eval applies to TrainConfig.
        # serve_dtype is the ONE ServeConfig field baked into the step
        # program (bf16 model dtype / int8 dequantize graph): it must
        # invalidate rung executables. int8 also changes the abstract
        # signature (int8 param leaves), but bf16 does not — hence the
        # explicit key component. cfg.model rides whole, which covers
        # attention_impl / use_pallas_attention / kernel block sizes /
        # blocked_dense_max_cells — and the lens quantile-head width
        # (quantile_taus) — by construction (dataclass fields).
        # lens_local distinguishes the attribution program (it returns
        # the extra local output and bakes in the pad pin).
        key, components = aot.cache_key(
            fn_id="serve.engine.step.v1",
            config={"model": cfg.model,
                    "serve_dtype": cfg.serve.serve_dtype,
                    "label_scale": cfg.train.label_scale,
                    "lens_local": bool(local),
                    "graph_type": cfg.graph_type},
            args_sig=aot.abstract_signature(abstract_args))
        name = (f"serve_rung_g{b.max_graphs}_n{b.max_nodes}"
                f"_e{b.max_edges}{'_local' if local else ''}")
        return name, key, components, abstract_args

    def _compile(self, idx: int, local: bool = False) -> object:
        plan = faults.active()
        if plan is not None:
            plan.fire("serve.compile", entry_ids=None)
        step_fn = self._step_local if local else self._step
        if self._store is not None:
            name, key, components, abstract_args = self._rung_entry(
                idx, local)
            with self._bus.span("serve.compile", bucket=idx):
                exe, outcome = self._store.load_or_build(
                    name, key, components, jax.jit(step_fn),
                    abstract_args)
            self._exe[(idx, local)] = exe
            if outcome == "deserialized":
                self.deserialized += 1
                self._bus.counter("serve.deserialized", bucket=idx)
            else:
                self.compiles += 1
                self._bus.counter("serve.compiles", bucket=idx)
            return exe
        with self._bus.span("serve.compile", bucket=idx):
            exe = jax.jit(step_fn).lower(
                self._variables,
                abstract_batch(self.ladder[idx], self._n_feat)).compile()
        self._exe[(idx, local)] = exe
        self.compiles += 1
        self._bus.counter("serve.compiles", bucket=idx)
        return exe

    def warmup(self) -> "InferenceEngine":
        """AOT-compile every ladder rung — plus, with LensConfig.
        lens_local, every rung's attribution variant — so steady-state
        serving never compiles. Idempotent; returns self for chaining."""
        t0 = time.perf_counter()
        # attribution: which quantized tier + kernel variant the rung
        # executables bake in (docs/OBSERVABILITY.md)
        self._bus.counter("serve.dtype", dtype=self.serve_dtype,
                          impl=resolve_attention_impl(self._cfg.model))
        variants = [False] + ([True] if self.lens_local else [])
        with self._bus.span("serve.warmup", buckets=len(self.ladder)):
            for i in range(len(self.ladder)):
                for local in variants:
                    if (i, local) not in self._exe:
                        self._compile(i, local)
        self.warmup_s = time.perf_counter() - t0
        self._warmed = True
        # post-warmup allocator state (ISSUE 17): every rung executable
        # + weights resident — the serve fleet's steady-state footprint.
        # None-safe no-op on backends without memory_stats (CPU).
        sample_device_memory(self._bus, where="serve_warmup")
        log.info("serve warmup: %d bucket executables in %.2fs "
                 "(%d compiled, %d deserialized%s; ladder %s)",
                 len(self._exe), self.warmup_s, self.compiles,
                 self.deserialized,
                 "; incl. lens-local variants" if self.lens_local else "",
                 [(b.max_nodes, b.max_edges) for b in self.ladder])
        return self

    # -- health / recovery -----------------------------------------------

    def mark_unhealthy(self, reason: str) -> None:
        """Flip the readiness signal (health(), serve_main's
        --health_port probe answer 503). Called by the queue's dispatch
        watchdog when an engine call wedges past its timeout."""
        self.healthy = False
        self.unhealthy_reason = reason
        log.error("engine marked unhealthy: %s", reason)

    def mark_recovered(self) -> None:
        self.healthy = True
        self.unhealthy_reason = None

    def health(self) -> dict:
        """JSON-ready readiness snapshot — what a load balancer polls
        before routing traffic here."""
        return {
            "healthy": self.healthy,
            "reason": self.unhealthy_reason,
            "warmed": self._warmed,
            "executables": len(self._exe),
            "buckets": len(self.ladder),
            "rebuilds": self.rebuilds,
            "nan_outputs": self.nan_outputs,
        }

    def rebuild(self) -> "InferenceEngine":
        """Drop every cached rung executable and re-run warmup — the
        one-shot recovery the watchdog attempts after a wedge. With an
        AOT store (PR 3) the re-warmup is a disk deserialize, not a
        recompile, so recovery costs seconds, not minutes. Raises if the
        rebuild itself fails (the caller decides the cooldown)."""
        self.rebuilds += 1
        self._bus.counter("serve.rebuild")
        log.warning("engine rebuild: dropping %d cached executables and "
                    "re-warming the ladder", len(self._exe))
        self._exe = {}
        self._warmed = False
        self.warmup()
        return self

    # -- request path ----------------------------------------------------

    @property
    def _bus(self):
        if self._injected_bus is not None:
            return self._injected_bus
        return telemetry.get_bus()

    @property
    def bus(self):
        """The engine's telemetry bus (injected, else the process-wide
        bus resolved at each use)."""
        return self._bus

    def record_queue_wait(self, seconds: float, coalesced: int) -> None:
        """The 'queue' stage of the request lifecycle, fed by the
        MicrobatchQueue fronting this engine (the engine itself never
        queues): one call per request when its microbatch leaves the
        queue, `coalesced` = that batch's request count."""
        self.stage_latency["queue"].record_s(seconds)
        self._bus.histogram("serve.queue_wait_ms", seconds * 1e3, level=2,
                            coalesced=coalesced)

    def request_size(self, entry_id: int) -> tuple[int, int]:
        """(nodes, edges) one request for this entry costs — the queue's
        capacity accounting. Counterfactual (edited) requests keep
        using the BASE mixture's sizes as a safe upper bound: edits
        only drop or substitute (lens/whatif.py asserts it), so an
        edited batch is under-filled at worst, never over-packed."""
        m = self._mixtures[int(entry_id)]
        return m.num_nodes, m.num_edges

    def base_mixture(self, entry_id: int) -> Mixture:
        """The entry's unedited mixture — what lens/whatif.py edits and
        lens/attribute.py maps attribution rows against."""
        return self._mixtures[int(entry_id)]

    def apply_whatif(self, entry_id: int, edits):
        """The entry's mixture under the request's counterfactual edits
        (pure; raises the typed WhatIfRefused) — validated with THIS
        dataset's vocabulary bounds so a substitution outside the
        embedding tables is refused at submit, not discovered as a
        clamped gather at dispatch."""
        from pertgnn_tpu.lens.whatif import apply_whatif

        bounds = self._lens_bounds or (None, None, None)
        return apply_whatif(
            self.base_mixture(entry_id), edits,
            num_ms=bounds[0], num_interfaces=bounds[1],
            num_rpctypes=bounds[2],
            feature_all_stage_copies=(
                self._cfg.model.feature_all_stage_copies))

    def attribution_rows(self, packed: PackedMicrobatch, slot: int,
                         k: int, mixture: Mixture) -> list[dict]:
        """Top-k attribution rows for graph ``slot`` of a completed
        lens microbatch (lens/attribute.py): the slot's real-node slice
        of the local output, ranked and mapped back through the arena
        vocabulary. Pad rows cannot appear — they were pinned to -inf
        in-graph and the slice below selects real lanes only."""
        from pertgnn_tpu.lens.attribute import top_k_rows

        if packed.local is None:
            raise ValueError("attribution requested from a microbatch "
                             "dispatched without the local variant")
        sel = ((np.asarray(packed.batch.node_graph) == slot)
               & np.asarray(packed.batch.node_mask))
        names = self._lens_names or (None, None)
        return top_k_rows(packed.local[sel], mixture,
                          min(int(k), self._cfg.lens.lens_top_k),
                          ms_names=names[0], iface_names=names[1])

    def pack_microbatch(self, entry_ids, ts_buckets,
                        max_rung: int | None = None,
                        mixtures: list | None = None,
                        want_local: bool = False) -> PackedMicrobatch:
        """Host half of a dispatch: bucket selection + ``pack_single``
        into the smallest fitting rung. Pure host work over read-only
        state — the overlapped queue runs this on its worker thread
        while the device computes the previous batch.

        ``max_rung`` caps the ladder search (the brownout DOWNGRADE:
        best-effort traffic served through rung `max_rung` and below —
        normally 0, the cheapest shape; fleet/shield.py). The cap is
        SOFT: a microbatch that fits no capped rung falls back to the
        full ladder (a downgrade degrades cost, never correctness), and
        every rung executable already exists from warmup so a downgrade
        can never trigger a compile.

        ``mixtures`` (aligned per request; None entries = base) carries
        counterfactually edited mixtures (lens/whatif.py) — packed
        under the request's REAL entry id, sized by the ACTUAL (edited)
        arrays, selected into the existing ladder: since edits never
        grow the graph and every rung executable exists from warmup, a
        what-if dispatch can never compile. ``want_local`` dispatches
        through the rung's attribution (local-returning) program.

        Raises RequestTooLarge if the microbatch exceeds the top rung —
        callers that cannot pre-size (predict_many, the queue) split
        instead."""
        entry_ids = np.asarray(entry_ids)
        g = len(entry_ids)
        mixes = [self._mixtures[int(e)]
                 if (mixtures is None or mixtures[i] is None)
                 else mixtures[i] for i, e in enumerate(entry_ids)]
        any_override = mixtures is not None and any(
            m is not None for m in mixtures)
        n = sum(m.num_nodes for m in mixes)
        e_tot = sum(m.num_edges for m in mixes)
        idx = None
        if max_rung is not None:
            idx = select_bucket(self.ladder[:max_rung + 1], g, n, e_tot)
            if idx is None:
                self._bus.counter("serve.downgrade_overflow", graphs=g,
                                  max_rung=max_rung)
        if idx is None:
            idx = select_bucket(self.ladder, g, n, e_tot)
        if idx is None:
            raise RequestTooLarge(
                f"microbatch of {g} graphs ({n} nodes, {e_tot} edges) "
                f"exceeds the top bucket {self.ladder[-1]}")
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        # arena lease (graftwire): plain batches pack into pooled
        # buffers released at complete; lens batches pack fresh — their
        # arrays outlive completion (attribution_rows reads them), so a
        # lease would either dangle or leak every time
        lease = None
        if not want_local:
            arena = self._arenas.get(idx)
            if arena is None:
                arena = self._arenas[idx] = PackArena(self.ladder[idx],
                                                      self._n_feat)
            lease = arena.acquire()
        with self.stage_latency["pack"].time(), \
                self._bus.span("serve.pack", level=2, bucket=idx,
                               graphs=g):
            batch = pack_single(self._mixtures, entry_ids,
                                np.asarray(ts_buckets), self.ladder[idx],
                                self._lookup,
                                node_depth_in_x=self._node_depth_in_x,
                                mixture_of=mixes if any_override else None,
                                into=lease)
        return PackedMicrobatch(entry_ids=entry_ids, idx=idx, batch=batch,
                                n=n, e_tot=e_tot,
                                engine_s=time.perf_counter() - t0,
                                stage_tm={"pack": (tm0, time.monotonic())},
                                want_local=bool(want_local),
                                arena_lease=lease)

    def dispatch_packed(self, packed: PackedMicrobatch) -> InFlightBatch:
        """Device half, part 1: resolve the rung executable and launch
        it (async — the returned handle's ``out`` is an in-flight device
        computation). Single-threaded like every engine device call:
        exactly one dispatch/complete runs at a time (the queue's worker
        or its watchdog dispatcher owns the order)."""
        bus = self._bus
        idx = packed.idx
        # fault-injection hook (pertgnn_tpu/testing/faults.py): "error"
        # raises here, "wedge" stalls here (mid-dispatch, where a real
        # device-transport hang lives), "nan" marks the handle so the
        # completion step corrupts the output and the finite guard must
        # catch it
        plan = faults.active()
        injected = (plan.fire("serve.dispatch",
                              entry_ids=packed.entry_ids)
                    if plan is not None else None)
        # engine_s accounting starts BEFORE executable resolution: a
        # post-warmup cache miss compiles on the serve path, and that
        # multi-second stall must show up in the engine latency
        # percentiles (as it did when predict_microbatch was one piece)
        t0 = time.perf_counter()
        exe_key = (idx, packed.want_local)
        if exe_key in self._exe:
            self.cache_hits += 1
            bus.counter("serve.cache_hit", bucket=idx, level=2)
            exe = self._exe[exe_key]
        else:
            self.cache_misses += 1
            bus.counter("serve.cache_miss", bucket=idx,
                        after_warmup=self._warmed)
            if self._warmed:
                log.warning(
                    "executable cache miss AFTER warmup for bucket %s "
                    "— the ladder no longer covers the request range",
                    self.ladder[idx])
            exe = self._compile(idx, packed.want_local)
        tm0 = time.monotonic()
        with self.stage_latency["dispatch"].time(), \
                bus.span("serve.dispatch", level=2, bucket=idx):
            out = exe(self._variables, packed.batch)
        packed.stage_tm["dispatch"] = (tm0, time.monotonic())
        packed.engine_s += time.perf_counter() - t0
        return InFlightBatch(packed=packed, out=out, injected=injected)

    def complete_microbatch(self, inflight: InFlightBatch) -> np.ndarray:
        """Device half, part 2: block until the in-flight result is
        host-readable, run the finite-output guard, account the batch.
        Returns per-request predictions in request order (label units)."""
        bus = self._bus
        packed = inflight.packed
        idx, g = packed.idx, len(packed.entry_ids)
        entry_ids, n, e_tot = packed.entry_ids, packed.n, packed.e_tot
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        with self.stage_latency["compute"].time(), \
                bus.span("serve.compute", level=2, bucket=idx):
            if packed.want_local:
                pred_dev, local_dev = inflight.out
                pred = np.asarray(pred_dev)[:g]
                packed.local = np.asarray(local_dev)
            else:
                pred = np.asarray(inflight.out)[:g]
        packed.stage_tm["compute"] = (tm0, time.monotonic())
        packed.engine_s += time.perf_counter() - t0
        if inflight.injected == "nan":
            pred = np.full_like(pred, np.nan)
        # output guard: NEVER hand garbage to a caller. A non-finite
        # prediction fails the batch (the queue's bisect then isolates
        # the offending request; direct callers see the typed error
        # instead of silently propagating NaN). Multi-quantile
        # predictions are (G, T): a request fails if ANY column is bad.
        finite_rows = (np.isfinite(pred) if pred.ndim == 1
                       else np.isfinite(pred).all(axis=-1))
        if not finite_rows.all():
            bad = entry_ids[~finite_rows]
            self.nan_outputs += 1
            bus.counter("serve.nan_outputs", bucket=idx, graphs=int(g))
            log.error("non-finite model output for %d/%d requests "
                      "(entries %s) — quarantining the batch",
                      int((~finite_rows).sum()), g,
                      bad[:8].tolist())
            raise NonFiniteOutput(
                f"model returned non-finite predictions for entries "
                f"{bad[:8].tolist()}")
        if packed.local is not None:
            # the local vector's REAL lanes get the same guard (-inf on
            # pad lanes is the pin, by design — not an error)
            nm = np.asarray(packed.batch.node_mask)
            if not np.isfinite(packed.local[nm]).all():
                self.nan_outputs += 1
                bus.counter("serve.nan_outputs", bucket=idx,
                            graphs=int(g))
                raise NonFiniteOutput(
                    "model returned non-finite LOCAL predictions for "
                    "real nodes — quarantining the batch")
        # stage stamps of the batch that JUST completed, for the queue's
        # per-request trace spans: engine device calls are strictly
        # serialized (one worker/dispatcher thread), so "last completed"
        # is unambiguous when the queue reads it in its settle step.
        # (Lens attribution deliberately does NOT ride engine state
        # like this: a watchdog-abandoned zombie thread could clobber
        # it between completion and settle, so the queue threads the
        # PackedMicrobatch object through its own call chain instead.)
        self.last_stage_tm = packed.stage_tm
        # pack + dispatch + compute phase durations, NOT wall since pack
        # start: an overlapped completion is deferred past the next
        # coalesce window, and that queue idle belongs to
        # serve.request_total_ms (the queue's metric), not here
        self.latency.record_s(packed.engine_s)
        self.requests += g
        self.batches += 1
        bucket = self.ladder[idx]
        bs = self._bucket_stats[idx]
        bs.dispatches += 1
        bs.real_nodes += n
        bs.real_edges += e_tot
        bs.padded_nodes += bucket.max_nodes
        bs.padded_edges += bucket.max_edges
        bus.histogram("serve.pad_waste", pad_waste(bucket, n, e_tot),
                      bucket=idx, level=2)
        # arena custody ends HERE for plain batches: the np.asarray
        # above forced the device computation, so nothing reads the
        # packed host buffers again — return them for the next pack.
        # (Error paths above deliberately leak: a quarantined batch's
        # lease is dropped and the pool refills on the next acquire.)
        if packed.arena_lease is not None:
            packed.arena_lease.release()
            packed.arena_lease = None
        return pred

    def predict_microbatch(self, entry_ids, ts_buckets,
                           max_rung: int | None = None,
                           mixtures: list | None = None,
                           want_local: bool = False) -> np.ndarray:
        """One bucket-shaped dispatch for a coalesced microbatch —
        pack → dispatch → complete, synchronously. The overlapped queue
        calls the three phases itself so the pack of batch k+1 runs
        while the device computes batch k. ``max_rung`` is the brownout
        rung cap; ``mixtures``/``want_local`` are the lens request
        variants (see pack_microbatch)."""
        return self.complete_microbatch(
            self.dispatch_packed(self.pack_microbatch(
                entry_ids, ts_buckets, max_rung=max_rung,
                mixtures=mixtures, want_local=want_local)))

    def predict_many(self, entry_ids, ts_buckets) -> np.ndarray:
        """Predictions for an arbitrary request list, split greedily into
        capacity-respecting microbatches (prefix order preserved — output
        row i answers input row i)."""
        entry_ids = np.asarray(entry_ids)
        ts_buckets = np.asarray(ts_buckets)
        top = self.ladder[-1]
        max_g = top.max_graphs
        preds: list[np.ndarray] = []
        i = 0
        while i < len(entry_ids):
            g = n = e = 0
            j = i
            while j < len(entry_ids) and g < max_g:
                dn, de = self.request_size(entry_ids[j])
                if g and (n + dn > top.max_nodes or e + de > top.max_edges):
                    break
                g, n, e = g + 1, n + dn, e + de
                j += 1
            preds.append(self.predict_microbatch(entry_ids[i:j],
                                                 ts_buckets[i:j]))
            i = j
        return (np.concatenate(preds) if preds
                else np.zeros(0, np.float32))

    # -- instrumentation -------------------------------------------------

    def pad_waste_ratio(self) -> float:
        """Aggregate fraction of dispatched node+edge slots that were
        padding (serve/buckets.pad_waste per dispatch, pooled)."""
        real = sum(b.real_nodes + b.real_edges
                   for b in self._bucket_stats.values())
        padded = sum(b.padded_nodes + b.padded_edges
                     for b in self._bucket_stats.values())
        return (padded - real) / padded if padded else 0.0

    def stats_dict(self) -> dict:
        """JSON-ready serving counters — the schema serve_bench reports
        and the serving docs describe."""
        buckets = []
        for i, b in enumerate(self.ladder):
            s = self._bucket_stats[i]
            buckets.append({
                **dataclasses.asdict(b),
                "dispatches": s.dispatches,
                "pad_waste": (pad_waste(
                    b, s.real_nodes / s.dispatches,
                    s.real_edges / s.dispatches) if s.dispatches else None),
            })
        return {
            "requests": self.requests,
            "batches": self.batches,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "compiles": self.compiles,
            "deserialized": self.deserialized,
            "healthy": self.healthy,
            "rebuilds": self.rebuilds,
            "nan_outputs": self.nan_outputs,
            "lens_local": self.lens_local,
            "warmup_s": self.warmup_s,
            "pad_waste_ratio": self.pad_waste_ratio(),
            "latency": self.latency.summary_dict(),
            # per-stage request-lifecycle percentiles (engine stages;
            # "queue" is populated only when a MicrobatchQueue fronts
            # this engine)
            "stages": {s: r.summary_dict()
                       for s, r in self.stage_latency.items()},
            "buckets": buckets,
        }

    def publish_stats(self) -> dict:
        """Emit the aggregate counters onto the bus at BASIC level (the
        per-dispatch events above are trace-level) and return stats_dict.
        Serving CLIs/benches call this once at end of run so a basic-level
        JSONL stream still carries cache hit/miss totals and per-bucket
        pad waste."""
        stats = self.stats_dict()
        bus = self._bus
        # gauges, not counters: these are lifetime TOTALS (snapshots), and
        # publish_stats may be called repeatedly on a long-lived engine —
        # a consumer summing counter deltas must not double-count them
        bus.gauge("serve.requests", self.requests)
        bus.gauge("serve.batches", self.batches)
        bus.gauge("serve.cache_hits_total", self.cache_hits)
        bus.gauge("serve.cache_misses_total", self.cache_misses)
        bus.gauge("serve.deserialized_total", self.deserialized)
        bus.gauge("serve.pad_waste_ratio", stats["pad_waste_ratio"])
        for i, b in enumerate(stats["buckets"]):
            if b["dispatches"]:
                bus.gauge("serve.bucket_pad_waste", b["pad_waste"],
                          bucket=i, dispatches=b["dispatches"],
                          max_nodes=b["max_nodes"],
                          max_edges=b["max_edges"])
        bus.event("serve.stats", fields=stats)
        return stats
