"""Deadline-based microbatching queue for concurrent serving traffic.

Per-dispatch overhead (host pack + H2D + program launch) is the serving
twin of the per-step dispatch latency the train loop amortizes with
lax.scan (TrainConfig.scan_chunk): a single-graph forward pays the same
fixed cost as a 16-graph one. The queue coalesces requests that arrive
within a flush deadline into ONE bucket-shaped microbatch, amortizing
that fixed cost across concurrent callers exactly the way the epoch
packer amortizes padding across a batch.

Semantics:
- `submit` returns a Future; `predict` is the blocking convenience.
- A batch flushes when (a) the oldest queued request has waited
  `flush_deadline_ms`, or (b) the pending set would overflow the engine's
  top bucket (graphs, nodes, or edges) — whichever comes first. Deadline
  0 degrades to per-request dispatch (lowest latency, no amortization).
- One worker thread owns the engine-call ORDER (batches are formed and
  resolved strictly serially), so per-request prediction alignment is
  preserved by construction: each flush packs its requests in submission
  order and fans the engine's per-request outputs back to the matching
  futures.
- **Overlapped dispatch** (ServeConfig.overlap_dispatch, default on):
  the worker packs microbatch k+1 on the host while the device computes
  k — one batch in flight, its result resolution deferred to a
  completion step (engine.pack_microbatch / dispatch_packed /
  complete_microbatch). An in-flight batch is always completed before
  the worker would block on an empty queue, before the next dispatch,
  and at close — a future never waits on traffic that may never arrive.
  Failure handling of a deferred completion routes through exactly the
  synchronous handlers below (watchdog trip -> recover -> sync retry;
  any other error -> sync bisect), so every fault invariant holds
  unchanged under overlap (benchmarks/pipeline_bench.py re-asserts the
  chaos scenarios on the overlapped path).

Lens request variants (pertgnn_tpu/lens/, docs/GUIDE.md §13) ride
``submit(lens=...)`` through the SAME machinery: multi-quantile heads
resolve futures to (T,)-vectors instead of scalars, attribution
requests batch separately (they dispatch the rung's local-pred program
variant) and resolve to a LensResult, and counterfactual edits are
applied + validated AT SUBMIT (a refused edit fast-fails with the typed
WhatIfRefused) so the worker packs pre-validated arrays — every fault
path below (bisect, watchdog, shed, deadline) applies to them
unchanged.

Failure semantics (docs/RELIABILITY.md) — a submitted Future ALWAYS
resolves, to a prediction or to a typed serve error (serve/errors.py):

- **admission control**: submit past `max_pending` queued requests
  sheds LOWEST-SLO-CLASS-FIRST (fleet/shield.py): a higher-class
  arrival evicts the newest queued request of the lowest class present
  (its Future resolves with the typed ``Shed`` — never lost), otherwise
  the arrival fast-fails with ``Shed`` (a QueueFull subclass; counters
  ``serve.shed`` / ``serve.shed_by_class``) — under overload the queue
  sheds instead of growing without bound, and sheds the traffic whose
  SLO tolerates it;
- **brownout downgrade**: requests flagged ``downgrade`` (the router's
  brownout verdict on best-effort traffic) batch separately and
  dispatch through the engine's CHEAPEST ladder rung
  (``pack_microbatch(max_rung=0)``, counter
  ``serve.brownout_downgrade``) — service degrades before anyone is
  shed;
- **per-request deadlines**: a request not dispatched within
  `request_deadline_ms` resolves with DeadlineExceeded (counter
  ``serve.deadline_exceeded``);
- **poisoned-batch quarantine**: a failing microbatch is bisect-retried
  so only the offending request gets the exception while innocent
  co-batched callers still get their predictions; an entry isolated as
  the poisoner of `quarantine_threshold` batches is rejected at submit
  with RequestQuarantined (counters ``serve.poisoned`` /
  ``serve.quarantined``);
- **dispatch watchdog**: with `dispatch_timeout_s` > 0 engine calls run
  on an abandonable helper thread; a call that wedges past the timeout
  (the device-transport hang signature, which raises nothing) trips the
  watchdog (counter ``serve.watchdog_trip``): the engine is marked
  unhealthy, ONE rebuild-from-AOT-store recovery is attempted (cheap —
  PR 3 made recompiles disk hits; counter ``serve.recovered``) and the
  batch retried once; while unhealthy, batches fail fast with
  EngineUnhealthy for a cooldown instead of queuing behind a dead
  device. NOTE a tripped watchdog abandons the wedged helper thread
  mid-engine-call; the single-threaded-engine invariant is then
  best-effort until that thread unwedges or the process exits — the
  rebuilt executables are fresh objects, so the zombie can only touch
  stale ones.
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
from concurrent.futures import Future

import numpy as np

from pertgnn_tpu.fleet import shield
from pertgnn_tpu.lens.request import LensResult
from pertgnn_tpu.serve.engine import InferenceEngine
from pertgnn_tpu.serve.errors import (DeadlineExceeded, DispatchTimeout,
                                      EngineUnhealthy, LensDisabled,
                                      QueueClosed, RequestQuarantined,
                                      Shed, WhatIfRefused)

log = logging.getLogger(__name__)

# pending-entry tuple layout (submission order is load-bearing):
# (entry_id, ts_bucket, arrival_time, deadline_abs, future, trace,
#  slo, downgrade, lens)
# trace is None (untraced) or a _ReqTrace; slo is the request's SLO
# class name (fleet/shield.py — admission sheds lowest-class-first);
# downgrade marks brownout'd best-effort traffic the engine serves
# through the cheapest ladder rung; lens is None (a plain request) or
# a _LensReq (pertgnn_tpu/lens/): attribution k + the counterfactually
# edited mixture, resolved AT SUBMIT so a refused edit fast-fails the
# caller and the worker packs pre-validated arrays. Batches never mix
# downgrade states, and never mix attribution (local-program) requests
# with plain ones — the two dispatch through different rung programs.


class _LensReq:
    """One admitted lens request's resolved variant state riding its
    pending tuple: ``k`` (top-k attribution rows; 0 = none) and
    ``mixture`` (the what-if-edited Mixture, None = the base).
    ``num_edits`` feeds the post-admission lens.whatif counter."""

    __slots__ = ("k", "mixture", "num_edits")

    def __init__(self, k: int, mixture, num_edits: int = 0):
        self.k = k
        self.mixture = mixture
        self.num_edits = num_edits

    @property
    def wants_local(self) -> bool:
        return self.k > 0


class _ReqTrace:
    """One traced request's context riding its pending tuple.

    ``owns_root`` distinguishes a root the queue STARTED (standalone
    serving — the queue finishes the trace at settle/fail) from a
    context ADOPTED off the fleet transport (the router owns the root;
    the worker-side queue only contributes stage spans)."""

    __slots__ = ("ctx", "tm_submit", "owns_root")

    def __init__(self, ctx, tm_submit: float, owns_root: bool):
        self.ctx = ctx
        self.tm_submit = tm_submit
        self.owns_root = owns_root


def _call_abandonable(fn, timeout: float, name: str):
    """Run ``fn()`` on a daemon thread and wait at most `timeout`.

    Returns (finished, box) with box["value"] or box["error"] when
    finished. On timeout the thread is ABANDONED, not joined — a wedged
    device call raises nothing, ever, and a daemon thread dies with the
    process. (ThreadPoolExecutor is unusable for this: its workers are
    non-daemon and joined by concurrent.futures' atexit hook even after
    shutdown(wait=False), so one truly wedged call would hang process
    exit forever.)"""
    box: dict = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = fn()
        except BaseException as exc:  # lint: allow-silent-except
            box["error"] = exc  # consumed by the waiting caller
        finally:
            done.set()

    threading.Thread(target=run, daemon=True, name=name).start()
    return done.wait(timeout), box


class _Dispatcher:
    """One persistent daemon thread owning engine calls so the queue
    worker can TIME OUT a wedged call and abandon it (a blocked device
    call raises nothing, ever — join is not an option). After a timeout
    the dispatcher is dead: its thread may still be inside the engine;
    the queue builds a fresh one for the next call.

    Calls are arbitrary thunks (`fn`) so the overlapped-dispatch path
    can run its two device phases — launch (`dispatch_packed`) and
    completion (`complete_microbatch`) — through the same single thread
    that owns the engine-call ORDER. A PERSISTENT daemon thread, unlike
    ``_call_abandonable``'s per-call spawn, so steady-state dispatches
    pay no thread start; the why-not-ThreadPoolExecutor rationale lives
    on _call_abandonable."""

    def __init__(self, engine: InferenceEngine):
        self._engine = engine
        self._calls: list = []
        self._have_call = threading.Semaphore(0)
        self.dead = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-dispatch")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            self._have_call.acquire()
            item = self._calls.pop(0)
            if item is None:
                return
            box, fn = item
            try:
                box["value"] = fn()
            except BaseException as exc:  # lint: allow-silent-except
                box["error"] = exc  # re-raised by call() on the worker
            box["done"].set()
            if self.dead:
                return

    def call(self, fn, timeout: float, what: str):
        box: dict = {"done": threading.Event()}
        self._calls.append((box, fn))
        self._have_call.release()
        if not box["done"].wait(timeout):
            self.dead = True
            raise DispatchTimeout(
                f"{what} exceeded {timeout:g}s (wedge signature); "
                f"abandoning the dispatch thread")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def close(self) -> None:
        self._calls.append(None)
        self._have_call.release()


class MicrobatchQueue:
    """Thread-safe request front-end over a (single-threaded) engine."""

    def __init__(self, engine: InferenceEngine,
                 flush_deadline_ms: float | None = None,
                 max_graphs: int | None = None,
                 max_pending: int | None = None,
                 request_deadline_ms: float | None = None,
                 dispatch_timeout_s: float | None = None,
                 quarantine_threshold: int | None = None,
                 overlap_dispatch: bool | None = None,
                 trace_roots: bool = True):
        cfg = engine._cfg.serve
        self._engine = engine
        # whether THIS queue is a trace front door (standalone serving).
        # A fleet worker's queue sets False: its requests arrive with a
        # router-owned context over the transport, and head-sampling
        # twice would fork the fleet's sampling decision per process.
        self._trace_roots = trace_roots
        self._deadline_s = (cfg.flush_deadline_ms
                            if flush_deadline_ms is None
                            else flush_deadline_ms) / 1e3
        top = engine.ladder[-1]
        self._max_graphs = min(max_graphs or top.max_graphs, top.max_graphs)
        self._max_nodes = top.max_nodes
        self._max_edges = top.max_edges
        # brownout'd (downgraded) batches are capped at the CHEAPEST
        # rung's capacity so they dispatch through its small executable
        # (fleet/shield.py; engine.pack_microbatch max_rung=0)
        rung0 = engine.ladder[0]
        self._dg_graphs = min(self._max_graphs, rung0.max_graphs)
        self._dg_nodes = rung0.max_nodes
        self._dg_edges = rung0.max_edges
        self._max_pending = (cfg.max_pending if max_pending is None
                             else max_pending)
        self._req_deadline_s = (cfg.request_deadline_ms
                                if request_deadline_ms is None
                                else request_deadline_ms) / 1e3
        self._dispatch_timeout_s = (cfg.dispatch_timeout_s
                                    if dispatch_timeout_s is None
                                    else dispatch_timeout_s)
        self._quarantine_threshold = (cfg.quarantine_threshold
                                      if quarantine_threshold is None
                                      else quarantine_threshold)
        # overlapped dispatch: pack microbatch k+1 while the device
        # computes k (one batch in flight, completion deferred)
        self._overlap = (cfg.overlap_dispatch if overlap_dispatch is None
                         else overlap_dispatch)
        # (batch, InFlightBatch) dispatched but not yet completed —
        # worker-thread-only state
        self._inflight: tuple[list, object] | None = None
        # fail-fast window after a watchdog trip whose recovery failed
        self._cooldown_s = max(1.0, self._dispatch_timeout_s)
        self._cooldown_until = 0.0
        self._rebuild_timeout_s = max(30.0, 5 * self._dispatch_timeout_s)
        self._dispatcher: _Dispatcher | None = None
        # poisoned-batch bookkeeping: entry_id -> isolated failure count
        self._offenders: dict[int, int] = {}
        self._quarantined: set[int] = set()
        # counters mirrored to the bus (serve.* names); stats_dict()
        # snapshots them for serve_main's metrics JSON
        self.shed = 0
        self.deadline_exceeded = 0
        self.poisoned = 0
        self.quarantine_rejected = 0
        self.watchdog_trips = 0
        self.recovered = 0
        self.overlapped = 0
        # requests taken from the pending set whose futures have not
        # resolved yet — the "in flight" half of the probe body (the
        # pending set is the other); maintained via done-callbacks so
        # bisect splits / retries cannot double-count. (Distinct from
        # _inflight, the overlapped-dispatch batch slot below.)
        self._inflight_reqs = 0
        # per-class counts of typed request failures (resolved futures
        # AND admission rejects) — what the extended health probe and
        # the fleet router read without scraping telemetry JSONL
        self.error_counts: collections.Counter = collections.Counter()
        self._pending: list[tuple[int, int, float, float, Future]] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        # an EXTERNAL drain request (begin_drain — SIGTERM); close()
        # also stops admissions via _draining but is not "a drain"
        self._drain_requested = False
        self._drain_announced = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="serve-microbatch")
        self._worker.start()

    # -- client side -----------------------------------------------------

    def submit(self, entry_id: int, ts_bucket: int, trace=None,
               slo: str | None = None, downgrade: bool = False,
               lens=None) -> Future:
        """Enqueue one request; the Future resolves to its predicted
        latency (label units) once its microbatch is served, or to a
        typed serve error. Raises QueueClosed / Shed (a QueueFull) /
        RequestQuarantined at admission (fast-fail: a rejected request
        never occupies a pending slot). ``trace`` is an adopted
        TraceContext propagated over the fleet transport; None lets the
        queue head-sample its own root (standalone serving).

        ``slo`` is the request's SLO class (fleet/shield.py; default
        "standard"): at a full pending set admission sheds LOWEST-
        CLASS-FIRST — a higher-class arrival evicts the newest queued
        request of the lowest class present (its Future resolves with
        Shed — never lost), otherwise the arrival itself is shed.
        ``downgrade`` marks brownout'd best-effort traffic the engine
        serves through the cheapest ladder rung.

        ``lens`` (a pertgnn_tpu/lens LensRequest, or None) attaches the
        distributional/what-if request variants: ``attribute_k`` > 0
        resolves the Future to a LensResult carrying top-k root-cause
        attribution (requires LensConfig.lens_local — else the typed
        LensDisabled at admission), ``edits`` serves the prediction of
        a counterfactually edited call graph (applied + validated HERE,
        so a refused edit fast-fails the caller with WhatIfRefused and
        never occupies a pending slot)."""
        eid = int(entry_id)
        slo_cls = shield.DEFAULT_CLASS if slo is None else slo
        shield.class_priority(slo_cls)  # unknown class fails the caller
        # size it NOW so an entry the engine has never seen fails the
        # caller, not the shared worker
        self._engine.request_size(eid)
        lens_req = None
        if lens is not None and not getattr(lens, "is_default", False):
            lens_req = self._resolve_lens(eid, lens)
        fut: Future = Future()
        # trace identity BEFORE the lock (a dice roll + urandom must not
        # serialize the admission path); a rejected submit just discards
        # the context — nothing was emitted, so no orphan root
        if trace is not None:
            tr = _ReqTrace(trace, time.monotonic(), owns_root=False)
        elif self._trace_roots:
            ctx = self._engine.bus.start_trace()
            tr = (_ReqTrace(ctx, time.monotonic(), owns_root=True)
                  if ctx is not None else None)
        else:
            tr = None
        reject = counter = None
        lowest_queued = slo_cls
        evicted = None
        with self._wake:
            if self._closed or self._draining:
                reject = QueueClosed(
                    "MicrobatchQueue is closed"
                    + (" (draining)" if self._draining else ""))
            elif eid in self._quarantined:
                self.quarantine_rejected += 1
                counter = "serve.quarantine_rejected"
                reject = RequestQuarantined(
                    f"entry {eid} is quarantined (poisoned "
                    f"{self._offenders.get(eid, 0)} microbatches)")
            elif len(self._pending) >= self._max_pending:
                pending_classes = [p[6] for p in self._pending]
                victim_i = shield.shed_victim_index(pending_classes,
                                                    slo_cls)
                if victim_i is None:
                    self.shed += 1
                    counter = "serve.shed"
                    # evidence tag: the lowest class queued at the
                    # moment of rejection (see fleet/router.py submit)
                    lowest_queued = max(
                        pending_classes, key=shield.class_priority,
                        default=slo_cls)
                    reject = Shed(
                        f"pending set is at "
                        f"max_pending={self._max_pending}; {slo_cls} "
                        f"request shed", slo=slo_cls)
                else:
                    # lowest-class-first: evict the newest queued
                    # request of the lowest class to admit this one —
                    # its future resolves OUTSIDE the lock below
                    evicted = self._pending.pop(victim_i)
                    self.shed += 1
                    self.error_counts["Shed"] += 1
                    self._admit_locked(eid, ts_bucket, fut, tr, slo_cls,
                                       downgrade, lens_req)
            else:
                self._admit_locked(eid, ts_bucket, fut, tr, slo_cls,
                                   downgrade, lens_req)
            if reject is not None:
                self.error_counts[type(reject).__name__] += 1
        if evicted is not None:
            bus = self._engine.bus
            bus.counter("serve.shed", entry_id=evicted[0])
            bus.counter("serve.shed_by_class", slo=evicted[6],
                        mode="evict", entry_id=evicted[0])
            evicted[4].set_exception(Shed(
                f"evicted at admission: a {slo_cls} arrival outranked "
                f"this queued {evicted[6]} request at "
                f"max_pending={self._max_pending}", slo=evicted[6]))
            etr = evicted[5]
            if etr is not None and etr.owns_root:
                bus.finish_trace("trace.request", etr.ctx, etr.tm_submit,
                                 time.monotonic(), outcome="error",
                                 error="Shed", entry_id=evicted[0])
        if reject is not None:
            # counter emission OUTSIDE the lock: a telemetry disk write
            # must not serialize the admission path — under overload the
            # shed fast-path fires on every submit, exactly when the
            # worker and other clients are contending for this lock
            if counter is not None:
                self._engine.bus.counter(counter, entry_id=eid)
            if isinstance(reject, Shed):
                self._engine.bus.counter("serve.shed_by_class",
                                         slo=slo_cls, mode="reject",
                                         entry_id=eid,
                                         lowest_queued=lowest_queued)
            raise reject
        if lens_req is not None and lens_req.mixture is not None:
            # ADMITTED counterfactual traffic only (the documented
            # semantics): edits validated AND a pending slot taken
            self._engine.bus.counter("lens.whatif", entry_id=eid,
                                     edits=lens_req.num_edits)
        return fut

    def _resolve_lens(self, eid: int, lens) -> _LensReq:
        """Validate + resolve one request's lens variants at admission
        (fast-fail, outside the queue lock — whatif application is pure
        numpy over read-only arenas). Raises the typed LensDisabled /
        WhatIfRefused; the rejected request never occupies a slot."""
        k = int(getattr(lens, "attribute_k", 0))
        edits = tuple(getattr(lens, "edits", ()))
        if k > 0 and not self._engine.lens_local:
            with self._lock:
                self.error_counts["LensDisabled"] += 1
            raise LensDisabled(
                "attribution requested but the engine's local-pred rung "
                "programs are not warmed (LensConfig.lens_local off) — "
                "nothing compiles on the request path, so the request "
                "is refused instead")
        mixture = None
        if edits:
            try:
                mixture = self._engine.apply_whatif(eid, edits)
            except WhatIfRefused:
                with self._lock:
                    self.error_counts["WhatIfRefused"] += 1
                self._engine.bus.counter("lens.whatif_refused",
                                         entry_id=eid)
                raise
            # the lens.whatif counter is emitted by submit() only once
            # the request is ACTUALLY admitted — a shed/closed reject
            # after a clean edit must not count as admitted traffic
        return _LensReq(k, mixture, len(edits))

    def _admit_locked(self, eid: int, ts_bucket: int, fut: Future,
                      tr, slo_cls: str, downgrade: bool,
                      lens_req: _LensReq | None = None) -> None:
        deadline = (time.perf_counter() + self._req_deadline_s
                    if self._req_deadline_s > 0 else math.inf)
        self._pending.append((eid, int(ts_bucket), time.perf_counter(),
                              deadline, fut, tr, slo_cls,
                              bool(downgrade), lens_req))
        self._wake.notify()

    def predict(self, entry_id: int, ts_bucket: int,
                timeout: float | None = None) -> float:
        """Blocking convenience; `timeout` bounds the wait on the Future
        (concurrent.futures.TimeoutError past it) so a caller cannot
        hang even with deadlines and the watchdog disabled."""
        return float(self.submit(entry_id, ts_bucket).result(timeout))

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admissions NOW (submit raises QueueClosed) while the
        worker keeps flushing already-admitted requests. Safe to call
        from a signal handler: it never blocks on the queue lock — the
        flag write is enough (submit reads it under the lock), and the
        worker wake-up is best-effort. `close()` completes the drain."""
        # lock-free BY DESIGN — one-way flag writes from a SIGNAL
        # HANDLER: taking the queue lock here could deadlock on a
        # thread interrupted mid-critical-section; atomic bool stores
        # are the only safe operation, and submit() reads them under
        # the lock so the close is never torn
        self._draining = True  # graftlint: allow-lock-discipline
        self._drain_requested = True  # graftlint: allow-lock-discipline
        # the serve.drain_begin counter is emitted by the WORKER thread
        # (next loop turn), not here: bus.counter takes the writer's
        # non-reentrant lock and does file I/O — poison for a handler
        # interrupting a thread that was mid-telemetry-write
        if self._lock.acquire(blocking=False):
            try:
                self._wake.notify()
            finally:
                self._lock.release()

    def requeue(self) -> list[tuple[int, int, Future]]:
        """Atomically remove every NOT-YET-DISPATCHED request from the
        pending set and hand it back as (entry_id, ts_bucket, future)
        triples — futures UNRESOLVED; the caller now owns them. The
        fleet router uses this for worker-loss recovery (undispatched
        work moves to a surviving worker instead of riding the sync
        drain), and a draining worker uses it to answer a deep backlog
        with a fast retryable error instead of serving it out
        (cli/fleet_main.py) — which is what makes SIGTERM drain fast
        under load. In-flight work is untouched: it resolves through
        the normal dispatch path. Safe alongside submit/close; a
        post-requeue close simply finds the pending set empty."""
        with self._wake:
            taken = self._pending[:]
            self._pending.clear()
        return [(item[0], item[1], item[4]) for item in taken]

    def probe_dict(self) -> dict:
        """The queue half of the health-probe body (serve/health.py):
        load + per-class failure counts, cheap enough to answer on
        every poll (no engine call, no telemetry scrape)."""
        with self._lock:
            return {
                "depth": len(self._pending),
                "inflight": self._inflight_reqs,
                "errors": dict(self.error_counts),
            }

    def close(self) -> None:
        """Drain pending requests, then stop the worker. Idempotent."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._draining = True
            self._wake.notify()
        self._worker.join()
        if self._drain_requested and not self._drain_announced:
            # the worker never woke between begin_drain and close
            # (empty queue); emit the marker from this safe context
            self._drain_announced = True
            self._engine.bus.counter("serve.drain_begin")
        if self._dispatcher is not None:
            self._dispatcher.close()
            self._dispatcher = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats_dict(self) -> dict:
        """JSON-ready fault-path counters (the queue-side complement of
        engine.stats_dict)."""
        with self._lock:
            return {
                "shed": self.shed,
                "deadline_exceeded": self.deadline_exceeded,
                "poisoned": self.poisoned,
                "quarantined_entries": sorted(self._quarantined),
                "quarantine_rejected": self.quarantine_rejected,
                "watchdog_trips": self.watchdog_trips,
                "recovered": self.recovered,
                "overlap_dispatch": self._overlap,
                "overlapped": self.overlapped,
                "pending": len(self._pending),
                "inflight": self._inflight_reqs,
                "errors": dict(self.error_counts),
            }

    # -- worker side -----------------------------------------------------

    @staticmethod
    def _wants_local(item) -> bool:
        return item[8] is not None and item[8].wants_local

    def _take_batch_locked(self) -> list[tuple]:
        """Pop the maximal capacity-respecting prefix of the pending list
        (submission order — alignment depends on it). Batches never mix
        DOWNGRADE states (a brownout'd best-effort batch is capped at
        the cheapest rung's capacity so it actually fits rung 0), and
        never mix ATTRIBUTION requests with plain ones — the two
        dispatch through different rung programs (the lens local
        variant) and a batch has exactly one. Submission order within
        each batch is preserved either way. What-if-only lens requests
        mix freely: they differ only in the packed arrays."""
        dg = bool(self._pending[0][7]) if self._pending else False
        loc = self._wants_local(self._pending[0]) if self._pending else False
        max_g, max_n, max_e = ((self._dg_graphs, self._dg_nodes,
                                self._dg_edges) if dg else
                               (self._max_graphs, self._max_nodes,
                                self._max_edges))
        g = n = e = 0
        take = 0
        for item in self._pending:
            dn, de = self._engine.request_size(item[0])
            if take and (bool(item[7]) != dg
                         or self._wants_local(item) != loc
                         or g + 1 > max_g
                         or n + dn > max_n or e + de > max_e):
                break
            g, n, e = g + 1, n + dn, e + de
            take += 1
        batch = self._pending[:take]
        del self._pending[:take]
        self._inflight_reqs += take  # caller holds the lock
        return batch

    def _dec_inflight(self, _fut) -> None:
        """Done-callback on every taken request's future: one resolution
        (result, typed error, bisect sub-batch — whatever path) is one
        in-flight departure, so splits and retries cannot skew the
        probe's in-flight count."""
        with self._lock:
            self._inflight_reqs -= 1

    def _full_locked(self) -> bool:
        """Would waiting longer be pointless? True once the pending
        prefix already saturates a top-bucket batch (or crosses a
        downgrade boundary — the next take flushes up to it anyway)."""
        g = n = e = 0
        dg = bool(self._pending[0][7]) if self._pending else False
        loc = self._wants_local(self._pending[0]) if self._pending else False
        for item in self._pending:
            dn, de = self._engine.request_size(item[0])
            if (bool(item[7]) != dg or self._wants_local(item) != loc
                    or g + 1 > self._max_graphs
                    or n + dn > self._max_nodes
                    or e + de > self._max_edges):
                return True
            g, n, e = g + 1, n + dn, e + de
        return False

    def _pop_expired_locked(self, now: float) -> list:
        """Drop overdue requests from the pending set and RETURN them;
        the caller resolves their futures OUTSIDE the lock (a Future
        callback that re-enters the queue — an RPC front-end resubmitting
        — must not deadlock on the non-reentrant lock)."""
        if self._req_deadline_s <= 0:
            return []
        expired = [item for item in self._pending if item[3] <= now]
        if expired:
            self._pending[:] = [item for item in self._pending
                                if item[3] > now]
        return expired

    def _fail_expired(self, expired: list) -> None:
        """Resolve deadline-overdue requests — a future must never wait
        forever. Called WITHOUT the lock held."""
        if not expired:
            return
        # one lock round-trip for the whole sweep: a deadline storm can
        # expire hundreds of requests at once, and submit() contends on
        # this same lock (future resolution + bus stay outside it)
        with self._lock:
            self.deadline_exceeded += len(expired)
            self.error_counts["DeadlineExceeded"] += len(expired)
        tm_now = time.monotonic()
        for item in expired:
            self._engine.bus.counter("serve.deadline_exceeded",
                                     entry_id=item[0])
            item[4].set_exception(DeadlineExceeded(
                f"request for entry {item[0]} waited past its "
                f"{self._req_deadline_s * 1e3:g}ms deadline without "
                f"being dispatched"))
            tr = item[5]
            if tr is not None and tr.owns_root:
                self._engine.bus.finish_trace(
                    "trace.request", tr.ctx, tr.tm_submit, tm_now,
                    outcome="error", error="DeadlineExceeded",
                    entry_id=item[0])

    def _run(self) -> None:
        while True:
            expired: list = []
            batch: list = []
            with self._wake:
                # an in-flight overlapped batch must be completed before
                # the worker blocks indefinitely — a future must never
                # wait on traffic that may never arrive
                while (not self._pending and not self._closed
                       and self._inflight is None):
                    self._wake.wait()
                if not self._pending and self._closed:
                    break
                # coalesce until the flush deadline (anchored at the
                # OLDEST queued request's ARRIVAL — a request that
                # queued while the worker was dispatching has already
                # been waiting), capacity saturation, a request-deadline
                # expiry, or close — whichever comes first
                while self._pending and not self._closed:
                    now = time.perf_counter()
                    expired += self._pop_expired_locked(now)
                    if expired:
                        break  # resolve them promptly, outside the lock
                    if not self._pending or self._full_locked():
                        break
                    t_flush = self._pending[0][2] + self._deadline_s
                    if now >= t_flush:
                        break
                    t_wake = min([t_flush] + [p[3] for p in self._pending
                                              if p[3] < math.inf])
                    self._wake.wait(timeout=max(t_wake - now, 0.0))
                now = time.perf_counter()
                expired += self._pop_expired_locked(now)
                # flush only when a flush condition held (an
                # expiry-only wakeup goes back to coalescing)
                if self._pending and (
                        self._closed or self._full_locked()
                        or now >= self._pending[0][2] + self._deadline_s):
                    batch = self._take_batch_locked()
            if self._drain_requested and not self._drain_announced:
                self._drain_announced = True
                self._engine.bus.counter("serve.drain_begin")
            self._fail_expired(expired)
            if not batch:
                # nothing flushed this turn: resolve the in-flight batch
                # instead of holding its callers' futures hostage
                self._finish_inflight()
                continue
            # registered OUTSIDE the lock: a callback fires on whatever
            # thread resolves the future, and _dec_inflight retakes the
            # lock — every taken future resolves exactly once (the
            # queue's core invariant), so the count cannot drift
            for item in batch:
                item[4].add_done_callback(self._dec_inflight)
            # the downgrade evidence, once per TAKEN batch (retries
            # and bisect halves of the same batch must not re-count)
            if batch[0][7]:
                self._engine.bus.counter("serve.brownout_downgrade",
                                         graphs=len(batch))
            # queue-wait stage of the request lifecycle: submit -> the
            # moment its microbatch leaves the queue for the engine
            t_now = time.perf_counter()
            tm_now = time.monotonic()
            for item in batch:
                self._engine.record_queue_wait(t_now - item[2],
                                               coalesced=len(batch))
                if item[5] is not None:
                    self._engine.bus.trace_span(
                        "trace.worker_queue", item[5].ctx,
                        item[5].tm_submit, tm_now, coalesced=len(batch))
            try:
                if self._overlap:
                    self._pump_overlap(batch)
                else:
                    self._resolve(batch)
            except BaseException as exc:  # never kill the worker thread
                log.exception("unexpected worker-side failure; failing "
                              "the batch's futures")
                self._fail(batch, exc)
        # closed + drained: the final in-flight batch still resolves
        self._finish_inflight()

    # -- failure handling ------------------------------------------------

    def _fail(self, batch, exc: BaseException) -> None:
        failed = 0
        tm_now = time.monotonic()
        for item in batch:
            fut, tr = item[4], item[5]
            if not fut.done():
                fut.set_exception(exc)
                failed += 1
                if tr is not None and tr.owns_root:
                    self._engine.bus.finish_trace(
                        "trace.request", tr.ctx, tr.tm_submit, tm_now,
                        outcome="error", error=type(exc).__name__)
        if failed:
            with self._lock:
                self.error_counts[type(exc).__name__] += failed

    def _health_gate(self, batch) -> bool:
        """THE unhealthy-engine gate, shared by the synchronous and
        overlapped dispatch paths so the recovery policy cannot
        diverge between them: inside the fail-fast cooldown (or if
        recovery fails) the batch is failed fast and dispatch must not
        proceed. Returns True when dispatch may go ahead."""
        if self._engine.healthy:
            return True
        if (time.perf_counter() < self._cooldown_until
                or not self._try_recover()):
            self._failfast(batch)
            return False
        return True

    def _resolve(self, batch, retried: bool = False) -> None:
        """Dispatch one capacity-respecting batch SYNCHRONOUSLY and
        resolve its futures — through the watchdog, the unhealthy
        fail-fast window, and the poisoned-batch bisect. Also the
        overlapped path's error-recovery fallback: a bisect or a
        post-recovery retry always runs synchronous, so the fault
        invariants cannot depend on pipeline state."""
        if not self._health_gate(batch):
            return
        entries = [b[0] for b in batch]
        ts_buckets = [b[1] for b in batch]
        mixtures, want_local = self._batch_lens_args(batch)
        try:
            preds, packed = self._dispatch(
                entries, ts_buckets,
                max_rung=self._batch_max_rung(batch),
                mixtures=mixtures, want_local=want_local)
        except DispatchTimeout as exc:
            self._recover_or_fail(batch, exc, retried=retried)
            return
        except Exception as exc:  # lint: allow-silent-except — _fail_or_bisect logs/counts per sub-batch
            self._fail_or_bisect(batch, exc, retried=retried)
            return
        self._settle(batch, preds, packed)

    def _recover_or_fail(self, batch, exc: DispatchTimeout,
                         retried: bool = False) -> None:
        """THE watchdog recovery policy, in one place: trip, attempt
        ONE rebuild-from-store recovery, retry the batch synchronously
        once — a transient wedge must not cost innocent requests their
        predictions; a second wedge (or failed recovery) fails them
        with the timeout."""
        self._trip_watchdog(exc)
        if not retried and self._try_recover():
            self._resolve(batch, retried=True)
        else:
            self._fail(batch, exc)

    def _pump_overlap(self, batch) -> None:
        """Overlapped dispatch: pack batch k+1 on THIS worker thread
        while the device computes batch k (the in-flight batch), then
        complete k, then launch k+1 — one batch in flight, result
        resolution deferred to the completion step. Every failure path
        routes through the same handlers as the synchronous _resolve,
        so the PR-4 invariants (bisect quarantine, watchdog recovery,
        fail-fast cooldown) hold unchanged."""
        packed = pack_exc = None
        try:
            # host-only work (bucket select + pack_single over read-only
            # state): safe while the single engine device thread still
            # owns the in-flight batch — THE overlap this path exists for
            mixtures, want_local = self._batch_lens_args(batch)
            packed = self._engine.pack_microbatch(
                [b[0] for b in batch], [b[1] for b in batch],
                max_rung=self._batch_max_rung(batch),
                mixtures=mixtures, want_local=want_local)
        except Exception as exc:  # lint: allow-silent-except — handed to _fail_or_bisect below
            pack_exc = exc
        self._finish_inflight()
        if pack_exc is not None:
            self._fail_or_bisect(batch, pack_exc, retried=False)
            return
        # completion may have tripped the watchdog; the packed batch
        # follows the same fail-fast/recover gate as a sync dispatch
        if not self._health_gate(batch):
            return
        try:
            handle = self._engine_call(
                lambda: self._engine.dispatch_packed(packed),
                what=f"engine dispatch of {len(batch)} request(s)")
        except DispatchTimeout as exc:
            self._recover_or_fail(batch, exc)
            return
        except Exception as exc:  # lint: allow-silent-except — _fail_or_bisect logs/counts per sub-batch
            self._fail_or_bisect(batch, exc, retried=False)
            return
        self._inflight = (batch, handle)
        with self._lock:  # stats_dict snapshots this counter
            self.overlapped += 1
        self._engine.bus.counter("serve.overlapped", level=2,
                                 graphs=len(batch))

    def _finish_inflight(self) -> None:
        """Resolve the in-flight overlapped batch (if any): block for
        its device result under the watchdog and settle its futures —
        the deferred completion step. Failure handling mirrors a
        synchronous dispatch exactly."""
        if self._inflight is None:
            return
        batch, handle = self._inflight
        self._inflight = None
        try:
            preds = self._engine_call(
                lambda: self._engine.complete_microbatch(handle),
                what=f"engine completion of {len(batch)} request(s)")
        except DispatchTimeout as exc:
            self._recover_or_fail(batch, exc)
            return
        except Exception as exc:  # lint: allow-silent-except — _fail_or_bisect logs/counts per sub-batch
            self._fail_or_bisect(batch, exc, retried=False)
            return
        self._settle(batch, preds, handle.packed)

    def _settle(self, batch, preds, packed=None) -> None:
        """Resolve a served batch's futures to their own predictions
        (submission-order alignment) + per-request total latency, and —
        for traced requests — the engine-stage trace spans (the batch's
        pack/dispatch/compute stamps, one span set per traced request:
        trees are per REQUEST even though the work was per batch).
        ``packed`` is THIS batch's completed PackedMicrobatch, threaded
        through the dispatch chain (never read off engine state — see
        _dispatch); lens attribution requires it."""
        bus = self._engine.bus
        t_done = time.perf_counter()
        stage_tm = self._engine.last_stage_tm
        pk = stage_tm.get("pack")
        dp = stage_tm.get("dispatch")
        cp = stage_tm.get("compute")
        tm_done = time.monotonic()
        for item in batch:
            tr = item[5]
            bus.histogram("serve.request_total_ms",
                          (t_done - item[2]) * 1e3, level=2)
            if tr is not None:
                if pk:
                    bus.trace_span("trace.pack", tr.ctx, pk[0], pk[1])
                if dp:
                    bus.trace_span("trace.dispatch", tr.ctx, dp[0],
                                   dp[1])
                if cp:
                    bus.trace_span("trace.compute", tr.ctx, cp[0],
                                   cp[1])
        # lens attribution rides THIS batch's completed microbatch
        # (threaded through the call chain); graph slot i is batch
        # position i by pack order. One counter per attributed batch.
        lens_packed = None
        if batch and self._wants_local(batch[0]):
            if packed is None or packed.local is None:
                # structurally impossible (every local-batch path
                # threads its packed through) — fail typed, not silent
                self._fail(batch, RuntimeError(
                    "lens batch settled without its packed microbatch"))
                return
            lens_packed = packed
            bus.counter("lens.attribution", graphs=len(batch))
        for slot, (item, p) in enumerate(zip(batch, preds)):
            fut, tr, lens_req = item[4], item[5], item[8]
            # multi-quantile heads resolve to the (T,) vector; the
            # legacy scalar contract is untouched in single-tau mode
            val = (float(p) if np.ndim(p) == 0
                   else np.asarray(p, np.float32))
            if lens_req is not None and lens_req.wants_local:
                mixture = (lens_req.mixture
                           if lens_req.mixture is not None
                           else self._engine.base_mixture(item[0]))
                rows = self._engine.attribution_rows(
                    lens_packed, slot, lens_req.k, mixture)
                fut.set_result(LensResult(pred=val,
                                          attribution=tuple(rows)))
            else:
                fut.set_result(val)
            if tr is not None and tr.owns_root:
                bus.finish_trace("trace.request", tr.ctx, tr.tm_submit,
                                 tm_done, outcome="ok", entry_id=item[0])

    def _fail_or_bisect(self, batch, exc: Exception,
                        retried: bool) -> None:
        """A failed microbatch: a multi-request batch is bisect-retried
        SYNCHRONOUSLY so only the poisoned request(s) fail while
        innocent co-batched callers still get predictions (alignment is
        per-sub-batch, so surviving futures resolve to exactly their
        own outputs); a single request gets ONE fresh dispatch before
        offender bookkeeping — the bisect halves of a multi-batch are
        re-dispatched anyway, so without this a TRANSIENT fault (an
        occurrence-addressed nan/error that has already been consumed)
        would cost exactly the caller who happened to ride alone its
        prediction, purely by coalescing luck."""
        if len(batch) == 1:
            if not retried:
                self._engine.bus.counter("serve.retry_single",
                                         entry_id=batch[0][0],
                                         error=type(exc).__name__)
                log.warning("single-request batch failed (%s: %s); one "
                            "fresh dispatch before recording the "
                            "offender", type(exc).__name__, exc)
                self._resolve(batch, retried=True)
                return
            self._record_offender(batch[0][0], exc)
            self._fail(batch, exc)
            return
        self._engine.bus.counter("serve.bisect", graphs=len(batch))
        log.warning("microbatch of %d failed (%s: %s); bisecting to "
                    "isolate the poisoned request", len(batch),
                    type(exc).__name__, exc)
        mid = len(batch) // 2
        self._resolve(batch[:mid], retried=retried)
        self._resolve(batch[mid:], retried=retried)

    def _failfast(self, batch) -> None:
        self._engine.bus.counter("serve.failfast", requests=len(batch))
        self._fail(batch, EngineUnhealthy(
            f"engine unhealthy ({self._engine.unhealthy_reason}); "
            f"failing fast during cooldown"))

    def _engine_call(self, fn, what: str):
        """Run one engine device call: inline without a watchdog,
        through the abandonable dispatcher thread with one."""
        if self._dispatch_timeout_s <= 0:
            return fn()
        if self._dispatcher is None or self._dispatcher.dead:
            self._dispatcher = _Dispatcher(self._engine)
        return self._dispatcher.call(fn, self._dispatch_timeout_s, what)

    def _batch_max_rung(self, batch) -> int | None:
        """The brownout rung cap for one (downgrade-homogeneous) batch:
        0 for downgraded best-effort traffic, None otherwise. PURE —
        the serve.brownout_downgrade counter is emitted once per TAKEN
        batch in the worker loop, not here: this helper also runs on
        watchdog retries and bisect halves, which would multi-count
        one admitted batch."""
        return 0 if (batch and batch[0][7]) else None

    def _batch_lens_args(self, batch) -> tuple[list | None, bool]:
        """(per-request mixture overrides, want_local) for one
        (local-homogeneous) batch — PURE, same retry/bisect argument
        as _batch_max_rung. Mixture overrides ride per item, so bisect
        halves keep exactly their own counterfactual edits."""
        mixtures = None
        if any(item[8] is not None and item[8].mixture is not None
               for item in batch):
            mixtures = [item[8].mixture if item[8] is not None else None
                        for item in batch]
        return mixtures, bool(batch and self._wants_local(batch[0]))

    def _dispatch(self, entries, ts_buckets, max_rung=None,
                  mixtures=None, want_local=False):
        """(predictions, packed-or-None). Lens (local) batches run the
        engine's three phases explicitly and RETURN the packed
        microbatch through this call chain — attribution must read the
        local vector of exactly this batch, and engine-level
        "last completed" state could be clobbered by a
        watchdog-abandoned zombie thread finishing late."""
        what = f"engine dispatch of {len(entries)} request(s)"
        if not want_local:
            return self._engine_call(
                lambda: self._engine.predict_microbatch(
                    entries, ts_buckets, max_rung=max_rung,
                    mixtures=mixtures),
                what=what), None

        def run():
            packed = self._engine.pack_microbatch(
                entries, ts_buckets, max_rung=max_rung,
                mixtures=mixtures, want_local=True)
            return self._engine.complete_microbatch(
                self._engine.dispatch_packed(packed)), packed

        return self._engine_call(run, what=what)

    def _trip_watchdog(self, exc: DispatchTimeout) -> None:
        with self._lock:  # stats_dict snapshots this counter
            self.watchdog_trips += 1
        self._engine.bus.counter("serve.watchdog_trip")
        self._engine.mark_unhealthy(str(exc))
        self._cooldown_until = time.perf_counter() + self._cooldown_s
        self._dispatcher = None  # its thread may be wedged mid-call

    def _try_recover(self) -> bool:
        """ONE bounded rebuild-from-AOT-store attempt; True when the
        engine is healthy again. The rebuild runs on an abandonable
        thread too — recovery of a wedged device must not wedge the
        worker."""
        bus = self._engine.bus
        finished, box = _call_abandonable(self._engine.rebuild,
                                          self._rebuild_timeout_s,
                                          "serve-rebuild")
        if not finished or "error" in box:
            err = box.get("error", "rebuild timed out")
            log.error("engine rebuild failed (%s); failing fast for "
                      "%.1fs", err, self._cooldown_s)
            bus.counter("serve.recovery_failed")
            self._cooldown_until = time.perf_counter() + self._cooldown_s
            return False
        self._engine.mark_recovered()
        with self._lock:  # stats_dict snapshots this counter
            self.recovered += 1
        bus.counter("serve.recovered")
        self._cooldown_until = 0.0
        # quarantine evidence predates the rebuild: failures during an
        # engine-wide sick period (a wedging transport, a NaN streak)
        # blame whichever entries happened to be in flight, and a
        # permanent blackhole of legitimate traffic is worse than
        # re-learning a genuinely poisoned entry over a few batches
        with self._lock:
            dropped = len(self._quarantined)
            self._offenders.clear()
            self._quarantined.clear()
        if dropped:
            log.warning("engine recovery amnestied %d quarantined "
                        "entr%s (offender evidence reset)", dropped,
                        "y" if dropped == 1 else "ies")
        log.warning("engine recovered after watchdog trip (rebuild #%d)",
                    self._engine.rebuilds)
        return True

    def _record_offender(self, entry_id: int, exc: Exception) -> None:
        bus = self._engine.bus
        with self._lock:
            self.poisoned += 1
            count = self._offenders[entry_id] = (
                self._offenders.get(entry_id, 0) + 1)
            newly_quarantined = (count >= self._quarantine_threshold
                                 and entry_id not in self._quarantined)
            if newly_quarantined:
                self._quarantined.add(entry_id)
        bus.counter("serve.poisoned", entry_id=entry_id,
                    error=type(exc).__name__)
        if newly_quarantined:
            bus.counter("serve.quarantined", entry_id=entry_id)
            log.error("entry %d quarantined: poisoned %d microbatches "
                      "(threshold %d); rejecting it at submit from now "
                      "on", entry_id, count, self._quarantine_threshold)
