"""Deadline-based microbatching queue for concurrent serving traffic.

Per-dispatch overhead (host pack + H2D + program launch) is the serving
twin of the per-step dispatch latency the train loop amortizes with
lax.scan (TrainConfig.scan_chunk): a single-graph forward pays the same
fixed cost as a 16-graph one. The queue coalesces requests that arrive
within a flush deadline into ONE bucket-shaped microbatch, amortizing
that fixed cost across concurrent callers exactly the way the epoch
packer amortizes padding across a batch.

Semantics:
- `submit` returns a Future; `predict` is the blocking convenience.
- A batch flushes when (a) the oldest queued request has waited
  `flush_deadline_ms`, or (b) the pending set would overflow the engine's
  top bucket (graphs, nodes, or edges) — whichever comes first. Deadline
  0 degrades to per-request dispatch (lowest latency, no amortization).
- One worker thread owns ALL engine calls, so the engine needs no locks
  and per-request prediction alignment is preserved by construction:
  each flush packs its requests in submission order and fans the
  engine's per-request outputs back to the matching futures.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from pertgnn_tpu.serve.engine import InferenceEngine


class MicrobatchQueue:
    """Thread-safe request front-end over a (single-threaded) engine."""

    def __init__(self, engine: InferenceEngine,
                 flush_deadline_ms: float | None = None,
                 max_graphs: int | None = None):
        cfg = engine._cfg.serve
        self._engine = engine
        self._deadline_s = (cfg.flush_deadline_ms
                            if flush_deadline_ms is None
                            else flush_deadline_ms) / 1e3
        top = engine.ladder[-1]
        self._max_graphs = min(max_graphs or top.max_graphs, top.max_graphs)
        self._max_nodes = top.max_nodes
        self._max_edges = top.max_edges
        # (entry_id, ts_bucket, arrival_time, future) — arrival anchors
        # the flush deadline even when the worker was busy dispatching
        self._pending: list[tuple[int, int, float, Future]] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="serve-microbatch")
        self._worker.start()

    # -- client side -----------------------------------------------------

    def submit(self, entry_id: int, ts_bucket: int) -> Future:
        """Enqueue one request; the Future resolves to its predicted
        latency (label units) once its microbatch is served."""
        # size it NOW so an entry the engine has never seen fails the
        # caller, not the shared worker
        self._engine.request_size(entry_id)
        fut: Future = Future()
        with self._wake:
            if self._closed:
                raise RuntimeError("MicrobatchQueue is closed")
            self._pending.append((int(entry_id), int(ts_bucket),
                                  time.perf_counter(), fut))
            self._wake.notify()
        return fut

    def predict(self, entry_id: int, ts_bucket: int) -> float:
        return float(self.submit(entry_id, ts_bucket).result())

    def close(self) -> None:
        """Drain pending requests, then stop the worker. Idempotent."""
        with self._wake:
            if self._closed:
                return
            self._closed = True
            self._wake.notify()
        self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- worker side -----------------------------------------------------

    def _take_batch_locked(self) -> list[tuple[int, int, float, Future]]:
        """Pop the maximal capacity-respecting prefix of the pending list
        (submission order — alignment depends on it)."""
        g = n = e = 0
        take = 0
        for entry_id, _ts, _t, _f in self._pending:
            dn, de = self._engine.request_size(entry_id)
            if take and (g + 1 > self._max_graphs
                         or n + dn > self._max_nodes
                         or e + de > self._max_edges):
                break
            g, n, e = g + 1, n + dn, e + de
            take += 1
        batch = self._pending[:take]
        del self._pending[:take]
        return batch

    def _full_locked(self) -> bool:
        """Would waiting longer be pointless? True once the pending
        prefix already saturates a top-bucket batch."""
        g = n = e = 0
        for entry_id, _ts, _t, _f in self._pending:
            dn, de = self._engine.request_size(entry_id)
            if (g + 1 > self._max_graphs or n + dn > self._max_nodes
                    or e + de > self._max_edges):
                return True
            g, n, e = g + 1, n + dn, e + de
        return False

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._pending and not self._closed:
                    self._wake.wait()
                if not self._pending and self._closed:
                    return
                # deadline anchored at the OLDEST queued request's ARRIVAL
                # (not at worker observation: a request that queued while
                # the worker was dispatching has already been waiting)
                t_flush = self._pending[0][2] + self._deadline_s
                while (not self._closed and not self._full_locked()):
                    remaining = t_flush - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._wake.wait(timeout=remaining)
                batch = self._take_batch_locked()
            if not batch:
                continue
            entries = [b[0] for b in batch]
            buckets = [b[1] for b in batch]
            futures = [b[3] for b in batch]
            # queue-wait stage of the request lifecycle: submit -> the
            # moment its microbatch leaves the queue for the engine
            t_now = time.perf_counter()
            for _e, _ts, t_arrival, _f in batch:
                self._engine.record_queue_wait(t_now - t_arrival,
                                               coalesced=len(batch))
            try:
                preds = self._engine.predict_microbatch(entries, buckets)
            except BaseException as exc:
                for f in futures:
                    f.set_exception(exc)
                continue
            t_done = time.perf_counter()
            for _e, _ts, t_arrival, _f in batch:
                self._engine.bus.histogram("serve.request_total_ms",
                                           (t_done - t_arrival) * 1e3,
                                           level=2)
            for f, p in zip(futures, preds):
                f.set_result(float(p))
