from pertgnn_tpu.batching.mixture import Mixture, build_mixtures
from pertgnn_tpu.batching.pack import (
    PackedBatch,
    BatchBudget,
    derive_budget,
    pack_examples,
)
from pertgnn_tpu.batching.arena import (
    CompactBatch,
    IndexBatch,
    build_feature_arena,
    build_mixture_arena,
    pack_epoch_compact,
    pack_epoch_indices,
)
from pertgnn_tpu.batching.materialize import (
    DeviceArenas,
    build_device_arenas,
    expand_compact,
    materialize_compact,
    materialize_device,
)
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.dataset import Dataset, build_dataset, split_indices
