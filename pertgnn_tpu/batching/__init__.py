from pertgnn_tpu.batching.mixture import Mixture, build_mixtures
from pertgnn_tpu.batching.pack import (
    PackedBatch,
    BatchBudget,
    derive_budget,
    pack_examples,
)
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.dataset import Dataset, build_dataset, split_indices
