"""Fixed-shape packed graph batches.

The TPU replacement for PyG's dynamic ragged batching
(/root/reference/pert_gnn.py:196-210): every batch has ONE static shape
(max_graphs, max_nodes, max_edges), so the jit'd train step compiles exactly
once. Graphs (= traces: one entry-mixture each) are packed greedily until a
budget would overflow; the remainder is padding, tracked by node/edge/graph
masks that the model and loss respect exactly (padding must be unobservable —
enforced by the padding-invariance tests).

Layout follows the jraph GraphsTuple idea (flat node/edge arrays + per-node
graph ids) re-derived for this workload: per-node pattern_prob/pattern_size
carry the reference's mixture weighting (pert_gnn.py:85-94, 122-131), and the
last graph slot is reserved as the pad graph that all pad nodes point to, so
segment pooling needs no special cases.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterator, NamedTuple

import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import Mixture


class PackedBatch(NamedTuple):
    """One fixed-shape batch. All arrays are host numpy until device put.

    Invariant: edge arrays are receiver-sorted with masked (pad) edges at
    the tail (established in `pack_examples.flush`). Segment aggregation is
    order-free so the XLA path doesn't care, but the fused Pallas kernel's
    block-skipping relies on it (ops/pallas_attention.py assume_sorted)."""

    x: np.ndarray              # (N, F) float32 node features
    ms_id: np.ndarray          # (N,) int32
    node_depth: np.ndarray     # (N,) float32
    node_graph: np.ndarray     # (N,) int32 — graph slot per node
    node_mask: np.ndarray      # (N,) bool
    pattern_prob: np.ndarray   # (N,) float32
    pattern_size: np.ndarray   # (N,) float32 (pad nodes: 1, avoids 0-div)
    senders: np.ndarray        # (E,) int32 (pad edges: 0, masked)
    receivers: np.ndarray      # (E,) int32
    edge_iface: np.ndarray     # (E,) int32
    edge_rpctype: np.ndarray   # (E,) int32
    edge_duration: np.ndarray  # (E,) float32 — span |rt| ms (0 for pert/pad)
    edge_mask: np.ndarray      # (E,) bool
    entry_id: np.ndarray       # (G,) int32
    y: np.ndarray              # (G,) float32
    graph_mask: np.ndarray     # (G,) bool

    @property
    def num_graphs(self) -> int:
        return len(self.entry_id)


@dataclasses.dataclass(frozen=True)
class BatchBudget:
    max_graphs: int   # real graph slots (one extra pad slot is added)
    max_nodes: int
    max_edges: int


def _round_up(v: int, m: int = 128) -> int:
    return ((v + m - 1) // m) * m


def pad_waste(budget: BatchBudget, num_nodes: float,
              num_edges: float) -> float:
    """Fraction of a budget's node+edge slots burned on padding — THE
    pad-waste metric, shared by the serving engine's per-bucket stats
    (serve/buckets.py re-exports it), the epoch packer's telemetry
    (assign_batches, flush) and the serve-bench JSON, so every stream
    reports the same quantity."""
    total = budget.max_nodes + budget.max_edges
    return (total - num_nodes - num_edges) / total


EDGE_FIELDS = ("senders", "receivers", "edge_iface", "edge_rpctype",
               "edge_duration", "edge_mask")


def receiver_sort_edges(arrays: dict, sentinel: int,
                        scratch: dict | None = None) -> dict:
    """Reorder all per-edge arrays by receiver, masked (pad) edges last —
    the PackedBatch edge-order invariant. `sentinel` is the sort key for
    masked edges (any value > the largest real node id). Shared by
    pack_examples.flush and parallel.data_parallel.stack_batches so the
    edge-field list can't drift between them.

    `scratch` (arena hot path): a dict of same-shape/dtype per-edge
    arrays to gather INTO (``np.take(..., out=)``) instead of fancy-
    index allocating fresh ones; the gathered array and the scratch
    swap roles in place, so over repeated batches the two buffers
    ping-pong and the sort allocates nothing."""
    key = np.where(arrays["edge_mask"], arrays["receivers"], sentinel)
    order = np.argsort(key, kind="stable")
    for field in EDGE_FIELDS:
        if scratch is None:
            arrays[field] = arrays[field][order]
        else:
            np.take(arrays[field], order, out=scratch[field])
            arrays[field], scratch[field] = scratch[field], arrays[field]
    return arrays


def _init_arrays(budget: BatchBudget, n_feat: int) -> dict:
    """Freshly-initialised packing buffers for one budget shape — the
    single source of truth for the empty-batch state. pack_examples
    allocates through it per batch; PackArena allocates through it once
    and RESETS leases back to exactly this state on reuse."""
    G = budget.max_graphs + 1  # +1: reserved pad graph slot
    return dict(
        x=np.zeros((budget.max_nodes, n_feat), dtype=np.float32),
        ms_id=np.zeros(budget.max_nodes, dtype=np.int32),
        node_depth=np.zeros(budget.max_nodes, dtype=np.float32),
        node_graph=np.full(budget.max_nodes, G - 1, dtype=np.int32),
        node_mask=np.zeros(budget.max_nodes, dtype=bool),
        pattern_prob=np.zeros(budget.max_nodes, dtype=np.float32),
        pattern_size=np.ones(budget.max_nodes, dtype=np.float32),
        senders=np.zeros(budget.max_edges, dtype=np.int32),
        receivers=np.zeros(budget.max_edges, dtype=np.int32),
        edge_iface=np.zeros(budget.max_edges, dtype=np.int32),
        edge_rpctype=np.zeros(budget.max_edges, dtype=np.int32),
        edge_duration=np.zeros(budget.max_edges, dtype=np.float32),
        edge_mask=np.zeros(budget.max_edges, dtype=bool),
        entry_id=np.zeros(G, dtype=np.int32),
        y=np.zeros(G, dtype=np.float32),
        graph_mask=np.zeros(G, dtype=bool),
    )


class ArenaLease:
    """Custody token for one set of arena buffers. Whoever holds the
    lease may write `arrays` (and hand them to pack_examples via
    ``into=``); calling `release()` returns the buffers to the pool for
    the NEXT microbatch to overwrite — so release only after every
    consumer of the packed arrays is done with them (the serving engine
    releases at complete_microbatch, AFTER np.asarray has forced the
    device computation; lens batches never release because attribution
    reads the host arrays later)."""

    __slots__ = ("arrays", "scratch", "_arena")

    def __init__(self, arrays: dict, scratch: dict, arena: "PackArena"):
        self.arrays = arrays
        self.scratch = scratch
        self._arena = arena

    def release(self) -> None:
        self._arena._release(self)


class PackArena:
    """Reusable packing-buffer pool for ONE budget shape.

    The serving hot path packs every microbatch into freshly-allocated
    numpy arrays (~a few MB per batch at serving budgets) that live just
    long enough to be device-put — pure allocator churn. The arena keeps
    a small pool (depth 2 covers pack-on-queue-thread overlapping
    complete-on-dispatch-thread) of buffer sets and hands them out as
    leases; `acquire` resets a reused lease to the exact `_init_arrays`
    state so packed output is bit-identical to the fresh-allocation
    path.

    Thread-safety: acquire and release happen on DIFFERENT threads (the
    queue worker packs, the dispatcher completes), hence the lock; it
    guards only the free-list, never any blocking work."""

    def __init__(self, budget: BatchBudget, n_feat: int, depth: int = 2):
        self._budget = budget
        self._n_feat = n_feat
        self._depth = depth
        self._lock = threading.Lock()
        self._free: list[ArenaLease] = []

    def _new_lease(self) -> ArenaLease:
        arrays = _init_arrays(self._budget, self._n_feat)
        scratch = {f: np.empty_like(arrays[f]) for f in EDGE_FIELDS}
        return ArenaLease(arrays, scratch, self)

    def _reset(self, lease: ArenaLease) -> None:
        a = lease.arrays
        G = self._budget.max_graphs + 1
        for field in ("x", "ms_id", "node_depth", "pattern_prob",
                      "senders", "receivers", "edge_iface",
                      "edge_rpctype", "edge_duration", "entry_id", "y"):
            a[field].fill(0)
        a["node_graph"].fill(G - 1)
        a["pattern_size"].fill(1.0)
        for field in ("node_mask", "edge_mask", "graph_mask"):
            a[field].fill(False)

    def acquire(self) -> ArenaLease:
        with self._lock:
            lease = self._free.pop() if self._free else None
        bus = telemetry.get_bus()
        if lease is None:
            lease = self._new_lease()
            if bus.enabled:
                bus.counter("pack.arena_alloc", level=2)
        else:
            self._reset(lease)
            if bus.enabled:
                bus.counter("pack.arena_reuse", level=2)
        return lease

    def _release(self, lease: ArenaLease) -> None:
        with self._lock:
            if len(self._free) < self._depth:
                self._free.append(lease)
            # beyond depth the lease is simply dropped (GC'd): a burst
            # that outran the pool shrinks back to steady state


def zero_masked(b: PackedBatch) -> PackedBatch:
    """A pure-padding clone of `b`: identical shapes, every mask False.
    Used as inert tail filler by the scan-chunked train loop and the
    data-parallel global-batch grouper."""
    return b._replace(node_mask=np.zeros_like(b.node_mask),
                      edge_mask=np.zeros_like(b.edge_mask),
                      graph_mask=np.zeros_like(b.graph_mask))


def derive_budget(mixtures: dict[int, Mixture], entry_ids: np.ndarray,
                  batch_size: int, headroom: float = 1.1) -> BatchBudget:
    """Budget sized so an average batch fits `batch_size` graphs.

    Node/edge budgets are mean-mixture-size * batch_size * `headroom` (but
    never below the single largest mixture), rounded up to multiples of
    128 for TPU lane alignment.

    Why 1.1: a shuffled epoch's batch is a sum of ~batch_size iid mixture
    sizes, so it concentrates tightly around the mean — measured on the
    bench workload (`python benchmarks/sweep_r3.py --utilization`),
    headroom 1.1 packs the SAME number of 170-graph batches as 1.3 at
    0.89/0.90 node/edge padded-slot utilization instead of 0.76 (≈15%
    less padded work per epoch for free; 0.9 reaches 0.99 util at +11%
    batches). Quantile BUCKETING of budgets was evaluated there and
    rejected: 2-3 size-bucketed budgets land at the same ~0.89-0.90
    utilization as the single 1.1 budget while costing k compiled shapes
    instead of one. Bucketing only pays when a single giant mixture
    forces max_nodes far above mean*batch_size; the `max(mixture)` floor
    below is where that regime would show up.
    """
    sizes_n = np.array([mixtures[int(e)].num_nodes for e in entry_ids])
    sizes_e = np.array([mixtures[int(e)].num_edges for e in entry_ids])
    max_nodes = _round_up(max(int(sizes_n.mean() * batch_size * headroom),
                              int(sizes_n.max()) + 1))
    max_edges = _round_up(max(int(sizes_e.mean() * batch_size * headroom),
                              int(sizes_e.max()) + 1))
    return BatchBudget(max_graphs=batch_size, max_nodes=max_nodes,
                       max_edges=max_edges)


def pack_single(
    mixtures: dict[int, Mixture],
    entry_ids: np.ndarray,
    ts_buckets: np.ndarray,
    budget: BatchBudget,
    lookup: ResourceLookup,
    ys: np.ndarray | None = None,
    node_depth_in_x: bool = False,
    mixture_of: "list[Mixture] | None" = None,
    into: ArenaLease | None = None,
) -> PackedBatch:
    """Pack the given examples into exactly ONE budget-shaped batch.

    The serving request path (serve/engine.py): a microbatch of requests
    is packed into one bucket shape with every `pack_examples` invariant
    intact (receiver-sorted edges, reserved pad graph slot) — by reusing
    its buffer machinery rather than re-implementing it. Unlike the epoch
    packer it never flushes: examples that cannot share one batch raise
    (the caller sizes its bucket BEFORE packing — serve/buckets.py
    `select_bucket`).

    `ys` defaults to zeros: a live request has no label; the y slots ride
    along only because the batch layout is shared with training.

    `mixture_of` overrides the mixture packed for each example (aligned
    with `entry_ids`; the entry_id slot keeps the REAL id for the entry
    embedding) — the counterfactual serving path (pertgnn_tpu/lens/
    whatif.py) packs an edited topology under the request's own entry.

    `into` (graftwire hot path): an ArenaLease whose buffers this batch
    is packed into instead of freshly-allocated arrays — zero-alloc
    steady state. The returned PackedBatch VIEWS the lease's arrays;
    custody rules are on ArenaLease.release.
    """
    entry_ids = np.asarray(entry_ids)
    if len(entry_ids) == 0:
        raise ValueError("pack_single needs at least one example")
    if ys is None:
        ys = np.zeros(len(entry_ids), dtype=np.float32)
    if mixture_of is None:
        mixes = [mixtures[int(e)] for e in entry_ids]
    else:
        mixes = list(mixture_of)
        if len(mixes) != len(entry_ids):
            raise ValueError(
                f"mixture_of has {len(mixes)} entries for "
                f"{len(entry_ids)} examples")
    n = sum(m.num_nodes for m in mixes)
    e_tot = sum(m.num_edges for m in mixes)
    if (len(entry_ids) > budget.max_graphs or n > budget.max_nodes
            or e_tot > budget.max_edges):
        raise ValueError(
            f"{len(entry_ids)} examples ({n} nodes, {e_tot} edges) do not "
            f"fit one batch of {budget}")
    with telemetry.span("pack.single", level=2, graphs=len(entry_ids)):
        batches = list(pack_examples(mixtures, entry_ids,
                                     np.asarray(ts_buckets), ys, budget,
                                     lookup,
                                     node_depth_in_x=node_depth_in_x,
                                     mixture_of=mixes, into=into))
        # the fit pre-check above makes a second flush impossible
        (batch,) = batches
        return batch


def pack_examples(
    mixtures: dict[int, Mixture],
    entry_ids: np.ndarray,
    ts_buckets: np.ndarray,
    ys: np.ndarray,
    budget: BatchBudget,
    lookup: ResourceLookup,
    node_depth_in_x: bool = False,
    mixture_of: "list[Mixture] | None" = None,
    into: ArenaLease | None = None,
) -> Iterator[PackedBatch]:
    """Greedily pack examples (in the given order) into fixed-shape batches.

    Every example must fit a budget alone; an example larger than the budget
    raises (size your budget with `derive_budget`). `mixture_of` (aligned
    per example) overrides the mixture looked up by entry id — the
    counterfactual serving path packs edited topologies through it.
    `into` packs the FIRST batch into an arena lease's buffers (the
    serving path always yields exactly one); any later batch falls back
    to fresh allocation so epoch packing can pass a lease too.
    """
    n_feat = lookup.num_features + (1 if node_depth_in_x else 0)

    # buffers are allocated lazily at the first example of each batch so
    # the lease (one buffer set) can be consumed by the first batch only
    buf: dict | None = None
    lease_pending = into is not None
    g = n = e = 0

    def next_buf():
        nonlocal lease_pending
        if lease_pending:
            lease_pending = False
            return into.arrays
        return _init_arrays(budget, n_feat)

    def flush():
        nonlocal buf, g, n, e
        bus = telemetry.get_bus()
        if bus.enabled:
            bus.histogram("pack.batch_pad_waste", pad_waste(budget, n, e),
                          level=2, graphs=g, nodes=n, edges=e)
        # Receiver-sort the edge arrays (pad edges to the tail). Segment
        # aggregation is order-free, so this changes nothing for the XLA
        # path, and it lets the fused Pallas kernel skip its in-jit sort
        # (ops/pallas_attention.py assume_sorted).
        scratch = (into.scratch
                   if into is not None and buf is into.arrays else None)
        batch = PackedBatch(**receiver_sort_edges(buf, budget.max_nodes,
                                                  scratch=scratch))
        buf = None
        g = n = e = 0
        return batch

    for i, (entry, bucket, y) in enumerate(zip(entry_ids, ts_buckets, ys)):
        mix = (mixture_of[i] if mixture_of is not None
               else mixtures[int(entry)])
        if mix.num_nodes > budget.max_nodes or mix.num_edges > budget.max_edges:
            raise ValueError(
                f"entry {entry} mixture ({mix.num_nodes} nodes, "
                f"{mix.num_edges} edges) exceeds budget {budget}")
        if (g + 1 > budget.max_graphs or n + mix.num_nodes > budget.max_nodes
                or e + mix.num_edges > budget.max_edges):
            yield flush()
        if buf is None:
            buf = next_buf()
        ns = slice(n, n + mix.num_nodes)
        es = slice(e, e + mix.num_edges)
        feats = lookup(np.full(mix.num_nodes, bucket, dtype=np.int64),
                       mix.ms_id.astype(np.int64),
                       feature_mask=mix.feature_mask)
        if node_depth_in_x:
            feats = np.concatenate([feats, mix.node_depth[:, None]], axis=1)
        buf["x"][ns] = feats
        buf["ms_id"][ns] = mix.ms_id
        buf["node_depth"][ns] = mix.node_depth
        buf["node_graph"][ns] = g
        buf["node_mask"][ns] = True
        buf["pattern_prob"][ns] = mix.pattern_prob
        buf["pattern_size"][ns] = mix.pattern_size
        buf["senders"][es] = mix.senders + n
        buf["receivers"][es] = mix.receivers + n
        buf["edge_iface"][es] = mix.edge_iface
        buf["edge_rpctype"][es] = mix.edge_rpctype
        buf["edge_duration"][es] = mix.edge_duration
        buf["edge_mask"][es] = True
        buf["entry_id"][g] = entry
        buf["y"][g] = y
        buf["graph_mask"][g] = True
        g += 1
        n += mix.num_nodes
        e += mix.num_edges
    if g:
        yield flush()
