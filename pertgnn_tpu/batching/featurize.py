"""Vectorized node featurization from the resource table.

Replaces the reference's train-time `get_x`
(/root/reference/pert_gnn.py:40-67): node features are the 8 aggregate
resource-usage values for (trace 30 s bucket, node's microservice), plus a
missing indicator. The reference memoizes a per-(timestamp, ms-tuple) Python
loop with lru_cache; here the lookup is one hashed gather over the whole
batch's (bucket, ms) key vector.

Indicator convention (PARITY.md): the live reference convention is
1 = missing (pert_gnn.py:50, 62-66); the reverse (preprocess-time, dead)
convention 1 = present (misc.py:153) is available via
`missing_indicator_is_one=False`.

Robustness divergence: the reference would KeyError on a microservice that
has resource rows but not at the trace's exact bucket (pert_gnn.py:59 uses
exact .loc); here any (bucket, ms) pair absent from the table is treated as
missing.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from pertgnn_tpu.ingest.schema import NUM_RESOURCE_FEATURES


#: ms ids must fit the low bits of the packed key; buckets the high bits.
#: |ts| < 2^40 and 0 <= ms < 2^22 keep ts*2^22 + ms inside int64 with a
#: sign bit to spare — anything outside falls back to the MultiIndex path
#: (VERDICT r4 weak #5: the pack previously had no bound check, so
#: adversarial real-data ids could silently collide or wrap).
_MS_BITS = 22
_MS_LIMIT = np.int64(1) << _MS_BITS
_TS_LIMIT = np.int64(1) << 40


class ResourceLookup:
    """Hashed (timestamp_bucket, msname) -> feature-row gather."""

    def __init__(self, resource_df: pd.DataFrame,
                 missing_indicator_is_one: bool = True):
        feat_cols = [c for c in resource_df.columns
                     if c not in ("timestamp", "msname")]
        if len(feat_cols) != NUM_RESOURCE_FEATURES:
            raise ValueError(
                f"expected {NUM_RESOURCE_FEATURES} feature columns, got "
                f"{feat_cols}")
        self._init_arrays(
            resource_df["timestamp"].to_numpy(dtype=np.int64),
            resource_df["msname"].to_numpy(dtype=np.int64),
            resource_df[feat_cols].to_numpy(dtype=np.float32),
            missing_indicator_is_one)

    @classmethod
    def from_arrays(cls, ts: np.ndarray, ms: np.ndarray,
                    values: np.ndarray,
                    missing_indicator_is_one: bool = True
                    ) -> "ResourceLookup":
        """Rebuild a lookup from `to_arrays()` output — the arena
        store's persistence path (batching/arena_store.py): a warm
        process reconstructs the table without the resource DataFrame
        (and therefore without running ingest at all)."""
        self = cls.__new__(cls)
        self._init_arrays(np.asarray(ts, dtype=np.int64),
                          np.asarray(ms, dtype=np.int64),
                          np.asarray(values, dtype=np.float32),
                          missing_indicator_is_one)
        return self

    def _init_arrays(self, ts: np.ndarray, ms: np.ndarray,
                     values: np.ndarray,
                     missing_indicator_is_one: bool) -> None:
        if values.ndim != 2 or values.shape[1] != NUM_RESOURCE_FEATURES:
            raise ValueError(
                f"expected (rows, {NUM_RESOURCE_FEATURES}) feature "
                f"values, got shape {values.shape}")
        self._values = values
        self._ts, self._ms = ts, ms
        self._packed = bool(np.all(self._in_bounds(ts, ms)))
        if self._packed:
            self._index = pd.Index(self._key(ts, ms))
        else:
            self._index = pd.MultiIndex.from_arrays([ts, ms])
        self.missing_indicator_is_one = missing_indicator_is_one
        self.num_features = NUM_RESOURCE_FEATURES + 1

    def to_arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(ts_bucket, ms_id, values) — everything `from_arrays` needs
        to reconstruct this table bit-identically."""
        return self._ts, self._ms, self._values

    @staticmethod
    def _in_bounds(ts: np.ndarray, ms: np.ndarray) -> np.ndarray:
        return ((ms >= 0) & (ms < _MS_LIMIT)
                & (ts > -_TS_LIMIT) & (ts < _TS_LIMIT))

    @staticmethod
    def _key(ts: np.ndarray, ms: np.ndarray) -> np.ndarray:
        return ts.astype(np.int64) * _MS_LIMIT + ms.astype(np.int64)

    def _lookup(self, ts: np.ndarray, ms: np.ndarray) -> np.ndarray:
        """Row index into the table per (bucket, ms) pair; -1 = absent."""
        if not self._packed:
            return self._index.get_indexer(
                pd.MultiIndex.from_arrays([ts, ms]))
        inb = self._in_bounds(ts, ms)
        if inb.all():
            return self._index.get_indexer(self._key(ts, ms))
        # a packed table holds only in-bounds keys, so an out-of-bounds
        # query CANNOT be present — but its wrapped packed key could
        # alias a real one; neutralize before the gather, then force
        # those rows to "missing"
        zero = np.zeros((), dtype=np.int64)
        locs = self._index.get_indexer(
            self._key(np.where(inb, ts, zero), np.where(inb, ms, zero)))
        locs[~inb] = -1
        return locs

    def __call__(self, ts_bucket: np.ndarray, ms_id: np.ndarray,
                 feature_mask: np.ndarray | None = None) -> np.ndarray:
        """Features for parallel arrays of buckets and microservice ids.

        Returns (len(ms_id), 9) float32: 8 resource features (0 where
        missing) + indicator column.

        `feature_mask`: nodes where it is False are treated as missing
        regardless of the table — the reference's live pert behavior
        feeds features only to the LAST stage-copy of each microservice
        (pert_gnn.py:56: `ms2nid` is a dict comprehension over the
        duplicated stage list, so later copies overwrite earlier ones
        and only the last index is ever assigned; discovered by
        benchmarks/parity/reference_driver_crosscheck.py, PARITY.md).
        """
        locs = self._lookup(np.asarray(ts_bucket, dtype=np.int64),
                            np.asarray(ms_id, dtype=np.int64))
        present = locs >= 0
        if feature_mask is not None:
            present = present & np.asarray(feature_mask, dtype=bool)
        x = np.zeros((len(locs), NUM_RESOURCE_FEATURES + 1), dtype=np.float32)
        x[present, :-1] = self._values[locs[present]]
        if self.missing_indicator_is_one:
            x[~present, -1] = 1.0
        else:
            x[present, -1] = 1.0
        return x
