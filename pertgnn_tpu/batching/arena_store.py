"""Persistent on-disk arena store: the data path's cold-start killer.

PR 3 made every *executable* resumable from disk; every process still
re-ran the entire host data path from raw inputs — preprocess → graph
construct → mixture collation → featurize — just to rebuild the SAME
``MixtureArena`` / ``FeatureArena`` byte for byte. This module persists
those arenas (plus the per-epoch-invariant pack metadata: splits,
budget, vocab sizes, the resource-lookup table) as ``.npy`` files under
``--arena_cache_dir``, memory-mapped back on load, so a warm process
skips ingest entirely: its first epoch gathers from the mmap'd arenas
exactly as the cold process gathered from the freshly built ones —
bit-identical batches by construction
(benchmarks/pipeline_bench.py asserts it across real processes).

Keying reuses the AOT content-hash machinery (``aot/keys.cache_key``):
sha256 over the ingest/data/graph Config subtree that shapes the arenas,
the arena-relevant model fields (``use_node_depth``,
``feature_all_stage_copies``, ``missing_indicator_is_one``), and a
caller-supplied raw-input fingerprint (synthetic spec, or the artifact/
CSV tree's file stats — ``cli/common.raw_input_fingerprint``). A miss
with other entries present diffs the persisted components and names the
changed ingredient loudly (same discipline as ``aot/store.py``); a
corrupt or truncated entry logs a warning and falls back to a fresh
build — never a crash.

Telemetry (docs/OBSERVABILITY.md): ``arena.cache_hit`` /
``arena.cache_miss`` (reason ``absent``/``corrupt``),
``arena.invalidated``, ``arena.build_seconds`` /
``arena.load_seconds`` / ``arena.save_seconds`` histograms, and the
``arena.mmap_bytes`` gauge (bytes now served from mmap instead of
rebuilt RAM).

TRUST BOUNDARY: entries are plain ``.npy`` arrays + a JSON manifest —
no pickle, no code execution at load (unlike the executable store). But
the arrays ARE the training data: whoever can write the cache dir can
silently alter every later run's features and labels. Point
``--arena_cache_dir`` only at directories writable solely by the user
running the jobs (docs/GUIDE.md §8).
"""

from __future__ import annotations

import json
import logging
import os
import time

import numpy as np

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.arena import FeatureArena, MixtureArena
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import Mixture
from pertgnn_tpu.batching.pack import BatchBudget
from pertgnn_tpu.store import durable
from pertgnn_tpu.store.durable import StoreCorruption, StoreLock

log = logging.getLogger(__name__)

# Bump to orphan every existing entry on a layout/semantics change of
# the store itself (it rides inside the key via fn_id). v2: graftvault
# durable layout — immutable generation dirs committed by one
# checksummed ``<key>.manifest.json`` replace (store/durable.py).
_STORE_VERSION = 2
_FN_ID = f"batching.arena_store.v{_STORE_VERSION}"

_ARENA_FIELDS = ("node_start", "node_count", "edge_start", "edge_count",
                 "ms_id", "node_depth", "pattern_prob", "pattern_size",
                 "feature_mask", "senders", "receivers", "edge_iface",
                 "edge_rpctype", "edge_duration")
_FEAT_FIELDS = ("pair_of_example", "feat_start", "x")
_SPLIT_FIELDS = ("entry_ids", "ts_buckets", "ys")
_LOOKUP_FIELDS = ("ts", "ms", "values")


def arena_cache_key(cfg, fingerprint: dict) -> tuple[str, dict]:
    """(hex key, components) for one dataset's arenas.

    Only the Config subtrees that shape the ARENAS are keyed: the whole
    IngestConfig, the dataset-shaping DataConfig fields (NOT
    shuffle_seed — epoch order is applied at pack time — and NOT
    arena_cache_dir itself), graph_type, and the three model fields
    baked into arena/feature content. Keying more would invalidate the
    cache on knobs the arenas never see (lr, epochs, serve tuning)."""
    from pertgnn_tpu import aot

    data = cfg.data
    config = {
        "ingest": cfg.ingest,
        "data": {k: getattr(data, k)
                 for k in ("max_traces", "split", "batch_size",
                           "max_nodes_per_batch", "max_edges_per_batch",
                           "budget_headroom")},
        "graph_type": cfg.graph_type,
        "model": {k: getattr(cfg.model, k)
                  for k in ("use_node_depth", "feature_all_stage_copies",
                            "missing_indicator_is_one")},
    }
    # env={}: arenas are host artifacts — a jax upgrade or device change
    # must NOT orphan them (contrast aot executables, which are welded
    # to the lowering environment)
    return aot.cache_key(fn_id=_FN_ID, config=config,
                         args_sig=fingerprint, env={})


def _slot_id(fingerprint: dict) -> str:
    """The logical-input identity a key belongs to — the arena twin of
    the aot store's per-program `name` slot. Invalidation diagnostics
    only compare entries WITHIN a slot: two different corpora (bench
    workloads at different sizes, two artifact dirs) coexisting in one
    store are not 'invalidation', and warning about them would teach
    operators to ignore the one log line that matters. For file-backed
    inputs the identity is (kind, dir) — edited files stay in-slot and
    diff loudly; for synthetic specs the spec IS the input, so any spec
    change is a different workload, not a drifted ingredient."""
    import hashlib
    import json as _json

    from pertgnn_tpu.aot.keys import _canonical

    if fingerprint.get("kind") in ("artifacts", "raw_csvs"):
        ident: dict = {"kind": fingerprint["kind"],
                       "dir": fingerprint.get("dir")}
    else:
        ident = fingerprint
    blob = _json.dumps(_canonical(ident), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def mixtures_from_arena(arena: MixtureArena) -> dict[int, Mixture]:
    """Reconstruct the per-entry Mixture dict from the flat arenas —
    what the serving engine's request path (``pack_single``,
    ``request_size``) needs from a warm cache without re-running graph
    construction. Arrays are views (zero-copy over the mmap).

    Edge order within each reconstructed mixture is the arena's
    receiver-sorted order, not the construction order — packing is
    bit-identical either way: the packer's stable receiver sort maps
    both to the same final batch order (pinned by
    tests/test_arena_store.py)."""
    out: dict[int, Mixture] = {}
    for e in range(len(arena.node_start)):
        ns, nc = int(arena.node_start[e]), int(arena.node_count[e])
        if ns < 0:
            continue
        es, ec = int(arena.edge_start[e]), int(arena.edge_count[e])
        out[e] = Mixture(
            entry_id=e,
            senders=arena.senders[es:es + ec],
            receivers=arena.receivers[es:es + ec],
            edge_iface=arena.edge_iface[es:es + ec],
            edge_rpctype=arena.edge_rpctype[es:es + ec],
            edge_duration=arena.edge_duration[es:es + ec],
            ms_id=arena.ms_id[ns:ns + nc],
            node_depth=arena.node_depth[ns:ns + nc],
            pattern_prob=arena.pattern_prob[ns:ns + nc],
            pattern_size=arena.pattern_size[ns:ns + nc],
            feature_mask=arena.feature_mask[ns:ns + nc],
            num_nodes=nc, num_edges=ec)
    return out


class ArenaStore:
    """Content-addressed dataset arenas under ``root``.

    Layout (graftvault, store/durable.py): an immutable generation dir
    ``<root>/<key>@g<N>/`` holding ``meta.json`` (key components +
    scalars) and one ``.npy`` per array, committed by ONE durable
    replace of ``<root>/<key>.manifest.json`` (which records every
    file's CRC32C — what ``graftvault scrub`` verifies). Arrays load
    with ``np.load(mmap_mode="r")`` so a warm process pages in only
    what an epoch actually gathers; writers serialize under the store
    lock (``<root>/.lock``)."""

    def __init__(self, root: str, bus=None):
        self.root = root
        self._injected_bus = bus
        os.makedirs(root, exist_ok=True)

    @property
    def _bus(self):
        return (self._injected_bus if self._injected_bus is not None
                else telemetry.get_bus())

    def exists(self, key: str) -> bool:
        """Whether a committed entry for ``key`` is on disk (manifest
        presence — the warm-start evidence fleet workers probe)."""
        return os.path.exists(durable.manifest_path(self.root, key))

    def _entry_dir(self, key: str) -> str | None:
        """The committed generation dir for ``key``, or None (absent).
        Raises StoreCorruption on a torn manifest."""
        resolved = durable.resolve_entry(self.root, key, store="arena")
        return None if resolved is None else resolved[0]

    # -- the one-stop entry point ---------------------------------------

    def load_or_build(self, cfg, fingerprint: dict, build_fn):
        """The Dataset for (cfg, fingerprint): a hit reconstructs it
        from mmap'd arrays (zero ingest / graph / featurize work), a
        miss calls ``build_fn()`` (the full ingest path) and persists
        the result for the next process."""
        key, components = arena_cache_key(cfg, fingerprint)
        slot = _slot_id(fingerprint)
        ds = self.load(key, components, cfg, slot=slot)
        if ds is not None:
            return ds
        bus = self._bus
        t0 = time.perf_counter()
        with bus.span("arena.build", key=key[:12]):
            ds = build_fn()
        bus.histogram("arena.build_seconds", time.perf_counter() - t0)
        self.save(key, components, ds, slot=slot)
        return ds

    # -- load ------------------------------------------------------------

    def load(self, key: str, components: dict, cfg, *,
             slot: str | None = None):
        """The cached Dataset for ``key``, or None (miss/corrupt — the
        caller builds fresh and saves). ``slot`` scopes the miss
        diagnostics to entries of the same logical input."""
        bus = self._bus
        t0 = time.perf_counter()
        try:
            d = self._entry_dir(key)
        except StoreCorruption as e:
            log.warning("corrupt arena store entry %s (%s: %s) — falling "
                        "back to a fresh build", key, type(e).__name__, e)
            bus.counter("arena.cache_miss", reason="corrupt")
            return None
        if d is None:
            self._log_invalidation(key, components, slot)
            bus.counter("arena.cache_miss", reason="absent")
            return None
        try:
            with bus.span("arena.load", key=key[:12]):
                ds, mmap_bytes = self._load_dataset(d, cfg)
        except Exception as e:
            # corrupt/truncated/stale entry: NEVER crash the caller —
            # rebuild fresh (the save overwrites this entry)
            log.warning("corrupt arena store entry %s (%s: %s) — falling "
                        "back to a fresh build", key, type(e).__name__, e)
            bus.counter("arena.cache_miss", reason="corrupt")
            return None
        dt = time.perf_counter() - t0
        bus.counter("arena.cache_hit")
        bus.histogram("arena.load_seconds", dt)
        bus.gauge("arena.mmap_bytes", mmap_bytes)
        log.info("arena store: hit %s (%.1f MiB mmap'd in %.3fs) — ingest "
                 "+ graph construction + featurization skipped",
                 key, mmap_bytes / 2**20, dt)
        return ds

    def _load_dataset(self, d: str, cfg):
        from pertgnn_tpu.batching.dataset import Dataset, Split

        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("store_version") != _STORE_VERSION:
            raise ValueError(f"store version {meta.get('store_version')!r}"
                             f" != {_STORE_VERSION}")
        mmap_bytes = 0

        def arr(name: str):
            nonlocal mmap_bytes
            path = os.path.join(d, f"{name}.npy")
            a = np.load(path, mmap_mode="r")
            mmap_bytes += a.nbytes
            return a

        arena = MixtureArena(**{f: arr(f"arena_{f}")
                                for f in _ARENA_FIELDS})
        feats = FeatureArena(**{f: arr(f"feat_{f}") for f in _FEAT_FIELDS})
        lookup = ResourceLookup.from_arrays(
            arr("lookup_ts"), arr("lookup_ms"), arr("lookup_values"),
            missing_indicator_is_one=cfg.model.missing_indicator_is_one)
        splits, feat_slices = {}, {}
        off = 0
        for name in meta["split_names"]:
            splits[name] = Split(**{f: arr(f"split_{name}_{f}")
                                    for f in _SPLIT_FIELDS})
            feat_slices[name] = slice(off, off + len(splits[name]))
            off += len(splits[name])
        if off != len(feats.pair_of_example):
            raise ValueError(
                f"split rows ({off}) do not cover the feature arena's "
                f"examples ({len(feats.pair_of_example)})")
        s = meta["scalars"]
        return Dataset(
            mixtures=mixtures_from_arena(arena), lookup=lookup,
            budget=BatchBudget(**meta["budget"]), splits=splits,
            num_ms=s["num_ms"], num_entries=s["num_entries"],
            num_interfaces=s["num_interfaces"],
            num_rpctypes=s["num_rpctypes"],
            node_feature_dim=s["node_feature_dim"], config=cfg,
            _arena=arena, _feat_all=feats,
            _feat_slices=feat_slices), mmap_bytes

    # -- save ------------------------------------------------------------

    def save(self, key: str, components: dict, dataset, *,
             slot: str | None = None) -> str | None:
        """Persist a freshly built Dataset's arenas under ``key``.
        Durable (store/durable.py): arrays land fsync'd in an immutable
        generation dir and ONE checksummed-manifest replace commits the
        entry — a kill at any instant leaves the previous entry fully
        live (never the old double-replace window where the current
        entry was gone while the backup pointed at the same
        generation); concurrent writers serialize under the store
        lock, and either one's entry is valid (content-addressed,
        deterministic)."""
        bus = self._bus
        t0 = time.perf_counter()
        try:
            arena = dataset.arena()
            feats = dataset.feat_arena()  # also fixes the split slices
            total = 0
            with StoreLock(os.path.join(self.root, ".lock"),
                           store="arena", bus=bus), \
                    durable.EntryWriter(self.root, key, store="arena",
                                        bus=bus) as w:
                def put(name: str, a) -> None:
                    nonlocal total
                    total += w.put_array(f"{name}.npy", a)

                for f in _ARENA_FIELDS:
                    put(f"arena_{f}", getattr(arena, f))
                for f in _FEAT_FIELDS:
                    put(f"feat_{f}", getattr(feats, f))
                ts, ms, values = dataset.lookup.to_arrays()
                put("lookup_ts", ts)
                put("lookup_ms", ms)
                put("lookup_values", values)
                for name, split in dataset.splits.items():
                    for f in _SPLIT_FIELDS:
                        put(f"split_{name}_{f}", getattr(split, f))
                meta = {
                    "key": key, "slot": slot,
                    "store_version": _STORE_VERSION,
                    "created_unix_time": time.time(),
                    "split_names": list(dataset.splits),
                    "budget": {"max_graphs": dataset.budget.max_graphs,
                               "max_nodes": dataset.budget.max_nodes,
                               "max_edges": dataset.budget.max_edges},
                    "scalars": {
                        "num_ms": dataset.num_ms,
                        "num_entries": dataset.num_entries,
                        "num_interfaces": dataset.num_interfaces,
                        "num_rpctypes": dataset.num_rpctypes,
                        "node_feature_dim": dataset.node_feature_dim,
                    },
                    **components,
                }
                final = w.commit(meta)
        except Exception as e:
            # a failed save must not fail the run the dataset was built
            # FOR — next process rebuilds
            log.warning("arena store: could not persist %s (%s: %s)",
                        key, type(e).__name__, e)
            return None
        dt = time.perf_counter() - t0
        bus.histogram("arena.save_seconds", dt)
        log.info("arena store: saved %s (%.1f MiB) in %.2fs", key,
                 total / 2**20, dt)
        return final

    # -- invalidation diagnostics ---------------------------------------

    @staticmethod
    def _diff_fingerprint_files(prev_args: dict, now_args: dict
                                ) -> list[str]:
        """Per-file diff of the raw-input fingerprint: the one log line
        an operator actually needs is WHICH shard changed, not a
        400-char list repr.  Works for both fingerprint modes (stat and
        content — cli/common.raw_input_fingerprint)."""
        def rows(args):
            return {r[0]: tuple(r[1:]) for r in (args.get("files") or [])
                    if isinstance(r, (list, tuple)) and r}

        pf, nf = rows(prev_args), rows(now_args)
        added = sorted(set(nf) - set(pf))
        removed = sorted(set(pf) - set(nf))
        changed = sorted(k for k in set(pf) & set(nf) if pf[k] != nf[k])
        out: list[str] = []

        def show(label, names, detail=False):
            if not names:
                return
            shown = ", ".join(
                (f"{n} ({pf[n]} -> {nf[n]})" if detail else n)
                for n in names[:5])
            more = f" (+{len(names) - 5} more)" if len(names) > 5 else ""
            out.append(f"{label} file(s): {shown}{more}")

        show("changed", changed, detail=True)
        show("added", added)
        show("removed", removed)
        return out

    def _log_invalidation(self, key: str, components: dict,
                          slot: str | None) -> None:
        """A miss while OTHER entries of the SAME logical input exist
        means an ingredient changed since they were saved — name it
        instead of rebuilding silently (same discipline and diff
        machinery as aot/store.py, whose per-program `name` is this
        store's `slot`). Entries of OTHER slots — different corpora
        legitimately sharing the store, e.g. bench workloads at several
        sizes — are not invalidation and stay silent."""
        from pertgnn_tpu.aot import diff_components

        prev = None
        for _k, mpath in durable.iter_manifests(self.root):
            try:
                m = durable.read_json(mpath, store="arena").get("meta", {})
            except (StoreCorruption, OSError, ValueError):
                continue
            if slot is not None and m.get("slot") != slot:
                continue
            if (prev is None or m.get("created_unix_time", 0)
                    > prev.get("created_unix_time", 0)):
                prev = m
        if prev is None:
            return
        raw = diff_components(prev, components)
        # the raw-input fingerprint diffs as one enormous list repr —
        # replace it with a per-file diff naming the exact shard that
        # changed (the diagnostic the operator acts on)
        file_msgs: list[str] = []
        prev_args = prev.get("args")
        now_args = components.get("args")
        if isinstance(prev_args, dict) and isinstance(now_args, dict) \
                and ("files" in prev_args or "files" in now_args):
            file_msgs = self._diff_fingerprint_files(prev_args, now_args)
            if file_msgs:
                raw = [c for c in raw if not c.startswith("args.files")]
        changed = file_msgs + [
            c if len(c) <= 400 else c[:400] + "...<truncated>"
            for c in raw]
        log.warning(
            "arena store: invalidating (saved key %s != wanted %s); "
            "changed: %s — rebuilding the arenas fresh",
            prev.get("key", "?")[:12], key[:12],
            "; ".join(changed) if changed else "unknown (metadata "
            "predates these components)")
        self._bus.counter("arena.invalidated")
