"""Dataset assembly: subsample, positional split, epoch iteration.

Matches the reference's split semantics exactly: take the FIRST
`max_traces` traces in tr2data insertion order (pert_gnn.py:297-299), then a
POSITIONAL 60/20/20 split (pert_gnn.py:196-210) — not random, not
chronological; order is grouped-by-entry-then-trace (SURVEY.md §2.1). Train
batches are shuffled per epoch (DataLoader shuffle=True for train only,
pert_gnn.py:201-209).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence

import numpy as np

from pertgnn_tpu.config import Config
from pertgnn_tpu.batching.arena import (
    CompactBatch, FeatureArena, IndexBatch, MixtureArena, assign_batches,
    build_feature_arena, build_mixture_arena, materialize_host,
    pack_epoch_compact, pack_epoch_indices)
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import Mixture, build_mixtures
from pertgnn_tpu.batching.pack import (
    BatchBudget, PackedBatch, derive_budget, pack_examples)
from pertgnn_tpu.graphs.construct import build_runtime_graphs
from pertgnn_tpu.ingest.assemble import TraceTable, assemble
from pertgnn_tpu.ingest.preprocess import PreprocessResult


def split_indices(n: int, fractions: Sequence[float]) -> list[np.ndarray]:
    """Positional split: [0, f0*n), [f0*n, (f0+f1)*n), ... (pert_gnn.py:198-200)."""
    bounds = np.cumsum([0.0] + list(fractions))
    edges = [int(n * b) for b in bounds[:-1]] + [n]
    # final edge takes any rounding remainder, like the reference's trailing
    # slice data_list[int(0.8*n):]
    return [np.arange(edges[i], edges[i + 1]) for i in range(len(fractions))]


@dataclasses.dataclass
class Split:
    entry_ids: np.ndarray
    ts_buckets: np.ndarray
    ys: np.ndarray

    def __len__(self):
        return len(self.ys)


@dataclasses.dataclass
class Dataset:
    mixtures: dict[int, Mixture]
    lookup: ResourceLookup
    budget: BatchBudget
    splits: dict[str, Split]           # train / valid / test
    num_ms: int                        # embedding vocab sizes
    num_entries: int
    num_interfaces: int
    num_rpctypes: int
    node_feature_dim: int
    config: Config
    # Built lazily: shared mixture arena + ONE feature arena over all
    # splits' (entry, ts_bucket) pairs (shared so chip-resident arenas have
    # one shape -> one compile for train and eval), plus a packed-batch
    # cache for the deterministic unshuffled splits (valid/test are
    # identical every epoch — pack them once).
    _arena: MixtureArena | None = None
    # DeviceArenas, lazy (see device_arenas()). A real dataclass field like
    # the sibling caches so dataclasses.replace() carries it over instead of
    # silently dropping it (which would rebuild a second HBM-resident copy
    # and defeat the one-copy contract device_arenas() documents).
    _device_arenas: object | None = None
    _feat_all: FeatureArena | None = None
    _feat_slices: dict = dataclasses.field(default_factory=dict)
    _epoch_cache: dict = dataclasses.field(default_factory=dict)

    def arena(self) -> MixtureArena:
        if self._arena is None:
            self._arena = build_mixture_arena(self.mixtures)
        return self._arena

    def device_arenas(self):
        """Single-device chip-resident arenas, built ONCE per dataset and
        shared by every consumer (fit() and bench ceilings alike) so HBM
        holds one copy regardless of how many programs gather from it.
        Mesh paths build their own sharded copies (materialize.
        build_device_arenas(sharding=...))."""
        if self._device_arenas is None:
            from pertgnn_tpu.batching.materialize import build_device_arenas
            self._device_arenas = build_device_arenas(self.arena(),
                                                      self.feat_arena())
        return self._device_arenas

    def feat_arena(self) -> FeatureArena:
        """The whole-dataset feature arena (all splits' unique pairs)."""
        if self._feat_all is None:
            names = list(self.splits)
            entry_ids = np.concatenate(
                [self.splits[n].entry_ids for n in names])
            ts_buckets = np.concatenate(
                [self.splits[n].ts_buckets for n in names])
            self._feat_all = build_feature_arena(
                self.arena(), entry_ids, ts_buckets, self.lookup,
                node_depth_in_x=self.config.model.use_node_depth)
            off = 0
            for n in names:
                self._feat_slices[n] = slice(off, off + len(self.splits[n]))
                off += len(self.splits[n])
        return self._feat_all

    def _feat_arena(self, split: str) -> FeatureArena:
        """Split view of the shared arena: same rows, per-split examples."""
        full = self.feat_arena()
        return dataclasses.replace(
            full, pair_of_example=full.pair_of_example[
                self._feat_slices[split]])

    def _cacheable(self, split: str, shuffle: bool) -> bool:
        # Only the deterministic EVAL splits are re-consumed identically
        # every epoch; caching "train" would eagerly pack the whole split
        # just because fit() peeks at one init sample.
        return not shuffle and split != "train"

    def _cached_epoch(self, kind: str, split: str, shuffle: bool,
                      make_stream) -> Iterator:
        """Shared cache shell for every epoch-stream flavor: deterministic
        eval splits are materialized once per (kind, split); everything
        else streams fresh."""
        key = (kind, split)
        if self._cacheable(split, shuffle) and key in self._epoch_cache:
            yield from self._epoch_cache[key]
            return
        stream = make_stream()
        if self._cacheable(split, shuffle):
            cached = list(stream)
            self._epoch_cache[key] = cached
            yield from cached
        else:
            yield from stream

    def _epoch_order(self, split: str, shuffle: bool,
                     seed: int) -> np.ndarray:
        order = np.arange(len(self.splits[split]))
        if shuffle:
            order = np.random.default_rng(seed).permutation(order)
        return order

    def index_batches(self, split: str, shuffle: bool = False,
                      seed: int = 0) -> Iterator[IndexBatch]:
        """Gather-recipe stream for device-side materialization
        (batching/materialize.py). Deterministic eval splits are cached."""
        s = self.splits[split]
        return self._cached_epoch(
            "idx", split, shuffle,
            lambda: pack_epoch_indices(
                self.arena(), self._feat_arena(split), s.entry_ids, s.ys,
                self.budget,
                order=self._epoch_order(split, shuffle, seed)))

    def compact_batches(self, split: str, shuffle: bool = False,
                        seed: int = 0) -> Iterator[CompactBatch]:
        """O(graphs) gather-recipe stream for device-side EXPANSION +
        materialization (materialize.expand_compact) — the cheapest
        possible per-epoch host path. Deterministic eval splits cached."""
        s = self.splits[split]
        return self._cached_epoch(
            "compact", split, shuffle,
            lambda: pack_epoch_compact(
                self.arena(), self._feat_arena(split), s.entry_ids, s.ys,
                self.budget,
                order=self._epoch_order(split, shuffle, seed)))

    def materializer(self, split: str):
        """Callable turning one of this split's IndexBatches into a host
        PackedBatch — multi-host input sharding materializes only the
        shards this process's devices consume (parallel/multihost.py)."""
        arena = self.arena()
        feats = self._feat_arena(split)
        return lambda idx: materialize_host(arena, feats, idx)

    def batches(self, split: str, shuffle: bool = False,
                seed: int = 0) -> Iterator[PackedBatch]:
        return self._cached_epoch(
            "packed", split, shuffle,
            lambda: (materialize_host(self.arena(),
                                      self._feat_arena(split), i)
                     for i in self.index_batches(split, shuffle=shuffle,
                                                 seed=seed)))

    def batches_slow(self, split: str, shuffle: bool = False,
                     seed: int = 0) -> Iterator[PackedBatch]:
        """The readable per-example reference packer (`pack_examples`);
        `batches()` is the vectorized arena path and must match it batch for
        batch (tests/test_batching.py parity)."""
        s = self.splits[split]
        order = np.arange(len(s))
        if shuffle:
            order = np.random.default_rng(seed).permutation(order)
        yield from pack_examples(
            self.mixtures, s.entry_ids[order], s.ts_buckets[order],
            s.ys[order], self.budget, self.lookup,
            node_depth_in_x=self.config.model.use_node_depth)

    def num_batches(self, split: str) -> int:
        """Batch count for the UNSHUFFLED order (the greedy packer's
        assignment on sizes only — arena.assign_batches is the single
        source of truth for the rule).

        Greedy packing is order-dependent, so a shuffled epoch may produce a
        different count — step loops must iterate `batches()` rather than
        range(num_batches())."""
        ids = self.splits[split].entry_ids
        arena = self.arena()
        batch_idx, _, _, _ = assign_batches(
            arena.node_count[ids], arena.edge_count[ids], self.budget)
        return int(batch_idx[-1]) + 1 if len(batch_idx) else 0


def build_dataset(pre: PreprocessResult, cfg: Config,
                  table: TraceTable | None = None) -> Dataset:
    """L2 artifacts -> ready-to-train dataset (all host work, vectorized)."""
    if table is None:
        table = assemble(pre, cfg.ingest)
    graphs = build_runtime_graphs(pre, table, cfg.graph_type)
    mixtures = build_mixtures(
        graphs, table.entry2runtimes,
        feature_all_stage_copies=cfg.model.feature_all_stage_copies)
    lookup = ResourceLookup(
        pre.resources,
        missing_indicator_is_one=cfg.model.missing_indicator_is_one)
    if len(table.meta) == 0:
        raise ValueError(
            "no traces survived preprocessing — check the ingest filters "
            f"(min_traces_per_entry={cfg.ingest.min_traces_per_entry}, "
            f"min_resource_coverage={cfg.ingest.min_resource_coverage}) "
            f"against the input; stats: {pre.stats}")
    return dataset_from_parts(mixtures, lookup, table.meta, cfg)


def dataset_from_parts(mixtures: dict[int, Mixture], lookup: ResourceLookup,
                       meta, cfg: Config) -> Dataset:
    """The mixtures/lookup/meta -> Dataset tail of build_dataset, shared
    with the stream subsystem: a delta-merged corpus
    (pertgnn_tpu/stream/merge.py) derives its budget, splits, and vocab
    sizes through the SAME code as a from-scratch rebuild, which is what
    makes the bit-identical-packing contract provable rather than
    maintained by hand."""
    meta = meta.iloc[:cfg.data.max_traces]
    if len(meta) == 0:
        raise ValueError("dataset meta is empty — nothing to batch")
    entry_ids = meta["entry_id"].to_numpy(np.int64)
    ts_buckets = meta["ts_bucket"].to_numpy(np.int64)
    ys = meta["y"].to_numpy(np.float32)

    budget = derive_budget(mixtures, entry_ids, cfg.data.batch_size,
                           headroom=cfg.data.budget_headroom)
    if cfg.data.max_nodes_per_batch is not None:
        budget = dataclasses.replace(budget,
                                     max_nodes=cfg.data.max_nodes_per_batch)
    if cfg.data.max_edges_per_batch is not None:
        budget = dataclasses.replace(budget,
                                     max_edges=cfg.data.max_edges_per_batch)

    parts = split_indices(len(meta), cfg.data.split)
    names = ("train", "valid", "test")
    splits = {name: Split(entry_ids[idx], ts_buckets[idx], ys[idx])
              for name, idx in zip(names, parts)}

    # embedding sizes from data maxima (reference derives them by scanning
    # the data list, pert_gnn.py:306-328)
    num_ifaces = 1 + max((int(m.edge_iface.max()) if m.num_edges else 0
                          for m in mixtures.values()), default=0)
    num_rpctypes = 1 + max((int(m.edge_rpctype.max()) if m.num_edges else 0
                            for m in mixtures.values()), default=0)
    num_ms = 1 + max(int(m.ms_id.max()) for m in mixtures.values())
    num_entries = 1 + int(max(mixtures.keys()))
    node_feature_dim = lookup.num_features + (
        1 if cfg.model.use_node_depth else 0)

    return Dataset(
        mixtures=mixtures, lookup=lookup, budget=budget, splits=splits,
        num_ms=num_ms, num_entries=num_entries, num_interfaces=num_ifaces,
        num_rpctypes=num_rpctypes, node_feature_dim=node_feature_dim,
        config=cfg)
