"""Bounded double-buffered prefetch: overlap host work with device compute.

The staged-epoch path ships a whole epoch's recipes in one transfer, but
past its MiB cap it used to degrade to FULLY SYNCHRONOUS per-chunk
transfers — the host packs chunk i+1 only after the device finished
chunk i, serializing the two halves of the pipeline exactly at the
production scale where the corpus no longer fits the cap.
PyTorch-Direct (arXiv:2101.07956) and DGL's async dataloader
(arXiv:1909.01315) both treat this overlap as a first-class subsystem;
this module is that subsystem for the repo's input path.

``prefetch_iter(items, fn, depth)`` runs ``fn`` (host pack + the async
``device_put``) over ``items`` on ONE background thread, ``depth``
results ahead of the consumer, through a bounded queue:

- **bit-identical** to the eager ``(fn(x) for x in items)`` — same
  items, same order, same single-threaded ``fn`` call sequence (pinned
  by tests/test_prefetch.py hypothesis properties);
- an upstream/``fn`` exception is re-raised AT THE CONSUMER, after every
  earlier item was yielded (never silently truncates an epoch);
- closing the consumer early (``break`` out of an epoch, an interrupt)
  stops the producer promptly and joins it — no thread leak, no
  unbounded queue growth;
- starvation accounting lands on the telemetry bus when the iterator
  finishes (``prefetch.device_starved_s``: the consumer sat waiting for
  the next batch — the HOST is the bottleneck; ``prefetch.host_starved_s``:
  the producer sat blocked on a full queue — the DEVICE is the
  bottleneck; plus ``prefetch.wall_s``), so a bench can attribute the
  remaining fit-vs-ceiling gap to the correct side.

``depth <= 0`` degrades to the eager synchronous loop (the A/B control
benchmarks/pipeline_bench.py measures against).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

from pertgnn_tpu import telemetry

# producer-side poll period while blocked on a full queue / handing over
# the sentinel: bounds how long a closed consumer leaves the thread alive
_POLL_S = 0.05


class _Raised:
    """Envelope carrying a producer-side exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_iter(items: Iterable, fn: Callable | None = None,
                  depth: int = 2, *, source: str = "prefetch",
                  bus=None) -> Iterator:
    """Yield ``fn(item)`` for each item, computed up to ``depth`` ahead
    on a background thread. ``fn=None`` is the identity. ``depth<=0``
    is the eager synchronous path (no thread, no queue) — the oracle
    the property tests compare against."""
    if fn is None:
        fn = lambda x: x  # noqa: E731
    if depth <= 0:
        for it in items:
            yield fn(it)
        return

    bus = bus if bus is not None else telemetry.get_bus()
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()
    # producer-side starvation total, read by the consumer only after
    # join() — no lock needed
    host_starved = [0.0]

    def _put(item) -> bool:
        """Blocking put that aborts when the consumer closed early;
        returns False on abort. Time blocked counts as host starvation
        (the queue is full: the device side is the bottleneck)."""
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                host_starved[0] += time.perf_counter() - t0
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for it in items:
                if stop.is_set():
                    return
                if not _put(fn(it)):
                    return
        except BaseException as exc:  # lint: allow-silent-except — re-raised at the consumer
            _put(_Raised(exc))
            return
        _put(_END)

    t = threading.Thread(target=produce, daemon=True,
                         name=f"prefetch-{source}")
    t_start = time.perf_counter()
    device_starved = 0.0
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()
            device_starved += time.perf_counter() - t0
            if item is _END:
                return
            if isinstance(item, _Raised):
                raise item.exc
            yield item
    finally:
        stop.set()
        # release a producer blocked on a full queue, then join it so no
        # thread outlives the iterator
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10.0)
        if bus.enabled:
            wall = time.perf_counter() - t_start
            bus.gauge("prefetch.device_starved_s", device_starved,
                      source=source, depth=depth)
            bus.gauge("prefetch.host_starved_s", host_starved[0],
                      source=source, depth=depth)
            bus.gauge("prefetch.wall_s", wall, source=source, depth=depth)
