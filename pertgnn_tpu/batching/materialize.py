"""Device-side batch materialization from chip-resident arenas.

The TPU-native answer to the host-packing bottleneck: topology and feature
arenas are static for a whole run, so they live in HBM (placed once), and
each step the host ships only a small int32 gather recipe (`IndexBatch`,
~1/4 the bytes of a full PackedBatch). The first thing the jitted train step
does is materialize the PackedBatch with device gathers — pure
HBM-bandwidth work that XLA fuses with the model's own input reads. Host
cost per epoch collapses to index arithmetic (`arena.pack_epoch_indices`).

Contrast with the reference, which re-does per-batch host collation +
feature lookup inside its train loop every epoch
(/root/reference/pert_gnn.py:219-231, 40-67).

`materialize_device` must stay the exact twin of `arena.materialize_host`
(tests/test_batching.py device/host parity).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from pertgnn_tpu.batching.arena import FeatureArena, IndexBatch, MixtureArena
from pertgnn_tpu.batching.pack import PackedBatch


class DeviceArenas(NamedTuple):
    """Chip-resident copies of the mixture + (per-split) feature arenas.
    Sentinel conventions are inherited from the host arenas: the last
    node/edge/feature row is the pad row."""

    ms_id: jnp.ndarray
    node_depth: jnp.ndarray
    pattern_prob: jnp.ndarray
    pattern_size: jnp.ndarray
    senders: jnp.ndarray
    receivers: jnp.ndarray
    edge_iface: jnp.ndarray
    edge_rpctype: jnp.ndarray
    edge_duration: jnp.ndarray
    feat_x: jnp.ndarray

    @property
    def node_sentinel(self) -> int:
        return self.ms_id.shape[0] - 1

    @property
    def edge_sentinel(self) -> int:
        return self.senders.shape[0] - 1


def arena_nbytes(arena: MixtureArena, feats: FeatureArena) -> int:
    """Bytes of HBM the chip-resident arenas will occupy (per device — they
    are replicated, not sharded, over a mesh). Drives the
    `arena_hbm_budget_gb` fallback in fit(): the feature arena scales with
    unique (entry, ts_bucket) pairs x mixture width and is unbounded by the
    batch shape."""
    node_e = (arena.ms_id.nbytes + arena.node_depth.nbytes
              + arena.pattern_prob.nbytes + arena.pattern_size.nbytes)
    edge_e = (arena.senders.nbytes + arena.receivers.nbytes
              + arena.edge_iface.nbytes + arena.edge_rpctype.nbytes
              + arena.edge_duration.nbytes)
    return node_e + edge_e + feats.x.nbytes


def build_device_arenas(arena: MixtureArena, feats: FeatureArena,
                        sharding=None) -> DeviceArenas:
    """Place the arenas on device (replicated under `sharding` on a mesh).

    On multi-host meshes every process holds the identical host arenas, so
    the replicated global arrays are assembled with
    make_array_from_process_local_data (device_put cannot target
    non-addressable devices)."""
    if sharding is None:
        put = jax.device_put
    elif jax.process_count() > 1:
        from pertgnn_tpu.parallel.multihost import put_replicated
        put = lambda a: put_replicated(a, sharding)
    else:
        put = lambda a: jax.device_put(a, sharding)
    return DeviceArenas(
        ms_id=put(arena.ms_id), node_depth=put(arena.node_depth),
        pattern_prob=put(arena.pattern_prob),
        pattern_size=put(arena.pattern_size),
        senders=put(arena.senders), receivers=put(arena.receivers),
        edge_iface=put(arena.edge_iface),
        edge_rpctype=put(arena.edge_rpctype),
        edge_duration=put(arena.edge_duration),
        feat_x=put(feats.x))


def materialize_device(dev: DeviceArenas, idx: IndexBatch) -> PackedBatch:
    """Gather a full PackedBatch out of HBM-resident arenas (jit-traceable;
    twin of arena.materialize_host)."""
    node_mask = idx.src_node != dev.node_sentinel
    edge_mask = idx.src_edge != dev.edge_sentinel
    return PackedBatch(
        x=dev.feat_x[idx.src_feat],
        ms_id=dev.ms_id[idx.src_node],
        node_depth=dev.node_depth[idx.src_node],
        node_graph=idx.node_graph,
        node_mask=node_mask,
        pattern_prob=dev.pattern_prob[idx.src_node],
        pattern_size=dev.pattern_size[idx.src_node],
        senders=dev.senders[idx.src_edge] + idx.edge_node_off,
        receivers=dev.receivers[idx.src_edge] + idx.edge_node_off,
        edge_iface=dev.edge_iface[idx.src_edge],
        edge_rpctype=dev.edge_rpctype[idx.src_edge],
        edge_duration=dev.edge_duration[idx.src_edge],
        edge_mask=edge_mask,
        entry_id=idx.entry_id, y=idx.y, graph_mask=idx.graph_mask)


def zero_masked_idx(idx: IndexBatch, arena: MixtureArena,
                    feats: FeatureArena) -> IndexBatch:
    """Inert tail filler for scan chunks in index space: every position the
    sentinel, every graph masked — materializes to a pure-padding batch
    (the IndexBatch analog of pack.zero_masked)."""
    return IndexBatch(
        src_node=np.full_like(idx.src_node, arena.node_sentinel),
        src_feat=np.full_like(idx.src_feat, feats.sentinel),
        node_graph=np.full_like(idx.node_graph, idx.num_graphs - 1),
        src_edge=np.full_like(idx.src_edge, arena.edge_sentinel),
        edge_node_off=np.zeros_like(idx.edge_node_off),
        entry_id=np.zeros_like(idx.entry_id),
        y=np.zeros_like(idx.y),
        graph_mask=np.zeros_like(idx.graph_mask))
