"""Device-side batch materialization from chip-resident arenas.

The TPU-native answer to the host-packing bottleneck: topology and feature
arenas are static for a whole run, so they live in HBM (placed once), and
each step the host ships only a small int32 gather recipe (`IndexBatch`,
~1/4 the bytes of a full PackedBatch). The first thing the jitted train step
does is materialize the PackedBatch with device gathers — pure
HBM-bandwidth work that XLA fuses with the model's own input reads. Host
cost per epoch collapses to index arithmetic (`arena.pack_epoch_indices`).

Contrast with the reference, which re-does per-batch host collation +
feature lookup inside its train loop every epoch
(/root/reference/pert_gnn.py:219-231, 40-67).

`materialize_device` must stay the exact twin of `arena.materialize_host`
(tests/test_batching.py device/host parity).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x keeps it in jax.experimental
    from jax.experimental.shard_map import shard_map as _shard_map

from pertgnn_tpu.batching.arena import FeatureArena, IndexBatch, MixtureArena
from pertgnn_tpu.batching.pack import PackedBatch


class DeviceArenas(NamedTuple):
    """Chip-resident copies of the mixture + (per-split) feature arenas.
    Sentinel conventions are inherited from the host arenas: the last
    node/edge/feature row is the pad row. The per-entry start/count tables
    let the device expand O(graphs) CompactBatch recipes into full gather
    index arrays (expand_compact)."""

    ms_id: jnp.ndarray
    node_depth: jnp.ndarray
    pattern_prob: jnp.ndarray
    pattern_size: jnp.ndarray
    senders: jnp.ndarray
    receivers: jnp.ndarray
    edge_iface: jnp.ndarray
    edge_rpctype: jnp.ndarray
    edge_duration: jnp.ndarray
    feat_x: jnp.ndarray
    node_start: jnp.ndarray   # (num_entries,) int32
    node_count: jnp.ndarray   # (num_entries,) int32
    edge_start: jnp.ndarray
    edge_count: jnp.ndarray

    @property
    def node_sentinel(self) -> int:
        return self.ms_id.shape[0] - 1

    @property
    def edge_sentinel(self) -> int:
        return self.senders.shape[0] - 1

    @property
    def feat_sentinel(self) -> int:
        return self.feat_x.shape[0] - 1


def arena_nbytes(arena: MixtureArena, feats: FeatureArena) -> int:
    """Bytes of HBM the chip-resident arenas will occupy (per device — they
    are replicated, not sharded, over a mesh). Drives the
    `arena_hbm_budget_gb` fallback in fit(): the feature arena scales with
    unique (entry, ts_bucket) pairs x mixture width and is unbounded by the
    batch shape."""
    node_e = (arena.ms_id.nbytes + arena.node_depth.nbytes
              + arena.pattern_prob.nbytes + arena.pattern_size.nbytes)
    edge_e = (arena.senders.nbytes + arena.receivers.nbytes
              + arena.edge_iface.nbytes + arena.edge_rpctype.nbytes
              + arena.edge_duration.nbytes)
    return node_e + edge_e + feats.x.nbytes


def build_device_arenas(arena: MixtureArena, feats: FeatureArena,
                        sharding=None) -> DeviceArenas:
    """Place the arenas on device (replicated under `sharding` on a mesh).

    On multi-host meshes every process holds the identical host arenas, so
    the replicated global arrays are assembled with
    make_array_from_process_local_data (device_put cannot target
    non-addressable devices)."""
    if sharding is None:
        put = jax.device_put
    elif jax.process_count() > 1:
        from pertgnn_tpu.parallel.multihost import put_replicated
        put = lambda a: put_replicated(a, sharding)
    else:
        put = lambda a: jax.device_put(a, sharding)
    return DeviceArenas(
        ms_id=put(arena.ms_id), node_depth=put(arena.node_depth),
        pattern_prob=put(arena.pattern_prob),
        pattern_size=put(arena.pattern_size),
        senders=put(arena.senders), receivers=put(arena.receivers),
        edge_iface=put(arena.edge_iface),
        edge_rpctype=put(arena.edge_rpctype),
        edge_duration=put(arena.edge_duration),
        feat_x=put(feats.x),
        node_start=put(arena.node_start.astype(np.int32)),
        node_count=put(arena.node_count.astype(np.int32)),
        edge_start=put(arena.edge_start.astype(np.int32)),
        edge_count=put(arena.edge_count.astype(np.int32)))


def materialize_device(dev: DeviceArenas, idx: IndexBatch) -> PackedBatch:
    """Gather a full PackedBatch out of HBM-resident arenas (jit-traceable;
    twin of arena.materialize_host)."""
    node_mask = idx.src_node != dev.node_sentinel
    edge_mask = idx.src_edge != dev.edge_sentinel
    return PackedBatch(
        x=dev.feat_x[idx.src_feat],
        ms_id=dev.ms_id[idx.src_node],
        node_depth=dev.node_depth[idx.src_node],
        node_graph=idx.node_graph,
        node_mask=node_mask,
        pattern_prob=dev.pattern_prob[idx.src_node],
        pattern_size=dev.pattern_size[idx.src_node],
        senders=dev.senders[idx.src_edge] + idx.edge_node_off,
        receivers=dev.receivers[idx.src_edge] + idx.edge_node_off,
        edge_iface=dev.edge_iface[idx.src_edge],
        edge_rpctype=dev.edge_rpctype[idx.src_edge],
        edge_duration=dev.edge_duration[idx.src_edge],
        edge_mask=edge_mask,
        entry_id=idx.entry_id, y=idx.y, graph_mask=idx.graph_mask)


def expand_compact(dev: DeviceArenas, cb, max_nodes: int,
                   max_edges: int) -> IndexBatch:
    """Expand an O(graphs) CompactBatch recipe into the full per-node/edge
    gather index arrays ON DEVICE (jit-traceable; dense XLA: gather +
    cumsum + searchsorted + iota arithmetic).

    Produces exactly what `arena.pack_epoch_indices` would have built on
    the host for the same greedy assignment (parity-tested), so
    `materialize_device(dev, expand_compact(...))` is a drop-in for the
    IndexBatch feed with ~30x less host->device traffic."""
    G = cb.entry_id.shape[0]
    entry = cb.entry_id.astype(jnp.int32)
    cnt_n = jnp.where(cb.graph_mask, dev.node_count[entry], 0)
    cnt_e = jnp.where(cb.graph_mask, dev.edge_count[entry], 0)
    start_n = jnp.cumsum(cnt_n) - cnt_n       # exclusive per-slot starts
    start_e = jnp.cumsum(cnt_e) - cnt_e
    total_n = start_n[-1] + cnt_n[-1]
    total_e = start_e[-1] + cnt_e[-1]

    def per_axis(start, total, size):
        ids = jnp.arange(size, dtype=jnp.int32)
        # slot containing position i: last slot whose start <= i (empty
        # slots share the next real slot's start; side="right" skips them)
        g = jnp.clip(jnp.searchsorted(start, ids, side="right") - 1, 0,
                     G - 1).astype(jnp.int32)
        within = ids - start[g]
        valid = ids < total
        return g, within, valid

    g_n, within_n, valid_n = per_axis(start_n, total_n, max_nodes)
    g_e, within_e, valid_e = per_axis(start_e, total_e, max_edges)
    src_node = jnp.where(valid_n, dev.node_start[entry[g_n]] + within_n,
                         dev.node_sentinel).astype(jnp.int32)
    src_feat = jnp.where(valid_n,
                         cb.feat_start.astype(jnp.int32)[g_n] + within_n,
                         dev.feat_sentinel).astype(jnp.int32)
    node_graph = jnp.where(valid_n, g_n, G - 1).astype(jnp.int32)
    src_edge = jnp.where(valid_e, dev.edge_start[entry[g_e]] + within_e,
                         dev.edge_sentinel).astype(jnp.int32)
    edge_node_off = jnp.where(valid_e, start_n[g_e], 0).astype(jnp.int32)
    return IndexBatch(src_node=src_node, src_feat=src_feat,
                      node_graph=node_graph, src_edge=src_edge,
                      edge_node_off=edge_node_off,
                      entry_id=entry, y=cb.y,
                      graph_mask=cb.graph_mask)


def materialize_compact(dev: DeviceArenas, cb, max_nodes: int,
                        max_edges: int) -> PackedBatch:
    """CompactBatch -> PackedBatch entirely on device."""
    return materialize_device(dev, expand_compact(dev, cb, max_nodes,
                                                  max_edges))


def expand_compact_sharded(dev: DeviceArenas, cb, max_nodes: int,
                           max_edges: int, mesh, axis: str):
    """SPMD expansion of a GLOBAL compact recipe (graph dim sharded over
    `axis`): each device expands ITS (G,)-block locally (shard_map) and
    shifts node_graph / edge_node_off by its shard's global offsets
    (axis_index), reproducing exactly what `stack_index_batches` builds on
    the host for the same per-shard recipes (parity-tested). `max_nodes`/
    `max_edges` are PER-SHARD budgets; the arenas are mesh-replicated."""
    from jax.sharding import PartitionSpec as P

    def local(dev_l: DeviceArenas, cb_l) -> IndexBatch:
        idx = expand_compact(dev_l, cb_l, max_nodes, max_edges)
        d = jax.lax.axis_index(axis)
        g = cb_l.entry_id.shape[0]
        return idx._replace(
            node_graph=idx.node_graph + d * g,
            edge_node_off=idx.edge_node_off + d * max_nodes)

    dev_specs = type(dev)(*([P()] * len(dev)))
    cb_specs = jax.tree.map(lambda _: P(axis), cb)
    out_specs = IndexBatch(*([P(axis)] * len(IndexBatch._fields)))
    return _shard_map(local, mesh=mesh,
                         in_specs=(dev_specs, cb_specs),
                         out_specs=out_specs)(dev, cb)


def materialize_compact_sharded(dev: DeviceArenas, cb, max_nodes: int,
                                max_edges: int, mesh,
                                axis: str) -> PackedBatch:
    """Global CompactBatch -> global sharded PackedBatch on the mesh."""
    return materialize_device(dev, expand_compact_sharded(
        dev, cb, max_nodes, max_edges, mesh, axis))


def zero_masked_idx(idx: IndexBatch, arena: MixtureArena,
                    feats: FeatureArena) -> IndexBatch:
    """Inert tail filler for scan chunks in index space: every position the
    sentinel, every graph masked — materializes to a pure-padding batch
    (the IndexBatch analog of pack.zero_masked)."""
    return IndexBatch(
        src_node=np.full_like(idx.src_node, arena.node_sentinel),
        src_feat=np.full_like(idx.src_feat, feats.sentinel),
        node_graph=np.full_like(idx.node_graph, idx.num_graphs - 1),
        src_edge=np.full_like(idx.src_edge, arena.edge_sentinel),
        edge_node_off=np.zeros_like(idx.edge_node_off),
        entry_id=np.zeros_like(idx.entry_id),
        y=np.zeros_like(idx.y),
        graph_mask=np.zeros_like(idx.graph_mask))
