"""Vectorized epoch packing over pre-built arenas.

`pack.pack_examples` is the readable reference packer: a per-example Python
loop that re-gathers node features and copies array slices for every example
of every epoch. That loop caps the host at ~9k traces/s while the chip
consumes millions of graphs/s — on fresh data the device starves (the
reference has the same disease much worse: its per-batch host loop rebuilds
mixture probabilities every step, /root/reference/pert_gnn.py:219-231).

This module removes the per-example work from the epoch path:

- `MixtureArena` — every entry's mixture arrays concatenated ONCE into flat
  node/edge arenas with per-entry (start, count) index tables. Built at
  dataset construction; epoch packing only gathers from it.
- `FeatureArena` — node features depend only on (ts_bucket, entry's ms ids),
  so they are gathered ONCE per unique (entry, ts_bucket) pair of a split
  (one vectorized ResourceLookup call for all pairs together) and re-used by
  every epoch.
- `pack_epoch` — packs a whole epoch (any example order) into fixed-shape
  batches using O(#vectorized-ops) numpy: a per-BATCH searchsorted pass
  assigns examples to batches (the same greedy rule as `pack_examples`,
  bitwise identical output — see tests/test_batching.py fast/slow parity),
  then ragged-arange gathers scatter nodes/edges/graphs of ALL examples at
  once, pre-sorted per mixture so no epoch-path sort remains.

Memory is bounded by packing in slabs of `slab_batches` batches.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, NamedTuple

import numpy as np

from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import Mixture
from pertgnn_tpu.batching.pack import BatchBudget, PackedBatch


@dataclasses.dataclass(frozen=True)
class MixtureArena:
    """All entries' mixtures concatenated into flat arenas.

    Per-entry views are `node arena[node_start[e] : node_start[e] +
    node_count[e]]` (same for edges); senders/receivers stay entry-local
    (0-based within the mixture) and are offset at pack time.
    """

    node_start: np.ndarray    # (num_entries,) int64, -1 for absent entries
    node_count: np.ndarray    # (num_entries,) int64
    edge_start: np.ndarray
    edge_count: np.ndarray
    # Node/edge arrays carry ONE extra sentinel row at the end (the pad
    # row: ms 0 / depth 0 / prob 0 / size 1; sender/receiver 0 / attrs 0),
    # so index-batch gathers need no masking: pad positions simply index
    # the sentinel. `node_sentinel`/`edge_sentinel` are its index.
    ms_id: np.ndarray         # (total_nodes+1,) int32
    node_depth: np.ndarray    # (total_nodes+1,) float32
    pattern_prob: np.ndarray  # (total_nodes+1,) float32
    pattern_size: np.ndarray  # (total_nodes+1,) float32
    feature_mask: np.ndarray  # (total_nodes+1,) bool — featurize gate
    senders: np.ndarray       # (total_edges+1,) int32 — entry-local
    receivers: np.ndarray     # (total_edges+1,) int32 — entry-local
    edge_iface: np.ndarray    # (total_edges+1,) int32
    edge_rpctype: np.ndarray  # (total_edges+1,) int32
    edge_duration: np.ndarray # (total_edges+1,) float32

    @property
    def node_sentinel(self) -> int:
        return len(self.ms_id) - 1

    @property
    def edge_sentinel(self) -> int:
        return len(self.senders) - 1


def build_mixture_arena(mixtures: dict[int, Mixture]) -> MixtureArena:
    num_entries = 1 + max(mixtures.keys())
    node_start = np.full(num_entries, -1, dtype=np.int64)
    node_count = np.zeros(num_entries, dtype=np.int64)
    edge_start = np.full(num_entries, -1, dtype=np.int64)
    edge_count = np.zeros(num_entries, dtype=np.int64)
    entries = sorted(mixtures.keys())
    n = e = 0
    for ent in entries:
        m = mixtures[ent]
        node_start[ent], node_count[ent] = n, m.num_nodes
        edge_start[ent], edge_count[ent] = e, m.num_edges
        n += m.num_nodes
        e += m.num_edges
    mixes = [mixtures[ent] for ent in entries]
    # Pre-sort each mixture's edges stably by local receiver. A packed
    # batch's examples occupy disjoint increasing node ranges, so the
    # batch-level receiver sort (pack.receiver_sort_edges) decomposes into
    # exactly this per-example order — storing it here removes any sorting
    # from the epoch path.
    eorders = [np.argsort(m.receivers, kind="stable") for m in mixes]

    def cat_n(f, pad):
        parts = [getattr(m, f) for m in mixes]
        tail = np.array([pad], dtype=parts[0].dtype if parts else np.float32)
        return np.concatenate(parts + [tail])

    def cat_e(f, pad):
        parts = [getattr(m, f)[o] for m, o in zip(mixes, eorders)]
        tail = np.array([pad], dtype=parts[0].dtype if parts else np.float32)
        return np.concatenate(parts + [tail])

    return MixtureArena(
        node_start=node_start, node_count=node_count,
        edge_start=edge_start, edge_count=edge_count,
        ms_id=cat_n("ms_id", 0), node_depth=cat_n("node_depth", 0.0),
        pattern_prob=cat_n("pattern_prob", 0.0),
        pattern_size=cat_n("pattern_size", 1.0),
        feature_mask=cat_n("feature_mask", False),
        senders=cat_e("senders", 0), receivers=cat_e("receivers", 0),
        edge_iface=cat_e("edge_iface", 0),
        edge_rpctype=cat_e("edge_rpctype", 0),
        edge_duration=cat_e("edge_duration", 0.0))


def _ragged_arange(counts: np.ndarray) -> np.ndarray:
    """[0..c0), [0..c1), ... concatenated: arange per count, flattened."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    excl = np.concatenate([[0], np.cumsum(counts)[:-1]])
    return np.arange(total, dtype=np.int64) - np.repeat(excl, counts)


@dataclasses.dataclass(frozen=True)
class FeatureArena:
    """Pre-gathered node features per unique (entry, ts_bucket) pair of a
    split. `pair_of_example[i]` indexes `feat_start`; feature rows for the
    example are `x[feat_start[p] : feat_start[p] + node_count[entry]]` and
    align with the entry's mixture-arena node order. The last row of `x` is
    an all-zero sentinel (index `sentinel`) for pad positions."""

    pair_of_example: np.ndarray  # (num_examples,) int64
    feat_start: np.ndarray       # (num_pairs,) int64
    x: np.ndarray                # (total_rows+1, F) float32

    @property
    def sentinel(self) -> int:
        return len(self.x) - 1


def build_feature_arena(arena: MixtureArena, entry_ids: np.ndarray,
                        ts_buckets: np.ndarray, lookup: ResourceLookup,
                        node_depth_in_x: bool = False) -> FeatureArena:
    pairs = np.stack([entry_ids.astype(np.int64),
                      ts_buckets.astype(np.int64)], axis=1)
    uniq, pair_of_example = np.unique(pairs, axis=0, return_inverse=True)
    u_entry, u_bucket = uniq[:, 0], uniq[:, 1]
    counts = arena.node_count[u_entry]
    feat_start = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int64)
    ragged = _ragged_arange(counts)
    src = np.repeat(arena.node_start[u_entry], counts) + ragged
    ms = arena.ms_id[src].astype(np.int64)
    buckets = np.repeat(u_bucket, counts)
    x = lookup(buckets, ms, feature_mask=arena.feature_mask[src])
    if node_depth_in_x:
        x = np.concatenate([x, arena.node_depth[src][:, None]], axis=1)
    x = np.concatenate([x, np.zeros((1, x.shape[1]), np.float32)])
    return FeatureArena(pair_of_example=pair_of_example.astype(np.int64),
                        feat_start=feat_start, x=x)


def assign_batches(node_counts: np.ndarray, edge_counts: np.ndarray,
                   budget: BatchBudget
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """The greedy packing rule of `pack_examples`, sizes only.

    Returns per-example (batch_idx, graph_slot, node_offset, edge_offset).

    The greedy rule packs each batch with the MAXIMAL prefix of remaining
    examples that fits all three budgets, so each batch boundary is a
    searchsorted into the size cumsums: the Python loop is per-BATCH
    (~examples/batch_size iterations), not per-example, and the
    per-example arrays expand vectorized. Exact scalar-greedy equivalence
    is pinned by tests/test_batching.py."""
    n_ex = len(node_counts)
    if n_ex == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z.copy(), z.copy(), z.copy()
    node_counts = np.asarray(node_counts, dtype=np.int64)
    edge_counts = np.asarray(edge_counts, dtype=np.int64)
    bad = np.where((node_counts > budget.max_nodes)
                   | (edge_counts > budget.max_edges))[0]
    if bad.size:
        i = int(bad[0])
        raise ValueError(
            f"example {i} mixture ({int(node_counts[i])} nodes, "
            f"{int(edge_counts[i])} edges) exceeds budget {budget}")
    cn = np.concatenate([[0], np.cumsum(node_counts)])
    ce = np.concatenate([[0], np.cumsum(edge_counts)])
    starts = []
    i = 0
    while i < n_ex:
        starts.append(i)
        # largest j with cumsum window <= budget on every axis
        jn = int(np.searchsorted(cn, cn[i] + budget.max_nodes, "right")) - 1
        je = int(np.searchsorted(ce, ce[i] + budget.max_edges, "right")) - 1
        i = min(i + budget.max_graphs, jn, je)
    starts_a = np.asarray(starts, dtype=np.int64)
    sizes = np.diff(np.concatenate([starts_a, [n_ex]]))
    # Padded-slot waste of this assignment — previously computed here
    # (the cumsums know it exactly) and thrown away; one event per epoch
    # pack on the process-wide bus (no-op when telemetry is off). The
    # aggregate over the epoch equals pad_waste of the mean per-batch
    # fill (n_ex > 0 here, so there is at least one batch).
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.batching.pack import pad_waste
    bus = telemetry.get_bus()
    if bus.enabled:
        n_batches = len(starts_a)
        bus.gauge("pack.pad_waste",
                  pad_waste(budget, float(cn[-1]) / n_batches,
                            float(ce[-1]) / n_batches),
                  batches=n_batches, examples=n_ex,
                  max_nodes=budget.max_nodes, max_edges=budget.max_edges)
    batch_idx = np.repeat(np.arange(len(starts_a), dtype=np.int64), sizes)
    start_of_ex = np.repeat(starts_a, sizes)
    idx = np.arange(n_ex, dtype=np.int64)
    graph_slot = idx - start_of_ex
    node_off = cn[idx] - cn[start_of_ex]
    edge_off = ce[idx] - ce[start_of_ex]
    return batch_idx, graph_slot, node_off, edge_off


class CompactBatch(NamedTuple):
    """The O(graphs) gather recipe — what the host actually needs to say
    about a batch. Everything per-NODE/EDGE that `IndexBatch` spells out
    (src_node/src_feat/src_edge/offsets) is derivable on DEVICE from the
    entry ids alone: per-entry node/edge counts live in the chip-resident
    arenas, so cumsum + searchsorted expand these G-sized arrays into the
    full N/E-sized index arrays inside the jitted step
    (materialize.expand_compact). Per-step transfer drops from O(N+E) to
    O(G) int32s (~30x) and per-epoch host packing collapses to
    assign_batches + G-sized scatters (pack_epoch_compact)."""

    entry_id: np.ndarray    # (G,) int32; pad slots 0, masked
    feat_start: np.ndarray  # (G,) int32 row into FeatureArena.x; pad 0
    y: np.ndarray           # (G,) float32
    graph_mask: np.ndarray  # (G,) bool

    @property
    def num_graphs(self) -> int:
        return len(self.entry_id)


def zero_masked_compact(cb: CompactBatch) -> CompactBatch:
    """Inert all-padding compact recipe (scan-chunk tail filler): masks
    False -> zero node/edge counts -> expands to a pure-padding batch."""
    return CompactBatch(entry_id=np.zeros_like(cb.entry_id),
                        feat_start=np.zeros_like(cb.feat_start),
                        y=np.zeros_like(cb.y),
                        graph_mask=np.zeros_like(cb.graph_mask))


def pack_epoch_compact(
    arena: MixtureArena,
    feats: FeatureArena,
    entry_ids: np.ndarray,
    ys: np.ndarray,
    budget: BatchBudget,
    order: np.ndarray | None = None,
) -> Iterator[CompactBatch]:
    """Pack an epoch into O(graphs) compact recipes: the same greedy
    assignment as `pack_epoch_indices` but emitting only the per-graph
    arrays — the whole epoch's host work is a few G-sized scatters."""
    if order is None:
        order = np.arange(len(entry_ids))
    ex_entry = entry_ids[order].astype(np.int64)
    ex_y = ys[order].astype(np.float32)
    ex_feat = feats.feat_start[feats.pair_of_example[order]]
    counts_n = arena.node_count[ex_entry]
    counts_e = arena.edge_count[ex_entry]
    batch_idx, graph_slot, _, _ = assign_batches(counts_n, counts_e, budget)
    num_batches = int(batch_idx[-1]) + 1 if len(batch_idx) else 0
    G = budget.max_graphs + 1  # +1 reserved pad graph slot
    entry_arr = np.zeros((num_batches, G), dtype=np.int32)
    feat_arr = np.zeros((num_batches, G), dtype=np.int32)
    y_arr = np.zeros((num_batches, G), dtype=np.float32)
    mask_arr = np.zeros((num_batches, G), dtype=bool)
    entry_arr[batch_idx, graph_slot] = ex_entry.astype(np.int32)
    feat_arr[batch_idx, graph_slot] = ex_feat.astype(np.int32)
    y_arr[batch_idx, graph_slot] = ex_y
    mask_arr[batch_idx, graph_slot] = True
    for b in range(num_batches):
        yield CompactBatch(entry_id=entry_arr[b], feat_start=feat_arr[b],
                           y=y_arr[b], graph_mask=mask_arr[b])


class IndexBatch(NamedTuple):
    """The per-batch gather recipe — everything the device needs to
    materialize one PackedBatch from resident arenas.

    Positions are already in the PackedBatch layout: real nodes/edges
    occupy a prefix (edges receiver-sorted — arena pre-sort + disjoint
    per-example node ranges make the scattered order sorted by
    construction), pads the tail. Pad positions hold the arena sentinel
    index, so gathers need no masking; masks are recovered on device by
    comparing against the sentinel.
    """

    src_node: np.ndarray       # (N,) int32 into node arenas; pad: sentinel
    src_feat: np.ndarray       # (N,) int32 into FeatureArena.x; pad: sentinel
    node_graph: np.ndarray     # (N,) int32 graph slot; pad: G-1
    src_edge: np.ndarray       # (E,) int32 into edge arenas; pad: sentinel
    edge_node_off: np.ndarray  # (E,) int32 batch node offset; pad: 0
    entry_id: np.ndarray       # (G,) int32
    y: np.ndarray              # (G,) float32
    graph_mask: np.ndarray     # (G,) bool

    @property
    def num_graphs(self) -> int:
        return len(self.entry_id)


def pack_epoch_indices(
    arena: MixtureArena,
    feats: FeatureArena,
    entry_ids: np.ndarray,
    ys: np.ndarray,
    budget: BatchBudget,
    order: np.ndarray | None = None,
    slab_batches: int = 128,
) -> Iterator[IndexBatch]:
    """Pack an epoch into IndexBatches with whole-slab vectorized index
    arithmetic — no per-example Python anywhere (assign_batches loops
    per batch)."""
    if order is None:
        order = np.arange(len(entry_ids))
    ex_entry = entry_ids[order].astype(np.int64)
    ex_y = ys[order].astype(np.float32)
    ex_pair = feats.pair_of_example[order]
    counts_n = arena.node_count[ex_entry]
    counts_e = arena.edge_count[ex_entry]
    batch_idx, graph_slot, node_off, edge_off = assign_batches(
        counts_n, counts_e, budget)
    num_batches = int(batch_idx[-1]) + 1 if len(batch_idx) else 0
    G = budget.max_graphs + 1  # +1 reserved pad graph slot

    for slab0 in range(0, num_batches, slab_batches):
        slab1 = min(slab0 + slab_batches, num_batches)
        B = slab1 - slab0
        sel = (batch_idx >= slab0) & (batch_idx < slab1)
        s_entry = ex_entry[sel]
        s_cn, s_ce = counts_n[sel], counts_e[sel]
        s_bi = batch_idx[sel] - slab0
        s_gs, s_no, s_eo = graph_slot[sel], node_off[sel], edge_off[sel]

        rag_n = _ragged_arange(s_cn)
        dst_n = np.repeat(s_bi * budget.max_nodes + s_no, s_cn) + rag_n
        src_node = np.full(B * budget.max_nodes, arena.node_sentinel,
                           dtype=np.int32)
        src_feat = np.full(B * budget.max_nodes, feats.sentinel,
                           dtype=np.int32)
        node_graph = np.full(B * budget.max_nodes, G - 1, dtype=np.int32)
        src_node[dst_n] = np.repeat(arena.node_start[s_entry], s_cn) + rag_n
        src_feat[dst_n] = np.repeat(feats.feat_start[ex_pair[sel]],
                                    s_cn) + rag_n
        node_graph[dst_n] = np.repeat(s_gs, s_cn).astype(np.int32)

        rag_e = _ragged_arange(s_ce)
        dst_e = np.repeat(s_bi * budget.max_edges + s_eo, s_ce) + rag_e
        src_edge = np.full(B * budget.max_edges, arena.edge_sentinel,
                           dtype=np.int32)
        edge_node_off = np.zeros(B * budget.max_edges, dtype=np.int32)
        src_edge[dst_e] = np.repeat(arena.edge_start[s_entry], s_ce) + rag_e
        edge_node_off[dst_e] = np.repeat(s_no, s_ce).astype(np.int32)

        entry_arr = np.zeros(B * G, dtype=np.int32)
        y_arr = np.zeros(B * G, dtype=np.float32)
        graph_mask = np.zeros(B * G, dtype=bool)
        dst_g = s_bi * G + s_gs
        entry_arr[dst_g] = s_entry.astype(np.int32)
        y_arr[dst_g] = ex_y[sel]
        graph_mask[dst_g] = True

        def r2(a, per):  # (B*per,) -> (B, per)
            return a.reshape(B, per)

        slab = IndexBatch(
            src_node=r2(src_node, budget.max_nodes),
            src_feat=r2(src_feat, budget.max_nodes),
            node_graph=r2(node_graph, budget.max_nodes),
            src_edge=r2(src_edge, budget.max_edges),
            edge_node_off=r2(edge_node_off, budget.max_edges),
            entry_id=r2(entry_arr, G), y=r2(y_arr, G),
            graph_mask=r2(graph_mask, G))
        for i in range(B):
            yield IndexBatch(*(a[i] for a in slab))


def materialize_host(arena: MixtureArena, feats: FeatureArena,
                     idx: IndexBatch) -> PackedBatch:
    """Numpy twin of `materialize.materialize_device` — turns a gather
    recipe into a full PackedBatch on the host (used off-TPU and as the
    parity oracle for the device path)."""
    node_mask = idx.src_node != arena.node_sentinel
    edge_mask = idx.src_edge != arena.edge_sentinel
    return PackedBatch(
        x=feats.x[idx.src_feat],
        ms_id=arena.ms_id[idx.src_node],
        node_depth=arena.node_depth[idx.src_node],
        node_graph=idx.node_graph,
        node_mask=node_mask,
        pattern_prob=arena.pattern_prob[idx.src_node],
        pattern_size=arena.pattern_size[idx.src_node],
        senders=arena.senders[idx.src_edge] + idx.edge_node_off,
        receivers=arena.receivers[idx.src_edge] + idx.edge_node_off,
        edge_iface=arena.edge_iface[idx.src_edge],
        edge_rpctype=arena.edge_rpctype[idx.src_edge],
        edge_duration=arena.edge_duration[idx.src_edge],
        edge_mask=edge_mask,
        entry_id=idx.entry_id, y=idx.y, graph_mask=idx.graph_mask)


def pack_epoch(
    arena: MixtureArena,
    feats: FeatureArena,
    entry_ids: np.ndarray,
    ts_buckets: np.ndarray,   # kept for signature symmetry; features come
    ys: np.ndarray,           # pre-gathered via `feats`
    budget: BatchBudget,
    order: np.ndarray | None = None,
    slab_batches: int = 128,
) -> Iterator[PackedBatch]:
    """Yield the same PackedBatch stream `pack_examples` would produce for
    `entry_ids[order]`: vectorized index build + host materialization."""
    del ts_buckets  # folded into `feats` at arena-build time
    for idx in pack_epoch_indices(arena, feats, entry_ids, ys, budget,
                                  order=order, slab_batches=slab_batches):
        yield materialize_host(arena, feats, idx)
