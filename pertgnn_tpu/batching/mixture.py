"""Per-entry topology-mixture collation, done ONCE offline.

The reference rebuilds each trace's mixture graph lazily with a stack of
lru_caches (/root/reference/pert_gnn.py:70-173) and re-derives per-node
pattern probabilities on the host INSIDE the train loop for every batch of
every epoch (pert_gnn.py:220-230). Both collapse into this module: for each
entry, the graphs of all its runtime patterns are concatenated
block-diagonally once — edge indices offset by the node-count cumsum
(pert_gnn.py:107-119), per-node pattern probability and pattern size repeated
per node (pert_gnn.py:85-94, 122-131) — into flat numpy arrays that batching
then slices with zero per-trace Python work.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pertgnn_tpu.graphs.construct import GraphSpec


@dataclasses.dataclass
class Mixture:
    """All runtime patterns of one entry, block-diagonally concatenated."""

    entry_id: int
    senders: np.ndarray        # (E,) int32
    receivers: np.ndarray      # (E,) int32
    edge_iface: np.ndarray     # (E,) int32
    edge_rpctype: np.ndarray   # (E,) int32
    edge_duration: np.ndarray  # (E,) float32 — span |rt| ms (0 for pert)
    ms_id: np.ndarray          # (N,) int32
    node_depth: np.ndarray     # (N,) float32
    pattern_prob: np.ndarray   # (N,) float32 — this node's pattern's weight
    pattern_size: np.ndarray   # (N,) float32 — this node's pattern's #nodes
    # (N,) bool — node receives resource features. The reference's live
    # get_x assigns features only to the LAST stage-copy of each
    # microservice within a graph (pert_gnn.py:56 dict-comprehension
    # overwrite; PARITY.md "Oracle independence"); span graphs have
    # unique ms per node, so there it is all-True either way.
    feature_mask: np.ndarray
    num_nodes: int
    num_edges: int


def _last_occurrence_mask(ms_id: np.ndarray) -> np.ndarray:
    """True at the LAST occurrence of each value (the reference's live
    get_x feature-assignment rule, pert_gnn.py:53-66)."""
    mask = np.zeros(len(ms_id), dtype=bool)
    last = list({int(v): i for i, v in enumerate(ms_id)}.values())
    mask[last] = True
    return mask


def build_mixtures(
    runtime_graphs: dict[int, GraphSpec],
    entry2runtimes: dict[int, tuple[np.ndarray, np.ndarray]],
    feature_all_stage_copies: bool = False,
) -> dict[int, Mixture]:
    out: dict[int, Mixture] = {}
    for entry_id, (rt_ids, probs) in entry2runtimes.items():
        graphs = [runtime_graphs[int(rt)] for rt in rt_ids]
        sizes = np.array([g.num_nodes for g in graphs], dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
        senders = np.concatenate(
            [g.senders + off for g, off in zip(graphs, offsets)])
        receivers = np.concatenate(
            [g.receivers + off for g, off in zip(graphs, offsets)])
        edge_attr = np.concatenate([g.edge_attr[:, :2] for g in graphs])
        edge_duration = np.concatenate(
            [g.edge_durations if g.edge_durations is not None
             else np.zeros(g.num_edges, np.float32) for g in graphs])
        ms_id = np.concatenate([g.ms_id for g in graphs])
        if feature_all_stage_copies:
            feature_mask = np.ones(len(ms_id), dtype=bool)
        else:
            feature_mask = np.concatenate(
                [_last_occurrence_mask(g.ms_id) for g in graphs])
        node_depth = np.concatenate([g.node_depth for g in graphs])
        pattern_prob = np.repeat(probs.astype(np.float32), sizes)
        pattern_size = np.repeat(sizes.astype(np.float32), sizes)
        out[int(entry_id)] = Mixture(
            entry_id=int(entry_id),
            senders=senders.astype(np.int32),
            receivers=receivers.astype(np.int32),
            edge_iface=edge_attr[:, 0].astype(np.int32),
            edge_rpctype=edge_attr[:, 1].astype(np.int32),
            edge_duration=edge_duration.astype(np.float32),
            ms_id=ms_id.astype(np.int32),
            node_depth=node_depth.astype(np.float32),
            pattern_prob=pattern_prob,
            pattern_size=pattern_size,
            feature_mask=feature_mask,
            num_nodes=int(sizes.sum()),
            num_edges=len(senders),
        )
    return out
