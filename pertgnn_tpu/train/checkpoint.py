"""Orbax checkpointing: restartable training the reference lacks.

The reference checkpoints DATA only (idempotent artifact caches,
preprocess.py:23-29, 192-199; SURVEY.md §5.4) and loses all training progress
on a crash — no state_dict save anywhere. Here the full TrainState (params,
batch_stats, optimizer state, step) plus the epoch counter is saved
asynchronously every epoch and restored on restart.
"""

from __future__ import annotations

import logging
import os
import jax
import numpy as np
import orbax.checkpoint as ocp

from pertgnn_tpu import telemetry
from pertgnn_tpu.train.loop import TrainState

log = logging.getLogger(__name__)


def _rebuffer(state):
    """Copy every restored leaf into an XLA-owned buffer (an eager
    elementwise select; dtype- and sharding-preserving, so it is safe
    under meshes and multihost).

    WHY: orbax-restored arrays can be zero-copy views over the restore
    read buffers, and XLA executables DESERIALIZED FROM THE PERSISTENT
    COMPILATION CACHE mishandle buffer donation of such externally
    backed inputs — the triple (restored state) + (cache-deserialized
    executable) + (donate_argnums) intermittently corrupts the heap and
    SIGSEGVs on this jax/jaxlib (reproduced minimally WITHOUT any of
    this repo's code: plain jit + warm jax_compilation_cache_dir +
    StandardRestore + donation; any two of the three are fine).  Found
    by benchmarks/stream_bench.py's warm-restart phase — which is
    exactly resume-from-checkpoint with a warm compile cache, the
    combination every continual-training round hits.  Cost: one
    elementwise pass over the state at restore time (transiently ~2x
    state bytes until the old tree drops)."""
    import jax.numpy as jnp

    if jax.process_count() > 1:
        # multi-process restore: eager global ops would have to be
        # issued collectively and the copy's sharding identity must
        # survive exactly (tests/multihost_worker.py pins it) — skip
        # the workaround there; the crash triple needs the persistent
        # cache, which multihost training runs configure per-host where
        # the TPU pjrt serialization path (not stablehlo replay) serves
        # warm starts anyway
        return state

    def leaf(x):
        if isinstance(x, jax.Array):
            # device_put pins the ORIGINAL sharding object on the copy
            # (a no-op when it already matches) so restore is
            # bit-AND-sharding-identical to pre-workaround behavior
            return jax.device_put(jnp.where(True, x, x), x.sharding)
        return x

    return jax.tree.map(leaf, state)


class CheckpointManager:
    """Thin orbax wrapper keyed by epoch."""

    def __init__(self, directory: str, keep: int = 3, every: int = 1):
        self.every = max(1, every)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep, enable_async_checkpointing=True)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory), options=options)

    def save(self, epoch: int, state: TrainState, metrics: dict | None = None
             ) -> None:
        if (epoch + 1) % self.every:
            return
        # state is passed as-is: orbax handles (multi-host) sharded
        # jax.Arrays natively; a device_get here would break multi-host
        # (no process holds remote shards) and forces a D2H copy.
        # The epoch-metrics item is named "history": orbax >= 0.7 reserves
        # the item name "metrics" for itself and rejects the save.
        # NB the span times the (async) save INITIATION, not the write —
        # the commit itself overlaps training by design (wait() below).
        with telemetry.span("checkpoint.save", epoch=epoch):
            self._mgr.save(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    history=ocp.args.JsonSave(metrics or {}),
                ),
            )
        from pertgnn_tpu.testing import faults
        plan = faults.active()
        if plan is not None and plan.fire("checkpoint.save") == "corrupt":
            # the chaos half of maybe_restore's fallback: commit the
            # step, then garble it on disk as a torn write would
            self._mgr.wait_until_finished()
            faults.corrupt_checkpoint_step(str(self._mgr.directory), epoch)

    def maybe_restore(self, state: TrainState) -> tuple[TrainState, int]:
        """Restore the latest checkpoint if present, directly INTO the
        live state's shardings — no host-numpy round-trip: the restore
        target is the abstract (shape, dtype, sharding) tree, so orbax
        reads each shard where it lives (sharded arrays stay sharded,
        multi-host restores stay distributed).

        Returns (state, start_epoch): start_epoch is one past the saved
        epoch, 0 when nothing is saved.

        A corrupt/partial newest step (torn write, killed mid-commit,
        bad disk) does NOT crash the resume path: it is logged, counted
        (``checkpoint.restore_fallback``), and the next-oldest preserved
        step is tried — losing one checkpoint interval of progress beats
        losing the run. Only when EVERY preserved step fails does the
        last error propagate (resuming from nothing would silently
        discard all progress, which a supervisor restart loop must not
        paper over).
        """
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            return state, 0

        def abstract(leaf):
            if isinstance(leaf, jax.Array):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=leaf.sharding)
            a = np.asarray(leaf)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        target = jax.tree.map(abstract, state)
        last_err: Exception | None = None
        for step in steps:
            try:
                with telemetry.span("checkpoint.restore", epoch=step):
                    restored = self._mgr.restore(
                        step,
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(target)),
                    )
            except Exception as exc:
                last_err = exc
                log.warning(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "falling back to the next-oldest preserved step",
                    step, type(exc).__name__, exc)
                telemetry.get_bus().counter("checkpoint.restore_fallback",
                                            step=step,
                                            error=type(exc).__name__)
                continue
            if step != steps[0]:
                log.warning("restored FALLBACK checkpoint at epoch %d "
                            "(newest step %d was corrupt); one "
                            "checkpoint interval of progress re-trains",
                            step, steps[0])
            else:
                log.info("restored checkpoint at epoch %d", step)
            return _rebuffer(restored["state"]), step + 1
        raise last_err

    def wait(self) -> None:
        with telemetry.span("checkpoint.wait"):
            self._mgr.wait_until_finished()

    # -- config sidecar -------------------------------------------------
    # Checkpoints restore by TREE SHAPE, which is blind to semantics:
    # a label_scale / graph_type / featurization mismatch between train
    # and inference restores cleanly and then silently mis-predicts.
    # CLIs persist the training Config next to the steps and cross-check
    # it at restore (cli/predict_main.py).

    def save_config(self, cfg) -> None:
        import dataclasses

        from pertgnn_tpu.store import durable

        # Only process 0 writes: on a shared checkpoint dir every process
        # races the same file, and two writers using one fixed tmp name
        # can interleave truncate/rename into a torn sidecar (ADVICE r5).
        # durable.write_json is the graftvault protocol — pid-suffixed
        # tmp, fsync, atomic replace, dir fsync, checksummed envelope —
        # so a kill mid-save leaves the previous sidecar intact and a
        # bit-rotted one is detected at load instead of silently
        # cross-checking garbage.
        if jax.process_index() != 0:
            return
        path = os.path.join(str(self._mgr.directory),
                            "train_config.json")
        durable.write_json(path, dataclasses.asdict(cfg),
                           store="checkpoint")

    def load_config_dict(self) -> dict | None:
        import json

        from pertgnn_tpu.store import durable
        from pertgnn_tpu.store.durable import StoreCorruption

        path = os.path.join(str(self._mgr.directory),
                            "train_config.json")
        try:
            return durable.read_json(path, store="checkpoint")
        except StoreCorruption as e:
            if e.reason == "not_envelope":
                # legacy sidecar written before graftvault: plain JSON,
                # no checksum — still cross-checkable
                try:
                    with open(path) as f:
                        return json.load(f)
                except (OSError, ValueError):
                    return None
            log.warning("checkpoint sidecar %s is corrupt (%s) — "
                        "treating as absent", path, e)
            return None
        except (OSError, ValueError):
            return None

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def close(self) -> None:
        self._mgr.close()


# Fields that change model OUTPUTS given the same restored weights.
# dropout/attn_dropout only act in train mode (no rngs at inference);
# init_scheme only shapes the initialization the restore overwrites.
_OUTPUT_IRRELEVANT_MODEL_FIELDS = frozenset(
    {"dropout", "attn_dropout", "init_scheme"})


# Ingest fields that change model INPUTS (and therefore outputs) given
# the same restored weights: the time-bucket keying of resource lookups,
# which aggregations become the 8 numeric features, and which traces
# survive the coverage filter (feature-table contents). The other ingest
# knobs (occurrence threshold, tie-break token) reshape WHICH entries
# exist, which the dataset build surfaces as its own shape errors.
_OUTPUT_RELEVANT_INGEST_FIELDS = (
    "ts_bucket_ms", "resource_aggs", "min_resource_coverage")


def config_mismatches(saved: dict, cfg) -> tuple[list, list]:
    """Compare a sidecar dict against the live Config on the semantics a
    checkpoint restore is blind to: graph_type, label_scale, every
    output-relevant model field, and the output-relevant ingest fields
    (ts_bucket_ms / resource_aggs / min_resource_coverage — these shape
    the feature values fed to the restored weights). Returns
    (mismatches [(key, saved, ours)], unknown [key]) — `unknown` are
    fields the sidecar predates (a newer code version): callers should
    warn, not wall, or every old checkpoint bricks the moment a config
    field is added."""
    import dataclasses

    ours = dataclasses.asdict(cfg)
    mism: list = []
    unknown: list = []

    def norm(v):
        # sequences round-trip through the JSON sidecar as lists; the
        # live Config holds tuples (e.g. resource_aggs) — compare values
        return list(v) if isinstance(v, (list, tuple)) else v

    def probe(key, container, our_val):
        leaf = key.rsplit(".", 1)[-1]
        if leaf not in container:
            unknown.append(key)
        elif norm(container[leaf]) != norm(our_val):
            mism.append((key, container[leaf], our_val))

    probe("graph_type", saved, ours["graph_type"])
    probe("train.label_scale", saved.get("train") or {},
          ours["train"]["label_scale"])
    saved_model = saved.get("model") or {}
    for k, v in ours["model"].items():
        if k not in _OUTPUT_IRRELEVANT_MODEL_FIELDS:
            probe(f"model.{k}", saved_model, v)
    saved_ingest = saved.get("ingest") or {}
    for k in _OUTPUT_RELEVANT_INGEST_FIELDS:
        probe(f"ingest.{k}", saved_ingest, ours["ingest"][k])
    return mism, unknown
