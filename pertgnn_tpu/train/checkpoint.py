"""Orbax checkpointing: restartable training the reference lacks.

The reference checkpoints DATA only (idempotent artifact caches,
preprocess.py:23-29, 192-199; SURVEY.md §5.4) and loses all training progress
on a crash — no state_dict save anywhere. Here the full TrainState (params,
batch_stats, optimizer state, step) plus the epoch counter is saved
asynchronously every epoch and restored on restart.
"""

from __future__ import annotations

import logging
import os
import jax
import numpy as np
import orbax.checkpoint as ocp

from pertgnn_tpu.train.loop import TrainState

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin orbax wrapper keyed by epoch."""

    def __init__(self, directory: str, keep: int = 3, every: int = 1):
        self.every = max(1, every)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep, enable_async_checkpointing=True)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory), options=options)

    def save(self, epoch: int, state: TrainState, metrics: dict | None = None
             ) -> None:
        if (epoch + 1) % self.every:
            return
        # state is passed as-is: orbax handles (multi-host) sharded
        # jax.Arrays natively; a device_get here would break multi-host
        # (no process holds remote shards) and forces a D2H copy
        self._mgr.save(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                metrics=ocp.args.JsonSave(metrics or {}),
            ),
        )

    def maybe_restore(self, state: TrainState) -> tuple[TrainState, int]:
        """Restore the latest checkpoint if present, directly INTO the
        live state's shardings — no host-numpy round-trip: the restore
        target is the abstract (shape, dtype, sharding) tree, so orbax
        reads each shard where it lives (sharded arrays stay sharded,
        multi-host restores stay distributed).

        Returns (state, start_epoch): start_epoch is one past the saved
        epoch, 0 when nothing is saved.
        """
        latest = self._mgr.latest_step()
        if latest is None:
            return state, 0

        def abstract(leaf):
            if isinstance(leaf, jax.Array):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=leaf.sharding)
            a = np.asarray(leaf)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        target = jax.tree.map(abstract, state)
        restored = self._mgr.restore(
            latest,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target)),
        )
        log.info("restored checkpoint at epoch %d", latest)
        return restored["state"], latest + 1

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
