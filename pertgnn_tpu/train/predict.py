"""Per-trace latency prediction (inference) from a trained state.

The reference computes predictions only transiently inside `test()`
(/root/reference/pert_gnn.py:254-294) and discards them after metric
accumulation — there is no way to get the model's answer for a given
trace out of it. Here prediction is a first-class output: a jitted
forward over a split's packed batches whose per-graph predictions are
aligned back to the split's rows (and from there to trace ids via the
assembled meta table — cli/predict_main.py).
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from pertgnn_tpu.batching.dataset import Dataset
from pertgnn_tpu.config import Config
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.train.loop import TrainState, _device_iter

log = logging.getLogger(__name__)


def make_predict_step(model, cfg: Config):
    """Jitted (state, batch) -> per-graph predicted latency in label units
    (the model regresses y / label_scale; predictions are scaled back)."""

    def step(state: TrainState, batch):
        global_pred, _ = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch, training=False)
        return global_pred * cfg.train.label_scale

    return jax.jit(step)


def predict_split(dataset: Dataset, cfg: Config, state: TrainState,
                  split: str, step=None) -> np.ndarray:
    """Predicted end-to-end latency for EVERY example in `split`, in the
    split's positional order.

    Alignment relies on the greedy packer filling each batch with the
    maximal prefix of the remaining unshuffled order (batching/arena.py
    `assign_batches`), so concatenating each batch's valid graphs
    restores the split order — asserted, not assumed, by comparing the
    concatenated labels to the split's label array bit-for-bit.

    `step` (from make_predict_step) is rebuilt when omitted; callers
    predicting several splits should build it once — the XLA program is
    identical across splits (one shared batch shape).
    """
    if step is None:
        model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                           dataset.num_interfaces, dataset.num_rpctypes)
        step = make_predict_step(model, cfg)
    preds: list[np.ndarray] = []
    ys: list[np.ndarray] = []
    for batch in _device_iter(dataset.batches(split)):
        p = step(state, batch)
        mask = np.asarray(batch.graph_mask)
        preds.append(np.asarray(p)[mask])
        ys.append(np.asarray(batch.y)[mask])
    pred = (np.concatenate(preds) if preds
            else np.zeros(0, np.float32))
    got_y = (np.concatenate(ys) if ys else np.zeros(0, np.float32))
    want_y = np.asarray(dataset.splits[split].ys, np.float32)
    if not np.array_equal(got_y, want_y):
        raise AssertionError(
            f"prediction order lost alignment with the '{split}' split "
            f"({len(got_y)} graphs vs {len(want_y)} rows) — the packer's "
            "prefix-order invariant this function documents no longer "
            "holds")
    return pred


def predict_split_served(dataset: Dataset, cfg: Config, state: TrainState,
                         split: str, engine=None) -> np.ndarray:
    """`predict_split` routed through the serving engine's bucketed
    request path (serve/engine.py) instead of the epoch packer.

    Same contract — one prediction per split row, positional order — but
    the split is consumed as a request stream: greedy microbatches packed
    into the engine's shape buckets and dispatched through the AOT
    executable cache. Alignment is per-request by construction
    (engine.predict_many preserves prefix order), so unlike
    `predict_split` there is no packer invariant to re-assert; the row
    count is still pinned.

    `engine` (an InferenceEngine built over THIS dataset's mixtures and
    already warmed) is rebuilt when omitted; callers predicting several
    splits should build it once — the executable cache is shared.
    """
    from pertgnn_tpu.serve.engine import InferenceEngine

    if engine is None:
        engine = InferenceEngine.from_dataset(dataset, cfg, state)
        if cfg.serve.warmup:
            engine.warmup()
    s = dataset.splits[split]
    pred = engine.predict_many(s.entry_ids, s.ts_buckets)
    # row-count pin only: a multi-quantile head serves (rows, T)
    if len(pred) != len(np.asarray(s.ys)):
        raise AssertionError(
            f"served prediction count {pred.shape} diverged from the "
            f"'{split}' split rows {np.asarray(s.ys).shape}")
    return pred
