"""jit'd training loop: one compiled step, optax Adam, masked metrics.

Replaces the reference's per-batch Python driver
(/root/reference/pert_gnn.py:213-294): forward + loss + backward + Adam land
in ONE jit'd function per (train/eval) — everything the reference did on the
host per batch (probability rebuilds, metric float() syncs) is gone: mixture
probs travel inside the packed batch, and metrics leave the device as summed
scalars once per log interval.

The loss is the pinball loss of the global head over valid graphs
(pert_gnn.py:245); the per-node local head gets an optional auxiliary pinball
term (weight `local_loss_weight`) against its graph's label — the reference
computes local_pred but never trains on it (SURVEY.md §2.3), so 0 keeps
parity.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import optax
from flax import struct

from pertgnn_tpu import telemetry
from pertgnn_tpu.telemetry.devmem import sample_device_memory
from pertgnn_tpu.batching.dataset import Dataset
from pertgnn_tpu.batching.arena import zero_masked_compact
from pertgnn_tpu.batching.materialize import (
    DeviceArenas, arena_nbytes, build_device_arenas, materialize_compact,
    zero_masked_idx)
from pertgnn_tpu.batching.pack import PackedBatch, zero_masked
from pertgnn_tpu.config import (Config, primary_tau_index,
                                resolve_attention_impl,
                                resolve_quantile_taus)
from pertgnn_tpu.models.pert_model import PertGNN, make_model
from pertgnn_tpu.train.metrics import masked_metric_sums, quantile_loss

log = logging.getLogger(__name__)


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


@functools.lru_cache(maxsize=8)
def _jitted_model_init(model: PertGNN):
    """model.init fused into ONE jitted program (keyed on the model,
    which hashes by its config dataclass fields — equal configs share
    the compiled program across fit() calls). Eager flax init dispatches
    ~100 tiny programs; fused it is a single compile — and with the
    persistent compilation cache on, a single DISK REPLAY in every later
    process, the first chunk of fit()'s cold-start cost."""
    return jax.jit(
        lambda rng, sample: model.init(rng, sample, training=False))


def create_train_state(model: PertGNN, tx: optax.GradientTransformation,
                       sample: PackedBatch, seed: int = 0, *,
                       jit_init: bool = False) -> TrainState:
    sample = jax.tree.map(jnp.asarray, sample)
    init = None
    if jit_init:
        try:
            init = _jitted_model_init(model)
        except TypeError:
            # unhashable module (e.g. a live mesh baked into an
            # edge-shard model) — the eager path always works
            log.info("model not hashable; using eager (unjitted) init")
    if init is None:
        init = functools.partial(model.init, training=False)
    variables = init(jax.random.PRNGKey(seed), sample)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(params=params, batch_stats=batch_stats,
                      opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def _resolved_taus(cfg: Config) -> tuple[tuple[float, ...], int]:
    """(quantile levels, primary column index) — the per-config loss
    layout, resolved once through the single resolution point
    (config.resolve_quantile_taus)."""
    taus = resolve_quantile_taus(cfg.model, cfg.train.tau)
    return taus, primary_tau_index(taus, cfg.train.tau)


def _loss_fn(model: PertGNN, cfg: Config, params, batch_stats, batch,
             dropout_rng):
    variables = {"params": params, "batch_stats": batch_stats}
    rngs = {"dropout": dropout_rng} if cfg.model.dropout > 0 else {}
    (global_pred, local_pred), updates = model.apply(
        variables, batch, training=True, mutable=["batch_stats"], rngs=rngs)
    scale = cfg.train.label_scale
    y_scaled = batch.y / scale
    taus, pi = _resolved_taus(cfg)
    if len(taus) == 1:
        loss = quantile_loss(y_scaled, global_pred, taus[0],
                             mask=batch.graph_mask)
        primary = global_pred
    else:
        # one pinball term per (tau, column): the summed objective is
        # what makes every column a calibrated quantile regressor
        # (lens_bench exit-gates the empirical coverage)
        loss = sum(quantile_loss(y_scaled, global_pred[:, i], t,
                                 mask=batch.graph_mask)
                   for i, t in enumerate(taus))
        primary = global_pred[:, pi]
    if cfg.model.local_loss_weight > 0:
        # auxiliary per-node head, trained at the PRIMARY tau: the
        # reference computes local_pred but never trains on it
        # (pert_gnn.py:245) — attribution from an untrained head is
        # noise (docs/GUIDE.md §13), so attribution serving should set
        # this weight > 0. Rides every AOT train key via cfg.model.
        y_per_node = y_scaled[batch.node_graph]
        loss = loss + cfg.model.local_loss_weight * quantile_loss(
            y_per_node, local_pred, taus[pi], mask=batch.node_mask)
    metrics = masked_metric_sums(batch.y, primary * scale, taus[pi],
                                 batch.graph_mask)
    return loss, (updates["batch_stats"], metrics)


def train_step_fn(model: PertGNN, cfg: Config,
                  tx: optax.GradientTransformation) -> Callable:
    """The UNJITTED train step — the single source of truth for both the
    single-chip path (jitted here) and the mesh-sharded path
    (parallel/data_parallel.py jits it with shardings)."""

    def step(state: TrainState, batch: PackedBatch):
        rng = jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed),
                                 state.step)
        grad_fn = jax.value_and_grad(
            lambda p: _loss_fn(model, cfg, p, state.batch_stats, batch, rng),
            has_aux=True)
        (_, (new_stats, metrics)), grads = grad_fn(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return state.replace(params=new_params, batch_stats=new_stats,
                             opt_state=new_opt, step=state.step + 1), metrics

    return step


def eval_step_fn(model: PertGNN, cfg: Config) -> Callable:
    taus, pi = _resolved_taus(cfg)

    def step(state: TrainState, batch: PackedBatch):
        (global_pred, _) = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch, training=False)
        pred = global_pred if global_pred.ndim == 1 else global_pred[:, pi]
        return masked_metric_sums(batch.y,
                                  pred * cfg.train.label_scale,
                                  taus[pi], batch.graph_mask)

    return step


def make_train_step(model: PertGNN, cfg: Config,
                    tx: optax.GradientTransformation) -> Callable:
    return jax.jit(train_step_fn(model, cfg, tx), donate_argnums=0)


def make_eval_step(model: PertGNN, cfg: Config) -> Callable:
    return jax.jit(eval_step_fn(model, cfg))


_METRIC_KEYS = ("mae_sum", "mape_sum", "qloss_sum", "count")


def _train_chunk_from_step(step: Callable) -> Callable:
    """Scan-fuse any (state, batch) -> (state, metrics) step over a
    leading-stacked batch pytree. Pure-padding batches (all graph_mask
    False — the tail filler) skip the optimizer update under lax.cond so
    the step counter and Adam moments advance exactly once per REAL batch,
    as in the per-step path."""

    def chunk(state: TrainState, batches):
        def body(s, b):
            def run(s):
                return step(s, b)

            def skip(s):
                return s, {k: jnp.zeros((), jnp.float32)
                           for k in _METRIC_KEYS}

            return jax.lax.cond(jnp.any(b.graph_mask), run, skip, s)

        state, ms = jax.lax.scan(body, state, batches)
        return state, jax.tree.map(lambda a: a.sum(0), ms)

    return chunk


def _eval_chunk_from_step(step: Callable) -> Callable:
    """Scan-fuse an eval step over a leading-stacked batch pytree →
    metric sums (zero-masked tail fillers skip the forward)."""

    def chunk(state: TrainState, batches):
        def body(_, b):
            m = jax.lax.cond(
                jnp.any(b.graph_mask),
                lambda: step(state, b),
                lambda: {k: jnp.zeros((), jnp.float32)
                         for k in _METRIC_KEYS})
            return None, m

        _, ms = jax.lax.scan(body, None, batches)
        return jax.tree.map(lambda a: a.sum(0), ms)

    return chunk


def train_chunk_fn(model: PertGNN, cfg: Config,
                   tx: optax.GradientTransformation) -> Callable:
    """UNJITTED scan-fused chunk: `scan_chunk` train steps in one program
    over a leading-stacked PackedBatch. Per-step dispatch latency dominates
    this workload (TrainConfig.scan_chunk); fusing K steps amortizes it K x.
    Jitted plain here (make_train_chunk) and with mesh shardings by
    parallel/data_parallel.make_sharded_train_chunk."""
    return _train_chunk_from_step(train_step_fn(model, cfg, tx))


def eval_chunk_fn(model: PertGNN, cfg: Config) -> Callable:
    """UNJITTED scan-fused eval over a leading-stacked PackedBatch →
    metric sums."""
    return _eval_chunk_from_step(eval_step_fn(model, cfg))


def make_train_chunk(model: PertGNN, cfg: Config,
                     tx: optax.GradientTransformation) -> Callable:
    return jax.jit(train_chunk_fn(model, cfg, tx), donate_argnums=0)


def make_eval_chunk(model: PertGNN, cfg: Config) -> Callable:
    return jax.jit(eval_chunk_fn(model, cfg))


def make_train_chunk_compact(model: PertGNN, cfg: Config,
                             tx: optax.GradientTransformation,
                             dev: DeviceArenas, max_nodes: int,
                             max_edges: int) -> Callable:
    """Scan-fused train chunk over O(graphs) CompactBatch recipes: each
    scan iteration expands the per-graph recipe to gather indices and
    materializes the PackedBatch, all on device (materialize.py)."""
    base = train_step_fn(model, cfg, tx)
    return jax.jit(_train_chunk_from_step(
        lambda s, c: base(s, materialize_compact(dev, c, max_nodes,
                                                 max_edges))),
        donate_argnums=0)


def make_eval_chunk_compact(model: PertGNN, cfg: Config, dev: DeviceArenas,
                            max_nodes: int, max_edges: int) -> Callable:
    base = eval_step_fn(model, cfg)
    return jax.jit(_eval_chunk_from_step(
        lambda s, c: base(s, materialize_compact(dev, c, max_nodes,
                                                 max_edges))))


def make_train_step_compact(model: PertGNN, cfg: Config,
                            tx: optax.GradientTransformation,
                            dev: DeviceArenas, max_nodes: int,
                            max_edges: int) -> Callable:
    step = train_step_fn(model, cfg, tx)
    return jax.jit(
        lambda s, c: step(s, materialize_compact(dev, c, max_nodes,
                                                 max_edges)),
        donate_argnums=0)


def make_eval_step_compact(model: PertGNN, cfg: Config, dev: DeviceArenas,
                           max_nodes: int, max_edges: int) -> Callable:
    step = eval_step_fn(model, cfg)
    return jax.jit(
        lambda s, c: step(s, materialize_compact(dev, c, max_nodes,
                                                 max_edges)))


def _host_chunks(batches: Iterator, chunk_size: int,
                 filler: Callable = zero_masked) -> Iterator:
    """Leading-stack host batches into chunks of `chunk_size` (tail padded
    with inert zero-mask clones made by `filler`). Works for PackedBatch
    and IndexBatch streams alike."""
    import numpy as np

    group: list = []
    for b in batches:
        group.append(b)
        if len(group) == chunk_size:
            yield jax.tree.map(lambda *xs: np.stack(xs), *group)
            group = []
    if group:
        group += [filler(group[-1])] * (chunk_size - len(group))
        yield jax.tree.map(lambda *xs: np.stack(xs), *group)


def _background(items: Iterator, depth: int = 2) -> Iterator:
    """Run a host-side producer in a thread so packing/stacking overlaps
    device compute. numpy-only work belongs behind this; device puts stay on
    the consuming thread."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()

    def produce():
        try:
            for it in items:
                q.put(it)
            q.put(_END)
        except BaseException as e:  # lint: allow-silent-except — surfaced at the consumer
            q.put(e)

    t = threading.Thread(target=produce, daemon=True,
                         name="train-device-prefetch")
    t.start()
    while True:
        it = q.get()
        if it is _END:
            return
        if isinstance(it, BaseException):
            raise it
        yield it


def _chunk_iter(batches: Iterator[PackedBatch],
                chunk_size: int) -> Iterator[PackedBatch]:
    """Host chunking composed with the existing one-ahead device prefetch."""
    return _device_iter(_host_chunks(batches, chunk_size))


def _staged_epoch_iter(chunks: Iterator,
                       max_bytes: int | None = None,
                       prefetch_depth: int = 2) -> Iterator:
    """Stage an ENTIRE epoch's compact recipes on device in ONE transfer
    per field, then slice per chunk ON DEVICE.

    Per-chunk `jnp.asarray` costs one H2D round-trip per field per chunk;
    over the axon tunnel a single small put is ~3.5 ms, so a 37-chunk
    epoch x 4 CompactBatch fields ~ 0.5 s of pure transfer latency — the
    prime suspect for the on-chip fit_over_ceiling 0.659 (VERDICT r3
    weak 2). A whole epoch of recipes is only O(graphs) int32s (~1.6 MB
    at 98k graphs), so ship it as 4 stacked arrays in one shot; the
    per-chunk `staged[i]` slice is a device-side op dispatched
    asynchronously, no host round-trip. Contrast: the reference blocks on
    a full-batch H2D every step (/root/reference/pert_gnn.py:231)."""
    import numpy as np

    yield from _staged_iter(chunks, lambda _path, stacked: jnp.asarray(
        stacked), max_bytes=max_bytes, prefetch_depth=prefetch_depth)


def _staged_epoch_iter_sharded(chunks: Iterator, shardings,
                               max_bytes: int | None = None,
                               prefetch_depth: int = 2) -> Iterator:
    """Mesh twin of `_staged_epoch_iter`: one sharded device_put for the
    whole epoch's global compact recipes, sliced per chunk on device.

    The stacked array gets each leaf's NamedSharding with the epoch axis
    prepended replicated (P(None, *spec)); slicing away that axis yields
    exactly the per-chunk sharding the SPMD program was jitted with
    (pinned by tests/test_parallel.py staged-equivalence)."""
    from jax.sharding import NamedSharding, PartitionSpec

    flat_sh = jax.tree.leaves(shardings)

    def put(i, stacked):
        s = flat_sh[i]
        return jax.device_put(
            stacked, NamedSharding(s.mesh, PartitionSpec(None, *s.spec)))

    yield from _staged_iter(chunks, put, max_bytes=max_bytes,
                            prefetch_depth=prefetch_depth)


def _staged_iter(chunks: Iterator, put,
                 max_bytes: int | None = None,
                 prefetch_depth: int = 2) -> Iterator:
    """Shared staging shell: stack the whole epoch on host, device-put
    each leaf ONCE via `put(leaf_index, stacked)`, slice per chunk on
    device.

    Leaves are paired with their index by one explicit tree_flatten per
    chunk (ADVICE r4: a shared counter inside tree.map relied on map and
    leaves agreeing on traversal order). Staged bytes are O(graphs)
    int32s by construction; `max_bytes` guards the pathological case by
    falling back to per-chunk transfers (same `put`, epoch axis length 1)
    so staging can never blow the HBM budget unaccounted (ADVICE r4)."""
    import numpy as np

    with telemetry.span("train.stage_epoch.pack"):
        host = list(chunks)
    if not host:
        return
    _, treedef = jax.tree.flatten(host[0])
    cols = list(zip(*(jax.tree.flatten(h)[0] for h in host)))
    if max_bytes is not None:
        total = sum(np.asarray(x).nbytes for col in cols for x in col)
        if total > max_bytes:
            from pertgnn_tpu.batching.prefetch import prefetch_iter

            log.warning(
                "staged epoch recipes need %.1f MiB > cap %.1f MiB; "
                "falling back to per-chunk transfers "
                "(double-buffered, prefetch_depth=%d)",
                total / 2**20, max_bytes / 2**20, prefetch_depth)
            # capture runs must RECORD which transfer regime they
            # measured (BENCH captures only logged this once via
            # logging, invisible to the telemetry JSONL)
            telemetry.get_bus().counter(
                "train.staging_fallback", staged_mib=total / 2**20,
                cap_mib=max_bytes / 2**20, chunks=len(host),
                prefetch_depth=prefetch_depth)

            def transfer(h):
                leaves = jax.tree.flatten(h)[0]
                dev = [put(i, np.asarray(x)[None])
                       for i, x in enumerate(leaves)]
                return jax.tree.unflatten(treedef, [d[0] for d in dev])

            # overlap the device_put of chunk i+1 with compute of chunk
            # i — the synchronous per-chunk regime here was exactly the
            # production-scale degradation ISSUE 5 targets
            yield from prefetch_iter(host, transfer, depth=prefetch_depth,
                                     source="train.staging_fallback")
            return
    with telemetry.span("train.stage_epoch.h2d", chunks=len(host)):
        staged = jax.tree.unflatten(
            treedef, [put(i, np.stack(col)) for i, col in enumerate(cols)])
    for i in range(len(host)):
        yield jax.tree.map(lambda a: a[i], staged)


def _one_ahead(items):
    """Yield each item one step behind the producer, so the (async)
    device-put of the next item overlaps the consumer's compute."""
    pending = None
    for nxt in items:
        if pending is not None:
            yield pending
        pending = nxt
    if pending is not None:
        yield pending


def _device_iter(batches: Iterator[PackedBatch]) -> Iterator[PackedBatch]:
    """Single-step prefetch: device-put the next batch while the current one
    computes (the reference's `data.to(device)` is a blocking copy per batch,
    pert_gnn.py:231)."""
    return _one_ahead(jax.tree.map(jnp.asarray, b) for b in batches)


def evaluate(eval_step: Callable, state: TrainState,
             batches: Iterator[PackedBatch]) -> dict[str, float]:
    """Aggregate metrics over host batches (device-put with prefetch)."""
    return _evaluate_stream(eval_step, state, _device_iter(batches))


def _evaluate_stream(eval_step: Callable, state: TrainState,
                     device_batches: Iterator[PackedBatch]
                     ) -> dict[str, float]:
    sums = None
    for batch in device_batches:
        m = eval_step(state, batch)
        sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
    if sums is None:
        return {"mae": float("nan"), "mape": float("nan"),
                "qloss": float("nan"), "count": 0.0}
    sums = jax.tree.map(float, sums)
    n = max(sums["count"], 1.0)
    return {"mae": sums["mae_sum"] / n, "mape": sums["mape_sum"] / n,
            "qloss": sums["qloss_sum"] / n, "count": sums["count"]}


def make_tx(cfg: Config) -> optax.GradientTransformation:
    """THE training optimizer — the single construction point. Checkpoint
    restore targets (cli/predict_main.py) must build the identical
    opt_state tree, so any change here (schedule, weight decay, clipping)
    propagates to them by construction instead of by hand."""
    return optax.adam(cfg.train.lr)


def _train_sample(dataset: Dataset) -> PackedBatch:
    sample = next(dataset.batches("train"), None)
    if sample is None:
        # surfaced by fit() AND by inference's restore-target init
        # (restore_target_state) — keep the wording path-neutral
        raise ValueError(
            "the train split is empty — the ingest filters "
            "(min_traces_per_entry, resource coverage) likely dropped "
            "every trace; lower them or feed a larger corpus")
    return sample


def restore_target_state(dataset: Dataset, cfg: Config
                         ) -> tuple[PertGNN, TrainState]:
    """(model, freshly-initialized TrainState) with exactly the tree
    shapes the single-chip fit() trains and checkpoints — the orbax
    restore target for inference/resume outside fit()."""
    model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                       dataset.num_interfaces, dataset.num_rpctypes)
    state = create_train_state(model, make_tx(cfg), _train_sample(dataset),
                               cfg.train.seed, jit_init=cfg.aot.enabled)
    return model, state


def _resolve_stage_epoch_recipes(cfg: Config, bus, *,
                                 applies: bool = True) -> bool:
    """TrainConfig.stage_epoch_recipes tri-state -> the decision fit()
    runs with. None = AUTO: staged on accelerator backends (one transfer
    per epoch amortizes the link's per-transfer latency — the VERDICT r3
    on-chip gap), DISABLED on the CPU backend where whole-epoch staging
    measured strictly slower than streaming (staged_over_unstaged 0.956,
    BENCH_r05: no transfer latency to amortize, only an extra
    epoch-sized copy). True/False force it. The decision is logged AND
    counted (train.staging_decision) so capture runs record which
    transfer regime they measured — including `applies=False` runs
    (host-packed paths where staging is structurally inapplicable and a
    forced `--staged_epochs on` would otherwise be swallowed silently)."""
    setting = cfg.train.stage_epoch_recipes
    backend = jax.default_backend()
    if setting is None:
        staged, source = backend != "cpu", "auto"
    else:
        staged, source = bool(setting), "explicit"
    if not applies:
        if staged and source == "explicit":
            log.warning(
                "--staged_epochs on has no effect on this run: epoch-"
                "recipe staging needs the single-process "
                "device-materialize compact path (disabled here — "
                "over-budget arenas, edge sharding, mesh pallas, or "
                "multi-process: each host owns only its slab)")
        staged = False
    log.info("epoch-recipe staging %s (%s; backend=%s%s)",
             "enabled" if staged else "disabled", source, backend,
             "" if applies else "; inapplicable: host-packed path")
    bus.counter("train.staging_decision", staged=int(staged),
                source=source, backend=backend, applies=int(applies))
    return staged


def _resolve_device_materialize(dataset: Dataset, cfg: Config) -> bool:
    """Gate the chip-resident-arena path on the HBM budget.

    The feature arena is unbounded by the batch shape (it scales with
    unique (entry, ts_bucket) pairs x mixture width — VERDICT r2 weak #3);
    rather than OOM the chip, oversized arenas fall back to host-packed
    streaming with a logged warning."""
    if cfg.scale.accum_buckets > 1:
        # the SAR accumulated step (parallel/scale.py) scans stacked
        # PackedBatch buckets — it engages precisely when the mixture is
        # too big for residency, so the two modes are mutually exclusive
        # everywhere that resolves this flag (fit, precompile, continual,
        # graftaudit)
        if cfg.train.device_materialize:
            log.info("accum_buckets=%d > 1 forces the host-packed batch "
                     "path (SAR bucket accumulation replaces "
                     "device_materialize)", cfg.scale.accum_buckets)
        return False
    if not cfg.train.device_materialize:
        return False
    nbytes = arena_nbytes(dataset.arena(), dataset.feat_arena())
    budget = cfg.train.arena_hbm_budget_gb
    if budget is not None and nbytes > budget * 2**30:
        log.warning(
            "device arenas need %.2f GiB > arena_hbm_budget_gb=%.2f — "
            "falling back to host-packed batch streaming (raise the budget "
            "or shrink the dataset/feature arena to re-enable "
            "device_materialize)", nbytes / 2**30, budget)
        return False
    log.info("device arenas: %.1f MiB chip-resident (budget %s GiB)",
             nbytes / 2**20,
             "inf" if budget is None else f"{budget:g}")
    return True


def _abstract_tree(tree):
    import numpy as np

    def leaf(x):
        if not hasattr(x, "shape") or not hasattr(x, "dtype"):
            x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree.map(leaf, tree)


def _dataset_fingerprint(dataset: Dataset) -> str:
    """Content hash of the arenas the compact programs bake in as
    constants. A serialized compact executable replayed against a
    DIFFERENT dataset would silently materialize yesterday's features —
    this hash in the cache key turns that into a loud store miss."""
    import hashlib

    import numpy as np

    import dataclasses

    h = hashlib.sha256()
    # MixtureArena / FeatureArena are plain frozen dataclasses of numpy
    # arrays, NOT registered pytrees — walk their fields explicitly (a
    # tree.flatten would treat each arena as one opaque leaf and hash
    # object identity, which differs every process)
    for arena in (dataset.arena(), dataset.feat_arena()):
        for f in dataclasses.fields(arena):
            a = np.ascontiguousarray(np.asarray(getattr(arena, f.name)))
            h.update(f"{f.name}:{a.shape}:{a.dtype}".encode())
            h.update(a.tobytes())
    return h.hexdigest()[:16]


def _train_eval_abstract(dataset: Dataset, cfg: Config, state: TrainState,
                         compact: bool, plain_step: bool = False):
    """The (state, batch) ShapeDtypeStruct signature of the train/eval
    programs fit() will run (train and eval share it: same budget, same
    chunking, tail chunks zero-pad to shape).  `plain_step` skips the
    scan_chunk grouping — the SAR path feeds eval single batches and
    stacks the train signature itself."""
    if compact:
        batches = dataset.compact_batches("train")
        filler = zero_masked_compact
    else:
        batches = dataset.batches("train")
        filler = zero_masked
    if cfg.train.scan_chunk > 1 and not plain_step:
        b = next(_host_chunks(batches, cfg.train.scan_chunk, filler))
    else:
        b = next(batches)
    return _abstract_tree(state), _abstract_tree(b)


# Arena bytes above this are not worth serializing into store entries
# (the compact programs embed the arenas as constants; export/replay
# cost scales with them). The persistent XLA cache still applies.
_STORE_ARENA_LIMIT_BYTES = 256 * 2**20


def _train_eval_key_config(dataset: Dataset, cfg: Config, *,
                           compact: bool, sar_buckets: int = 0) -> dict:
    """The Config/dataset ingredients baked into the train/eval programs
    as constants — everything the abstract signature CANNOT see."""
    # only the TrainConfig fields BAKED INTO the program as constants:
    # keying the whole dataclass would invalidate on epochs/log_every/
    # checkpoint knobs that the compiled chunk never sees
    config = {"model": cfg.model, "graph_type": cfg.graph_type,
              "train": {k: getattr(cfg.train, k)
                        for k in ("lr", "tau", "label_scale", "seed",
                                  "scan_chunk")},
              # the packer budget sizes the program's padded buffers
              # (compact programs take it as make_*_compact constants and
              # their CompactBatch signature is (G,)-shaped, so the
              # abstract args can't see max_nodes/max_edges; without it a
              # budget_headroom/max_*_per_batch change would replay a
              # program whose scatters silently drop out-of-bounds rows)
              "budget": dataset.budget}
    if compact:
        config["dataset_sha"] = _dataset_fingerprint(dataset)
    if sar_buckets:
        # the SAR step's bucket CAPACITY is its only extra compiled
        # dimension (a live-count change reuses the program); remat
        # rides the key because remat on/off compile different HLO for
        # the same signature
        config["scale"] = {"accum_buckets": sar_buckets, "remat": True}
    return config


def _stored_train_eval(store, dataset: Dataset, cfg: Config,
                       state: TrainState, train_jit: Callable,
                       eval_jit: Callable, *, compact: bool,
                       sar_buckets: int = 0
                       ) -> tuple[Callable, Callable]:
    """Resolve fit()'s train/eval programs through the AOT executable
    store (pertgnn_tpu/aot/): a hit deserializes yesterday's executable
    (zero fresh model traces/compiles), a miss compiles ONCE and
    persists. Key = (env fingerprint, model+train config, graph_type,
    batch budget, dataset arena hash for compact programs, abstract
    signature).  With `sar_buckets` > 1 the train program is the SAR
    accumulated step (parallel/scale.py): its batch signature is the
    bucket-stacked PackedBatch and its key config carries the bucket
    capacity + remat mode — a capacity change is a new program, a LIVE
    bucket-count change is not (the capacity is the only compiled
    dimension)."""
    from pertgnn_tpu import aot

    abs_args = _train_eval_abstract(dataset, cfg, state, compact,
                                    plain_step=bool(sar_buckets))
    config = _train_eval_key_config(dataset, cfg, compact=compact)
    kind = "compact" if compact else "packed"
    suffix = ("chunk" if cfg.train.scan_chunk > 1 and not sar_buckets
              else "step")
    out = []
    for tag, jit_fn in (("train", train_jit), ("eval", eval_jit)):
        if tag == "train" and sar_buckets:
            name = "sar_step_packed"
            a = (abs_args[0],
                 jax.tree.map(lambda s: jax.ShapeDtypeStruct(
                     (sar_buckets,) + s.shape, s.dtype), abs_args[1]))
            sar_config = _train_eval_key_config(
                dataset, cfg, compact=compact, sar_buckets=sar_buckets)
            key, components = aot.cache_key(
                fn_id=f"train.loop.{name}.v1", config=sar_config,
                args_sig=aot.abstract_signature(a))
        else:
            name = f"{tag}_{suffix}_{kind}"
            a = abs_args
            key, components = aot.cache_key(
                fn_id=f"train.loop.{name}.v1", config=config,
                args_sig=aot.abstract_signature(a))
        # the train step jits with donate_argnums=0 (make_train_* and
        # make_sar_train_step alike); the store's stablehlo replay must
        # mirror it or jax keeps the donated state arrays "live" over
        # buffers XLA reuses in place
        exe, outcome = store.load_or_build(
            name, key, components, jit_fn, a,
            donate_argnums=(0,) if tag == "train" else ())
        log.info("AOT %s program: %s", name, outcome)
        out.append(exe)
    return out[0], out[1]


def _model_init_key_config(cfg: Config, model: PertGNN) -> dict:
    """model_init bakes the dataset vocab sizes into the embedding table
    shapes (make_model constructor args) — the packed-sample signature
    alone can't distinguish two datasets with different vocabs, and a
    stale init would hand back undersized tables that clamped gathers
    then index silently wrong."""
    return {"model": cfg.model, "graph_type": cfg.graph_type,
            "vocab": {"num_ms": model.num_ms,
                      "num_entries": model.num_entries,
                      "num_interfaces": model.num_interfaces,
                      "num_rpctypes": model.num_rpctypes}}


def _stored_init_state(store, cfg: Config, model: PertGNN,
                       tx: optax.GradientTransformation,
                       sample: PackedBatch) -> TrainState | None:
    """TrainState whose model init ran through the executable store —
    warm processes deserialize the init program instead of re-tracing
    the model. None when the model can't take the jitted path."""
    from pertgnn_tpu import aot

    try:
        init_jit = _jitted_model_init(model)
    except TypeError:
        return None
    sample_dev = jax.tree.map(jnp.asarray, sample)
    rng = jax.random.PRNGKey(cfg.train.seed)
    abs_args = (_abstract_tree(rng), _abstract_tree(sample_dev))
    key, components = aot.cache_key(
        fn_id="train.loop.model_init.v1",
        config=_model_init_key_config(cfg, model),
        args_sig=aot.abstract_signature(abs_args))
    exe, outcome = store.load_or_build("model_init", key, components,
                                       init_jit, abs_args)
    log.info("AOT model_init program: %s", outcome)
    variables = exe(rng, sample_dev)
    params = variables["params"]
    return TrainState(params=params,
                      batch_stats=variables.get("batch_stats", {}),
                      opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))


def build_single_device_programs(dataset: Dataset, cfg: Config, *,
                                 model: PertGNN,
                                 tx: optax.GradientTransformation,
                                 sample: PackedBatch,
                                 device_materialize: bool, bus=None
                                 ) -> tuple[TrainState, Callable, Callable]:
    """(state, train_step, eval_step) exactly as single-process fit()
    runs them — THE shared construction for fit() and the host-side
    precompile stage (aot/precompile.py), so the programs the precompile
    persists are the programs fit() replays, by code identity rather
    than by parallel maintenance. With CompileCacheConfig.cache_dir set,
    init is one fused jitted program and init + train/eval programs
    resolve through the serialized-executable store."""
    store = None
    if cfg.aot.enabled:
        from pertgnn_tpu import aot

        # unconditional: the branches below that SKIP the executable
        # store (large arenas, serialize_executables=False) are exactly
        # the ones that depend on the persistent XLA cache, and
        # programmatic fit() callers have no CLI to have enabled it
        aot.enable_compile_cache(cfg.aot)
        if cfg.aot.serialize_executables:
            if device_materialize and arena_nbytes(
                    dataset.arena(),
                    dataset.feat_arena()) > _STORE_ARENA_LIMIT_BYTES:
                log.info("arenas exceed the executable-store size guard "
                         "(%d MiB) — compact programs rely on the "
                         "persistent XLA cache only",
                         _STORE_ARENA_LIMIT_BYTES // 2**20)
            else:
                store = aot.store_from_config(cfg, bus=bus)
    state = None
    if store is not None:
        state = _stored_init_state(store, cfg, model, tx, sample)
    if state is None:
        state = create_train_state(model, tx, sample, cfg.train.seed,
                                   jit_init=cfg.aot.enabled)
    # SAR bucket accumulation (parallel/scale.py): one jitted step scans
    # the whole mixture as stacked topology buckets with a rematerialized
    # body — engages when accum_buckets > 1 (device_materialize already
    # resolved False for it, see _resolve_device_materialize)
    sar_buckets = cfg.scale.accum_buckets if cfg.scale.accum_buckets > 1 else 0
    if sar_buckets and device_materialize:
        raise ValueError(
            "accum_buckets > 1 needs the host-packed path; "
            "device_materialize should have resolved False")
    chunked = cfg.train.scan_chunk > 1 and not sar_buckets
    if sar_buckets:
        from pertgnn_tpu.parallel.scale import make_sar_train_step

        train_step = make_sar_train_step(model, cfg, tx, remat=True)
        eval_step = make_eval_step(model, cfg)
    elif device_materialize:
        dev = dataset.device_arenas()
        mn, me = dataset.budget.max_nodes, dataset.budget.max_edges
        if chunked:
            train_step = make_train_chunk_compact(model, cfg, tx, dev,
                                                  mn, me)
            eval_step = make_eval_chunk_compact(model, cfg, dev, mn, me)
        else:
            train_step = make_train_step_compact(model, cfg, tx, dev,
                                                 mn, me)
            eval_step = make_eval_step_compact(model, cfg, dev, mn, me)
    elif chunked:
        train_step = make_train_chunk(model, cfg, tx)
        eval_step = make_eval_chunk(model, cfg)
    else:
        train_step = make_train_step(model, cfg, tx)
        eval_step = make_eval_step(model, cfg)
    if store is not None:
        train_step, eval_step = _stored_train_eval(
            store, dataset, cfg, state, train_step, eval_step,
            compact=device_materialize, sar_buckets=sar_buckets)
    return state, train_step, eval_step


def fit(dataset: Dataset, cfg: Config,
        epochs: int | None = None,
        checkpoint_manager=None,
        profile_hook: Callable[[int, dict], None] | None = None,
        mesh=None,
        bus=None,
        ) -> tuple[TrainState, list[dict]]:
    """Epoch driver: train on `train`, evaluate `valid`+`test` per epoch
    (pert_gnn.py:344-350). Returns (final state, per-epoch history).

    With `mesh` (jax.sharding.Mesh with a `data` axis), per-step batches are
    grouped into global batches sharded over the mesh and the step runs
    SPMD (BASELINE config 3). `device_materialize` composes: the arenas are
    replicated over the mesh and each SPMD program gathers its global batch
    from HBM, fed only the sharded int32 gather recipes.

    The first history row carries ``ttfs_s`` — wall time from fit()
    entry to the first completed train step (model build + state init +
    first batch + first-chunk compile & execute: THE cold-start metric;
    also emitted as the ``train.time_to_first_step_s`` gauge). With a
    persistent compile cache (CompileCacheConfig + a precompile pass)
    the compile component is a disk replay — benchmarks/
    coldstart_bench.py measures the delta.

    `bus` is an injected telemetry bus (default: the process-wide bus,
    a no-op unless a CLI configured one). Per epoch it receives the
    host/device wall-time split (train.epoch_host_s / train.epoch_device_s
    — host = time blocked on the batch iterator: packing, staging,
    assembly; device = step dispatch + the metric sync, which absorbs
    device execution), graph/step counters, and eval + checkpoint spans.
    When the process-wide bus is still the no-op, the injected bus is
    installed as the process-wide bus for the duration of this call so
    the global-bus call sites underneath (the packer's pad-waste gauges,
    staging spans, checkpoint spans) reach it too; an explicitly
    configured global bus is never displaced."""
    t_fit0 = time.perf_counter()
    if mesh is not None and cfg.scale.accum_buckets > 1:
        # the SAR accumulated step and SPMD data parallelism both decide
        # how a step's batches map onto memory — composing them silently
        # would accumulate over PER-SHARD buckets with unclear semantics;
        # pick one scale-out axis per run (GUIDE §15)
        raise ValueError(
            "accum_buckets > 1 is the single-device scale-out path; it "
            "does not compose with a mesh — drop the mesh or set "
            "accum_buckets=1")
    edge_shard = mesh is not None and cfg.parallel.shard_edges
    model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                       dataset.num_interfaces, dataset.num_rpctypes,
                       edge_shard_mesh=mesh if edge_shard else None)
    tx = make_tx(cfg)
    sample = _train_sample(dataset)
    if edge_shard and cfg.model.attn_dropout > 0:
        # the layer would silently fall back to full-edge unsharded
        # attention in training (layers.py), defeating the giant-graph mode
        # exactly where it matters — refuse the combination instead
        raise ValueError(
            "shard_edges does not support attn_dropout > 0 (attention-"
            "weight dropout would need per-shard rng plumbing inside the "
            "shard_map); set attn_dropout=0 or disable shard_edges")
    mesh_pallas = mesh is not None and cfg.model.use_pallas_attention
    if mesh_pallas and cfg.train.device_materialize and not edge_shard:
        # stack_index_batches does NOT restore the global receiver-sorted
        # edge order the Pallas kernel's assume_sorted block-skipping
        # requires (stack_batches does) — host-packed keeps it correct
        log.warning(
            "use_pallas_attention with a mesh forces the host-packed batch "
            "path: the stacked gather recipes are not globally "
            "receiver-sorted, which the fused kernel requires")
    device_materialize = (not edge_shard and not mesh_pallas
                          and _resolve_device_materialize(dataset, cfg))
    stage_recipes = _resolve_stage_epoch_recipes(
        cfg, bus if bus is not None else telemetry.get_bus(),
        applies=device_materialize and jax.process_count() == 1)
    if edge_shard:
        # Giant-graph ("sequence parallel") mode: the layers shard each
        # batch's EDGE set over the mesh's data axis internally
        # (graph_shard.sharded_edge_attention); batches stay replicated —
        # the data axis scales graph size, not batch count (SURVEY.md §5.7,
        # BASELINE config 5).
        from pertgnn_tpu.parallel.data_parallel import (
            make_edge_sharded_eval_step, make_edge_sharded_train_step,
            shard_batch)
        from pertgnn_tpu.parallel.mesh import replicated_batch_shardings
        n_data = mesh.shape["data"]
        num_edges = sample.senders.shape[0]
        if num_edges % n_data:
            raise ValueError(
                f"shard_edges needs the edge budget ({num_edges}) divisible "
                f"by the data axis ({n_data}); set data.max_edges_per_batch "
                f"to a multiple of {n_data}")
        chunked = cfg.train.scan_chunk > 1
        state = create_train_state(model, tx, sample, cfg.train.seed)
        train_step, state = make_edge_sharded_train_step(
            model, cfg, tx, mesh, state, chunked=chunked)
        eval_step = make_edge_sharded_eval_step(model, cfg, mesh, state,
                                                chunked=chunked)
        b_sh = replicated_batch_shardings(mesh)

        def batch_stream(split, shuffle=False, seed=0):
            batches = dataset.batches(split, shuffle=shuffle, seed=seed)
            if chunked:
                batches = _host_chunks(batches, cfg.train.scan_chunk)
            return _one_ahead(shard_batch(b, mesh, b_sh) for b in batches)
    elif mesh is not None:
        from pertgnn_tpu.parallel.data_parallel import (
            chunk_compact_batch_shardings, compact_batch_shardings,
            grouped_batches, grouped_compact_batches,
            make_sharded_eval_chunk, make_sharded_eval_step,
            make_sharded_eval_step_compact, make_sharded_train_chunk,
            make_sharded_train_step, make_sharded_train_step_compact,
            shard_batch, stack_batches)
        from pertgnn_tpu.parallel.mesh import (
            batch_shardings, chunk_batch_shardings, replicated_sharding)
        from pertgnn_tpu.parallel.multihost import (
            assemble_global, host_grouped_batches,
            host_grouped_compact_batches)
        n_shards = mesh.shape["data"]
        n_proc = jax.process_count()
        init_sample = stack_batches([sample] * n_shards)
        state = create_train_state(model, tx, init_sample, cfg.train.seed)
        chunked = cfg.train.scan_chunk > 1
        arena_h = dataset.arena()
        feats_h = dataset.feat_arena()

        def idx_filler(b):
            return zero_masked_idx(b, arena_h, feats_h)

        def to_device(glob, sh):
            """Host global-batch (or per-host slab) stream -> mesh arrays.
            Single-process: direct sharded device_put. Multi-process: each
            host built only its slab; assemble the global arrays (the
            sharded dim is 1 inside a scan chunk, 0 otherwise)."""
            if n_proc == 1:
                return _one_ahead(shard_batch(g, mesh, sh) for g in glob)
            return _one_ahead(
                assemble_global(g, sh, axis=1 if chunked else 0)
                for g in glob)

        if device_materialize:
            # O(graphs) SPMD feeding: global compact recipes sharded over
            # `data`; each shard expands its block locally (shard_map) and
            # the program materializes the global batch from replicated
            # arenas (materialize.expand_compact_sharded).
            dev = build_device_arenas(arena_h, feats_h,
                                      sharding=replicated_sharding(mesh))
            mn, me = dataset.budget.max_nodes, dataset.budget.max_edges
            train_step, state = make_sharded_train_step_compact(
                model, cfg, tx, mesh, state, dev, mn, me, chunked=chunked)
            eval_step = make_sharded_eval_step_compact(
                model, cfg, mesh, state, dev, mn, me, chunked=chunked)
            sh = (chunk_compact_batch_shardings(mesh) if chunked
                  else compact_batch_shardings(mesh))

            def batch_stream(split, shuffle=False, seed=0):
                cbs = dataset.compact_batches(split, shuffle=shuffle,
                                              seed=seed)
                if n_proc > 1:  # each process stacks only its own shards
                    glob = host_grouped_compact_batches(
                        cbs, n_shards, zero_masked_compact)
                else:
                    glob = grouped_compact_batches(cbs, n_shards)
                if chunked:
                    glob = _host_chunks(glob, cfg.train.scan_chunk,
                                        zero_masked_compact)
                if n_proc == 1 and stage_recipes:
                    # O(graphs) recipes: one sharded transfer per epoch
                    # (multi-process keeps per-chunk assembly — each host
                    # owns only its slab)
                    return _staged_epoch_iter_sharded(
                        glob, sh,
                        max_bytes=int(cfg.train.stage_recipes_max_mb * 2**20),
                        prefetch_depth=cfg.train.prefetch_depth)
                if shuffle:  # train: packing off the critical path
                    glob = _background(glob)
                return to_device(glob, sh)
        else:
            if chunked:
                # scan-fused SPMD: one dispatch per scan_chunk globals
                train_step, state = make_sharded_train_chunk(model, cfg, tx,
                                                             mesh, state)
                eval_step = make_sharded_eval_chunk(model, cfg, mesh, state)
                sh = chunk_batch_shardings(mesh)
            else:
                train_step, state = make_sharded_train_step(model, cfg, tx,
                                                            mesh, state)
                eval_step = make_sharded_eval_step(model, cfg, mesh, state)
                sh = batch_shardings(mesh)

            def batch_stream(split, shuffle=False, seed=0):
                if n_proc > 1:  # materialize only this host's shards
                    glob = host_grouped_batches(
                        dataset.index_batches(split, shuffle=shuffle,
                                              seed=seed),
                        n_shards, dataset.materializer(split), idx_filler)
                else:
                    glob = grouped_batches(
                        dataset.batches(split, shuffle=shuffle, seed=seed),
                        n_shards)
                if chunked:
                    glob = _host_chunks(glob, cfg.train.scan_chunk)
                return to_device(glob, sh)
    else:
        # single-device paths: program construction (incl. the AOT
        # executable store / fused init when a compile cache is
        # configured) is shared with the precompile stage
        state, train_step, eval_step = build_single_device_programs(
            dataset, cfg, model=model, tx=tx, sample=sample,
            device_materialize=device_materialize, bus=bus)
        if cfg.scale.accum_buckets > 1:
            # SAR bucket accumulation: the whole train mixture rides ONE
            # accumulated step per epoch as a stacked bucket pytree (the
            # step's scan skips dead padding buckets, so short epochs
            # reuse the same program); eval stays per-batch.  A mixture
            # larger than the capacity refuses (AccumulationOverflow)
            # instead of training on a silent subset.
            from pertgnn_tpu.parallel.scale import (bucket_batches,
                                                    sample_bucket_memory)
            _sar_cap = cfg.scale.accum_buckets
            _sar_step = train_step

            def train_step(state, batch):  # noqa: F811
                out = _sar_step(state, batch)
                # per-bucket-capacity allocator curve (no-op on CPU; the
                # bench asserts the compiled temp-bytes proxy there)
                sample_bucket_memory(None, buckets=_sar_cap)
                return out

            def batch_stream(split, shuffle=False, seed=0):
                batches = dataset.batches(split, shuffle=shuffle,
                                          seed=seed)
                if not shuffle:
                    return _device_iter(batches)
                stacked = bucket_batches(list(batches), _sar_cap)
                return _device_iter(iter([stacked]))
        elif device_materialize:
            # Chip-resident arenas + O(graphs) CompactBatch feeding: the
            # host ships only per-graph (entry, feat_start, y, mask)
            # rows; the device expands them to gather indices (cumsum +
            # searchsorted) and materializes the batch out of HBM.
            # Per-epoch host work is the greedy assignment + G-sized
            # scatters (batching/arena.py).
            def batch_stream(split, shuffle=False, seed=0):
                cbs = dataset.compact_batches(split, shuffle=shuffle,
                                              seed=seed)
                if cfg.train.scan_chunk > 1:
                    cbs = _host_chunks(cbs, cfg.train.scan_chunk,
                                       zero_masked_compact)
                if stage_recipes:
                    # one H2D per field per EPOCH (recipes are O(graphs)
                    # int32s); host packing is a few ms so no background
                    # thread is needed ahead of the single transfer
                    return _staged_epoch_iter(
                        cbs,
                        max_bytes=int(cfg.train.stage_recipes_max_mb
                                      * 2**20),
                        prefetch_depth=cfg.train.prefetch_depth)
                if shuffle:  # train: pack off the critical path
                    cbs = _background(cbs)
                return _device_iter(cbs)
        elif cfg.train.scan_chunk > 1:
            # scan-fused stepping: one dispatch per `scan_chunk` steps
            def batch_stream(split, shuffle=False, seed=0):
                return _chunk_iter(dataset.batches(split, shuffle=shuffle,
                                                   seed=seed),
                                   cfg.train.scan_chunk)
        else:
            def batch_stream(split, shuffle=False, seed=0):
                return _device_iter(dataset.batches(split, shuffle=shuffle,
                                                    seed=seed))

    if device_materialize and mesh is None:
        # Deterministic eval splits are identical every epoch; on the
        # single-device compact path the per-epoch feed is only O(graphs)
        # int32 recipes, so stage them on device ONCE and replay (eval
        # steps don't donate their batch). Mesh runs also feed O(graphs)
        # compact recipes now, but are excluded anyway: multi-host replay
        # would pin make_array-assembled globals per process and the win
        # is the same few ms — revisit if mesh eval ever shows up in a
        # profile. Shuffled (train) streams always rebuild.
        _eval_device_cache: dict[str, list] = {}
        _inner_stream = batch_stream

        def batch_stream(split, shuffle=False, seed=0):  # noqa: F811
            if shuffle:
                return _inner_stream(split, shuffle=shuffle, seed=seed)
            cached = _eval_device_cache.get(split)
            if cached is None:
                cached = _eval_device_cache[split] = list(
                    _inner_stream(split, seed=seed))
            return iter(cached)

    restore_bus = None
    if bus is None:
        bus = telemetry.get_bus()
    elif not telemetry.get_bus().enabled:
        # scope the injected bus process-wide so the global-bus call
        # sites below fit (packer, staging, checkpoints) see it too
        restore_bus = telemetry.set_bus(bus)
    # which conv hot-op implementation this run's programs bake in —
    # capture JSONLs must attribute every throughput number to its
    # kernel variant (docs/OBSERVABILITY.md)
    bus.counter("model.kernel_variant",
                impl=resolve_attention_impl(cfg.model),
                block_n=cfg.model.kernel_block_n,
                block_e=cfg.model.kernel_block_e)
    try:
        return _fit_epochs(dataset, cfg, epochs, checkpoint_manager,
                           profile_hook, state, train_step, eval_step,
                           batch_stream, bus, t_fit0)
    finally:
        if restore_bus is not None:
            telemetry.set_bus(restore_bus)


def _fit_epochs(dataset, cfg, epochs, checkpoint_manager, profile_hook,
                state, train_step, eval_step, batch_stream, bus,
                t_start: float | None = None
                ) -> tuple[TrainState, list[dict]]:
    """fit()'s epoch driver, split out so the injected-bus scoping wraps
    it in one try/finally."""
    start_epoch = 0
    if checkpoint_manager is not None:
        state, start_epoch = checkpoint_manager.maybe_restore(state)

    ttfs_s: float | None = None
    history: list[dict] = []
    epochs = cfg.train.epochs if epochs is None else epochs
    _END = object()
    for epoch in range(start_epoch, epochs):
        t0 = time.perf_counter()
        sums = None
        # Host/device wall split: t_host = blocked on the batch iterator
        # (packing / staging / H2D assembly); t_dev = step dispatch + the
        # final metric sync — with async dispatch the device's execution
        # time surfaces wherever the host blocks, which is here.
        t_host = t_dev = 0.0
        steps = 0
        stream = iter(batch_stream("train", shuffle=True,
                                   seed=cfg.data.shuffle_seed + epoch))
        while True:
            t1 = time.perf_counter()
            batch = next(stream, _END)
            t_host += time.perf_counter() - t1
            if batch is _END:
                break
            t1 = time.perf_counter()
            with bus.span("train.chunk", level=2, epoch=epoch, step=steps):
                state, m = train_step(state, batch)
                sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
            if ttfs_s is None and t_start is not None:
                # time-to-first-step: everything between fit() entry and
                # the first step's results being real — model build,
                # state init, first batch, first-chunk compile (a disk
                # replay when the persistent compile cache is warm) and
                # execution. The one extra sync is first-step-only.
                jax.block_until_ready(m)
                ttfs_s = time.perf_counter() - t_start
                bus.gauge("train.time_to_first_step_s", ttfs_s)
                log.info("time to first train step: %.2fs", ttfs_s)
            t_dev += time.perf_counter() - t1
            steps += 1
        t1 = time.perf_counter()
        sums = jax.tree.map(float, sums)
        t_dev += time.perf_counter() - t1
        n = max(sums["count"], 1.0)
        train_time = time.perf_counter() - t0

        with bus.span("train.eval", epoch=epoch, split="valid"):
            valid = _evaluate_stream(eval_step, state, batch_stream("valid"))
        with bus.span("train.eval", epoch=epoch, split="test"):
            test = _evaluate_stream(eval_step, state, batch_stream("test"))
        row = {
            "epoch": epoch,
            "train_qloss": sums["qloss_sum"] / n,
            "train_mae": sums["mae_sum"] / n,
            "train_mape": sums["mape_sum"] / n,
            "valid_mae": valid["mae"], "valid_mape": valid["mape"],
            "valid_qloss": valid["qloss"],
            "test_mae": test["mae"], "test_mape": test["mape"],
            "test_qloss": test["qloss"],
            "train_time_s": train_time,
            "host_time_s": t_host,
            "device_time_s": t_dev,
            "graphs_per_s": sums["count"] / max(train_time, 1e-9),
        }
        if ttfs_s is not None and epoch == start_epoch:
            row["ttfs_s"] = ttfs_s
        bus.gauge("train.epoch_host_s", t_host, epoch=epoch)
        bus.gauge("train.epoch_device_s", t_dev, epoch=epoch)
        bus.gauge("train.epoch_graphs_per_s", row["graphs_per_s"],
                  epoch=epoch)
        bus.gauge("train.epoch_qloss", row["train_qloss"], epoch=epoch)
        # allocator state per epoch (ISSUE 17): None-safe no-op on
        # backends without memory_stats (CPU); on-chip it turns "did the
        # arena + donation discipline hold" into a per-epoch curve
        sample_device_memory(bus, where="fit_epoch", epoch=epoch)
        bus.counter("train.graphs", sums["count"], epoch=epoch)
        # every train_step/chunk dispatch donates its input state buffers
        # (make_train_* jit with donate_argnums=0) — the reuse count was
        # previously computed and thrown away
        bus.counter("train.donated_buffer_dispatches", steps, epoch=epoch)
        history.append(row)
        log.info(
            "epoch %d: train qloss %.4f mae %.4f | valid mae %.4f mape %.4f "
            "| test mae %.4f mape %.4f qloss %.4f | %.1f graphs/s",
            epoch, row["train_qloss"], row["train_mae"], row["valid_mae"],
            row["valid_mape"], row["test_mae"], row["test_mape"],
            row["test_qloss"], row["graphs_per_s"])
        if profile_hook is not None:
            profile_hook(epoch, row)
        if checkpoint_manager is not None:
            checkpoint_manager.save(epoch, state, row)
    if profile_hook is not None and hasattr(profile_hook, "close"):
        profile_hook.close()
    if checkpoint_manager is not None:
        checkpoint_manager.wait()
    return state, history
