"""Crash/hang supervisor: automatic restart-and-resume for training runs.

The reference loses all training progress on any failure (no state_dict
save anywhere in pert_gnn.py — SURVEY.md §5.3/5.4) and, being a local
single-GPU script, never faces a flaky device transport. A TPU run does:
this round's capture log shows the device tunnel wedging INSIDE a blocked
device call, a failure mode that raises nothing and hangs the process
forever — no in-process guard can fire (the endurance drill in
benchmarks/endurance_drill.py proves the crash half; this module makes
both halves operational).

`supervise` runs the training command as a child process and watches the
checkpoint directory for progress:

- child exits 0            -> done
- child exits nonzero      -> restart (fit() auto-resumes from the last
                              committed orbax checkpoint via
                              CheckpointManager.maybe_restore)
- no checkpoint progress   -> the wedge signature: SIGKILL the child and
  for `hang_timeout` s        restart it; a reopened device transport
                              resumes from the last committed epoch

Restart correctness is not hoped-for: the endurance drill pins resumed
final qloss bit-identical to an uninterrupted control at full scale
(benchmarks/endurance_r5.jsonl).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import time

log = logging.getLogger(__name__)

CHILD_ENV_MARKER = "PERTGNN_SUPERVISED_CHILD"


def progress_token(progress_dir: str) -> tuple:
    """A cheap token that changes whenever the checkpoint directory makes
    progress: the top-level step entries plus the newest mtime anywhere
    under the tree. Orbax commits a step as a directory rename (entry-set
    change); the deep walk sees async write churn inside a step too, so a
    child mid-way through one long checkpoint write still reads as alive
    rather than wedged."""
    try:
        entries = sorted(os.listdir(progress_dir))
    except OSError:
        return ("missing",)
    newest = 0.0
    for root, _dirs, files in os.walk(progress_dir):
        for name in (*files, ""):
            try:
                newest = max(newest, os.stat(
                    os.path.join(root, name) if name else root).st_mtime)
            except OSError:
                pass
    return (tuple(entries), newest)


def restart_backoff(consecutive_failures: int, base: float,
                    cap: float) -> float:
    """Seconds to wait before restart number `consecutive_failures`
    (1-based): exponential from `base`, clamped at `cap`. Pure — the
    backoff tests pin the schedule without sleeping through it."""
    if consecutive_failures <= 0 or base <= 0:
        return 0.0
    return min(cap, base * (2.0 ** (consecutive_failures - 1)))


def supervise(cmd: list[str], progress_dir: str, *,
              max_restarts: int = 3, hang_timeout: float = 900.0,
              poll_interval: float = 5.0, backoff_base: float = 1.0,
              backoff_cap: float = 60.0, min_uptime_s: float = 5.0) -> int:
    """Run `cmd` under crash/hang supervision; returns the final exit code
    (0 on eventual success, the last failure code once `max_restarts` is
    exhausted, 124 if the final attempt hung).

    `hang_timeout` must exceed the child's startup (data build + first
    compile) plus one checkpoint interval — progress is only visible at
    checkpoint granularity.

    Restarts back off exponentially (`backoff_base` * 2^k, clamped at
    `backoff_cap`) instead of respawning immediately: a child that dies
    during startup (bad flag, wedged transport, poisoned cache) would
    otherwise burn its whole restart budget in seconds. A child that
    dies within `min_uptime_s` of spawn is the crash-loop signature —
    counted separately (``supervisor.crash_loop``) so a dashboard can
    tell "it keeps dying instantly" from "it trained for an hour and
    crashed". A child that survives `min_uptime_s` resets the backoff
    (the same restart discipline the serve watchdog applies to the
    request path — docs/RELIABILITY.md).
    """

    def _kill_group(child) -> None:
        # the whole session: a wedged runtime can leave helper processes
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except OSError:
            child.kill()
        child.wait()

    # The child lives in its own session (so killpg can't suicide the
    # supervisor), which also detaches it from the terminal's Ctrl-C —
    # the supervisor dying must therefore take the child with it, or an
    # unsupervised run keeps the accelerator. SIGINT arrives as
    # KeyboardInterrupt (the finally covers it); SIGTERM (job-manager
    # preemption) is converted to SystemExit so the finally runs too.
    def _term(signum, frame):
        raise SystemExit(128 + signum)

    try:
        prev_term = signal.signal(signal.SIGTERM, _term)
    except ValueError:  # not the main thread: rely on the finally alone
        prev_term = None
    from pertgnn_tpu import telemetry
    bus = telemetry.get_bus()
    attempt = 0
    consecutive_failures = 0
    child = None
    try:
        while True:
            attempt += 1
            log.info("supervisor: starting attempt %d/%d: %s",
                     attempt, max_restarts + 1, " ".join(cmd))
            t_spawn = time.monotonic()
            child = subprocess.Popen(
                cmd, env={**os.environ, CHILD_ENV_MARKER: "1"},
                start_new_session=True)
            last_token = progress_token(progress_dir)
            last_change = time.monotonic()
            hung = False
            while True:
                rc = child.poll()
                if rc is not None:
                    break
                time.sleep(poll_interval)
                token = progress_token(progress_dir)
                if token != last_token:
                    last_token, last_change = token, time.monotonic()
                elif time.monotonic() - last_change > hang_timeout:
                    hung = True
                    log.warning("supervisor: no checkpoint progress for "
                                "%.0f s; killing the child (wedge "
                                "signature)", hang_timeout)
                    _kill_group(child)
                    rc = 124
                    break
            if rc == 0:
                log.info("supervisor: child completed (attempt %d)",
                         attempt)
                bus.counter("supervisor.completed", attempt=attempt)
                return 0
            uptime = time.monotonic() - t_spawn
            log.warning("supervisor: child %s (rc=%s) on attempt %d "
                        "after %.1fs", "hung" if hung else "died", rc,
                        attempt, uptime)
            bus.counter("supervisor.hang" if hung else "supervisor.crash",
                        attempt=attempt, rc=rc)
            # a child that ran for a while earned a clean slate; one
            # that died within min_uptime_s is crash-looping — escalate
            # the backoff instead of burning the restart budget in
            # seconds (hangs always ran >= hang_timeout, so they reset)
            if not hung and uptime < min_uptime_s:
                consecutive_failures += 1
                log.warning("supervisor: crash loop signature — child "
                            "died within min_uptime_s=%.1fs (%d "
                            "consecutive fast failures)", min_uptime_s,
                            consecutive_failures)
                bus.counter("supervisor.crash_loop",
                            consecutive=consecutive_failures, rc=rc)
            else:
                consecutive_failures = 0
            if attempt > max_restarts:
                log.error("supervisor: restart budget exhausted")
                bus.counter("supervisor.budget_exhausted", rc=rc)
                return rc
            # every restart waits at least `backoff_base`; consecutive
            # fast failures double it up to the cap
            delay = restart_backoff(max(1, consecutive_failures),
                                    backoff_base, backoff_cap)
            if delay > 0:
                log.info("supervisor: backing off %.1fs before restart",
                         delay)
                bus.gauge("supervisor.backoff_s", delay, attempt=attempt)
                time.sleep(delay)
            bus.counter("supervisor.restart", attempt=attempt)
    finally:
        if child is not None and child.poll() is None:
            log.warning("supervisor: exiting; killing the live child")
            _kill_group(child)
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
