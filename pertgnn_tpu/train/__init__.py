from pertgnn_tpu.train.metrics import quantile_loss, masked_metric_sums
from pertgnn_tpu.train.loop import (
    TrainState,
    create_train_state,
    make_train_step,
    make_eval_step,
    fit,
    evaluate,
)
