"""Loss and metrics — exact definitions from the reference.

- pinball/quantile loss `mean(max(tau*e, (tau-1)*e))`, e = y - y_hat
  (/root/reference/pert_gnn.py:191-193);
- MAE = sum |pred - y| / n, MAPE = sum |pred - y| / y / n, and the
  tau-quantile loss accumulated per sample then divided by the dataset size
  (pert_gnn.py:284-289) — here returned as masked SUMS plus a count so the
  caller can aggregate across fixed-shape batches (and devices) without
  padding bias. Note the reference's reported "train mae" is actually the
  mean quantile loss (pert_gnn.py:248); we report train qloss under its own
  name and compute real MAE everywhere.
"""

from __future__ import annotations

import jax.numpy as jnp


def quantile_loss_sums(y: jnp.ndarray, y_hat: jnp.ndarray, tau: float,
                       mask: jnp.ndarray
                       ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(masked pinball numerator, mask count) — the un-divided halves of
    :func:`quantile_loss`, so a sequential accumulator (parallel/scale.py
    SAR buckets) can sum partials across buckets and divide ONCE with the
    same elementwise ops the monolithic loss uses."""
    e = y - y_hat
    per = jnp.maximum(tau * e, (tau - 1) * e)
    w = mask.astype(per.dtype)
    return (per * w).sum(), w.sum()


def quantile_loss(y: jnp.ndarray, y_hat: jnp.ndarray, tau: float,
                  mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Masked mean pinball loss (pert_gnn.py:191-193)."""
    if mask is None:
        e = y - y_hat
        return jnp.maximum(tau * e, (tau - 1) * e).mean()
    num, cnt = quantile_loss_sums(y, y_hat, tau, mask)
    return num / jnp.maximum(cnt, 1.0)


def masked_metric_sums(y: jnp.ndarray, y_hat: jnp.ndarray, tau: float,
                       mask: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Per-batch metric SUMS over valid graphs (pert_gnn.py:284-289)."""
    w = mask.astype(jnp.float32)
    err = jnp.abs(y_hat - y) * w
    e = y - y_hat
    pin = jnp.maximum(tau * e, (tau - 1) * e) * w
    return {
        "mae_sum": err.sum(),
        "mape_sum": (err / jnp.where(y != 0, y, 1.0)).sum(),
        "qloss_sum": pin.sum(),
        "count": w.sum(),
    }
