"""graftvault scrub — verify every store checksum, quarantine bit-rot.

``python -m pertgnn_tpu.store.scrub`` (console script ``graftvault``)
walks the on-disk stores, re-verifies every manifest envelope and every
blob/array CRC32C recorded in it, and quarantines EXACTLY the corrupt
entry — the manifest plus its payload move to ``<root>/.quarantine/``
so the store's load path takes its existing single-entry rebuild route
(fresh compile / arena rebuild / one-shard re-ingest) on the next run,
while every healthy entry keeps warm-loading with zero rebuilds.
Whole-store invalidation is exactly what this tool exists to avoid.

Also swept (NOT corruption — the expected residue of a crashed
writer): stale ``.tmp.*`` files/dirs and generation dirs no manifest
references (a kill between the generation rename and the manifest
commit). A store with only orphans scrubs CLEAN.

Exit codes: 0 clean (orphans allowed), 1 corruption found (quarantined
unless ``--dry_run``), 2 usage error.

Telemetry: ``store.scrub.entries`` / ``store.scrub.corrupt`` /
``store.scrub.orphans`` counters and ``store.quarantined`` (tag
``store``), ``store.scrub.seconds`` histogram (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys
import time

from pertgnn_tpu.store import durable
from pertgnn_tpu.store.durable import (StoreCorruption, StoreLock,
                                       file_crc32c)

log = logging.getLogger(__name__)


def _bus(bus=None):
    if bus is not None:
        return bus
    from pertgnn_tpu import telemetry
    return telemetry.get_bus()


def _quarantine(root: str, paths: list[str], *, dry_run: bool) -> None:
    """Move an entry's files/dirs into <root>/.quarantine/ — evidence
    preserved, load path unblocked."""
    if dry_run:
        return
    qdir = os.path.join(root, ".quarantine")
    os.makedirs(qdir, exist_ok=True)
    stamp = int(time.time() * 1e3)
    for p in paths:
        if not os.path.exists(p):
            continue
        dest = os.path.join(qdir, f"{os.path.basename(p)}.{stamp}")
        try:
            os.replace(p, dest)  # graftlint: allow-durable-write
        except OSError as e:
            log.warning("scrub: could not quarantine %s (%s)", p, e)


def _sweep(paths: list[str], *, dry_run: bool) -> int:
    removed = 0
    for p in paths:
        removed += 1
        if dry_run:
            continue
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        else:
            try:
                os.unlink(p)
            except OSError:
                pass
    return removed


def scrub_aot(root: str, *, dry_run: bool = False, bus=None) -> dict:
    """The executable store: flat ``<name>/<key>.json`` manifests, each
    naming an immutable ``<key>@g<N>.bin`` blob with its CRC32C."""
    report = {"store": "aot", "root": root, "entries": 0,
              "corrupt": [], "orphans_removed": 0}
    if not os.path.isdir(root):
        return report
    with StoreLock(os.path.join(root, ".lock"), store="aot", bus=bus):
        for slot in sorted(os.listdir(root)):
            d = os.path.join(root, slot)
            if not os.path.isdir(d) or slot == ".quarantine":
                continue
            referenced: set[str] = set()
            orphans: list[str] = []
            for name in sorted(os.listdir(d)):
                path = os.path.join(d, name)
                if ".tmp." in name:
                    orphans.append(path)
                    continue
                if not name.endswith(".json"):
                    continue
                key = name[:-len(".json")]
                report["entries"] += 1
                entry = f"{slot}/{key}"
                blob = ""
                try:
                    body = durable.read_json(path, store="aot")
                    blob = str(body.get("blob", ""))
                    if not blob.startswith(f"{key}@g"):
                        blob = ""
                        raise StoreCorruption(
                            "manifest names a foreign blob",
                            store="aot", path=path, reason="bad_dir")
                    referenced.add(blob)
                    crc, nbytes = file_crc32c(os.path.join(d, blob))
                    if (crc != body.get("blob_crc32c")
                            or nbytes != body.get("blob_bytes")):
                        raise StoreCorruption(
                            f"blob CRC32C mismatch (recorded "
                            f"{body.get('blob_crc32c')!r}, computed "
                            f"{crc})", store="aot", path=path,
                            reason="crc_mismatch")
                except (StoreCorruption, OSError) as e:
                    report["corrupt"].append(
                        {"entry": entry,
                         "reason": getattr(e, "reason", "io_error"),
                         "detail": str(e)})
                    victims = [path]
                    if blob:
                        victims.append(os.path.join(d, blob))
                    _quarantine(root, victims, dry_run=dry_run)
            # blobs no manifest references: the crashed-writer residue
            for name in sorted(os.listdir(d)):
                if (name.endswith(".bin") and "@g" in name
                        and name not in referenced):
                    orphans.append(os.path.join(d, name))
            report["orphans_removed"] += _sweep(orphans, dry_run=dry_run)
    return report


def scrub_dir_store(root: str, store: str, *, dry_run: bool = False,
                    bus=None) -> dict:
    """Arena / delta stores: ``<key>.manifest.json`` pointing at an
    immutable ``<key>@g<N>`` dir whose per-file CRC32Cs it records."""
    report = {"store": store, "root": root, "entries": 0,
              "corrupt": [], "orphans_removed": 0}
    if not os.path.isdir(root):
        return report
    with StoreLock(os.path.join(root, ".lock"), store=store, bus=bus):
        referenced: set[str] = set()
        for key, mp in durable.iter_manifests(root):
            report["entries"] += 1
            gen_dir = None
            try:
                resolved = durable.resolve_entry(root, key, store=store)
                if resolved is None:
                    continue
                gen_dir, body = resolved
                referenced.add(os.path.basename(gen_dir))
                for filename, rec in sorted(
                        (body.get("files") or {}).items()):
                    crc, nbytes = file_crc32c(
                        os.path.join(gen_dir, filename))
                    if (crc != rec.get("crc32c")
                            or nbytes != rec.get("bytes")):
                        raise StoreCorruption(
                            f"{filename}: CRC32C mismatch (recorded "
                            f"{rec.get('crc32c')!r}, computed {crc})",
                            store=store, path=mp,
                            reason="crc_mismatch")
            except (StoreCorruption, OSError) as e:
                report["corrupt"].append(
                    {"entry": key,
                     "reason": getattr(e, "reason", "io_error"),
                     "detail": str(e)})
                victims = [mp] + ([gen_dir] if gen_dir else [])
                _quarantine(root, victims, dry_run=dry_run)
        orphans = []
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if name.startswith(".tmp."):
                orphans.append(path)
            elif ("@g" in name and os.path.isdir(path)
                    and name not in referenced):
                orphans.append(path)
        report["orphans_removed"] += _sweep(orphans, dry_run=dry_run)
    return report


def scrub_sidecar(checkpoint_dir: str, *, dry_run: bool = False,
                  bus=None) -> dict:
    """The train_config.json sidecar. A pre-graftvault plain-JSON
    sidecar is LEGACY, not corruption (load_config_dict still reads
    it); only a torn/tampered envelope quarantines."""
    report = {"store": "checkpoint", "root": checkpoint_dir,
              "entries": 0, "corrupt": [], "orphans_removed": 0,
              "legacy": 0}
    path = os.path.join(checkpoint_dir, "train_config.json")
    if not os.path.exists(path):
        return report
    report["entries"] = 1
    try:
        durable.read_json(path, store="checkpoint")
    except StoreCorruption as e:
        if e.reason == "not_envelope":
            try:
                with open(path) as f:
                    json.load(f)
                report["legacy"] = 1
                return report
            except (OSError, ValueError):
                pass
        report["corrupt"].append({"entry": "train_config.json",
                                  "reason": e.reason,
                                  "detail": str(e)})
        with StoreLock(os.path.join(checkpoint_dir, ".lock"),
                       store="checkpoint", bus=bus):
            _quarantine(checkpoint_dir, [path], dry_run=dry_run)
    return report


def scrub_journal(path: str, *, dry_run: bool = False,
                  bus=None) -> dict:
    """The capture journal: per-record CRC32C verification. A torn
    FINAL line is the expected signature of a kill mid-append (clean);
    an interior bad line or CRC mismatch is corruption — reported, not
    rewritten (the reader already skips it loudly; rewriting an
    append-only journal would forge history)."""
    report = {"store": "journal", "root": path, "entries": 0,
              "corrupt": [], "orphans_removed": 0, "torn_tail": 0}
    try:
        with open(path, "rb") as f:
            lines = f.read().splitlines()
    except OSError:
        return report
    from pertgnn_tpu.telemetry.capture import verify_record_crc
    from pertgnn_tpu.telemetry.schema import validate_event
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        report["entries"] += 1
        try:
            ev = validate_event(json.loads(line.decode("utf-8")))
            if not verify_record_crc(ev):
                raise ValueError("record CRC32C mismatch")
        except (ValueError, TypeError, UnicodeDecodeError) as e:
            if i == len(lines) - 1:
                report["torn_tail"] = 1
            else:
                report["corrupt"].append(
                    {"entry": f"line {i + 1}", "reason": "bad_record",
                     "detail": str(e)})
    return report


def _emit_telemetry(reports: list[dict], seconds: float, bus) -> None:
    b = _bus(bus)
    for r in reports:
        tag = r["store"]
        if r["entries"]:
            b.counter("store.scrub.entries", r["entries"], store=tag)
        if r["corrupt"]:
            b.counter("store.scrub.corrupt", len(r["corrupt"]),
                      store=tag)
            b.counter("store.quarantined", len(r["corrupt"]), store=tag)
        if r["orphans_removed"]:
            b.counter("store.scrub.orphans", r["orphans_removed"],
                      store=tag)
    b.histogram("store.scrub.seconds", seconds)


def scrub_all(*, aot_dir: str | None = None, arena_dir: str | None = None,
              delta_dir: str | None = None,
              checkpoint_dir: str | None = None,
              journal: str | None = None, dry_run: bool = False,
              bus=None) -> tuple[list[dict], int]:
    """Run every requested scrub; (reports, exit code)."""
    t0 = time.perf_counter()
    reports: list[dict] = []
    if aot_dir:
        reports.append(scrub_aot(aot_dir, dry_run=dry_run, bus=bus))
    if arena_dir:
        reports.append(scrub_dir_store(arena_dir, "arena",
                                       dry_run=dry_run, bus=bus))
    if delta_dir:
        reports.append(scrub_dir_store(delta_dir, "stream",
                                       dry_run=dry_run, bus=bus))
    if checkpoint_dir:
        reports.append(scrub_sidecar(checkpoint_dir, dry_run=dry_run,
                                     bus=bus))
    if journal:
        reports.append(scrub_journal(journal, dry_run=dry_run, bus=bus))
    _emit_telemetry(reports, time.perf_counter() - t0, bus)
    code = 1 if any(r["corrupt"] for r in reports) else 0
    return reports, code


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftvault scrub",
        description="Verify every store manifest/blob CRC32C; "
                    "quarantine exactly the corrupt entries.")
    p.add_argument("--aot_dir", default="",
                   help="executable store root (--compile_cache_dir)")
    p.add_argument("--arena_dir", default="",
                   help="arena store root (--arena_cache_dir)")
    p.add_argument("--delta_dir", default="",
                   help="delta arena store root (--delta_cache_dir)")
    p.add_argument("--checkpoint_dir", default="",
                   help="checkpoint dir (verifies the config sidecar)")
    p.add_argument("--journal", default="",
                   help="capture journal path (per-record CRC verify)")
    p.add_argument("--dry_run", action="store_true",
                   help="report only: quarantine and sweep nothing")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the report as JSON on stdout")
    args = p.parse_args(argv)
    if not any((args.aot_dir, args.arena_dir, args.delta_dir,
                args.checkpoint_dir, args.journal)):
        p.error("nothing to scrub: pass at least one store location")
    reports, code = scrub_all(
        aot_dir=args.aot_dir or None, arena_dir=args.arena_dir or None,
        delta_dir=args.delta_dir or None,
        checkpoint_dir=args.checkpoint_dir or None,
        journal=args.journal or None, dry_run=args.dry_run)
    if args.as_json:
        print(json.dumps({"reports": reports, "clean": code == 0},
                         indent=1, sort_keys=True))
    else:
        for r in reports:
            line = (f"{r['store']:<10} {r['root']}: "
                    f"{r['entries']} entries, "
                    f"{len(r['corrupt'])} corrupt, "
                    f"{r['orphans_removed']} orphans swept")
            if r.get("torn_tail"):
                line += ", torn tail (expected crash residue)"
            if r.get("legacy"):
                line += ", legacy (pre-graftvault) sidecar"
            print(line)
            for c in r["corrupt"]:
                verb = "would quarantine" if args.dry_run \
                    else "quarantined"
                print(f"  CORRUPT {c['entry']} ({c['reason']}): "
                      f"{c['detail']} — {verb}")
        print("scrub: " + ("CLEAN" if code == 0 else "CORRUPTION FOUND"))
    return code


if __name__ == "__main__":
    sys.exit(main())
