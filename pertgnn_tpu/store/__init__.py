"""graftvault — crash-consistent durable state for every on-disk store.

``store/durable.py`` is the ONE durable-write protocol (tmp →
fsync(file) → rename → fsync(dir), CRC32C-checksummed manifests,
advisory store locks, deterministic crash-injection hook sites);
``store/scrub.py`` is the ``graftvault scrub`` CLI that verifies every
manifest/blob checksum and quarantines exactly the corrupt entry.
"""

from pertgnn_tpu.store.durable import (EntryWriter, StoreCorruption,
                                       StoreLock, StoreLockTimeout,
                                       crc32c, durable_write, read_json,
                                       write_json)

__all__ = ["EntryWriter", "StoreCorruption", "StoreLock",
           "StoreLockTimeout", "crc32c", "durable_write", "read_json",
           "write_json"]
